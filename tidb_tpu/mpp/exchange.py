"""Device-side exchange primitives for the MPP shuffle join.

The partition/exchange shape follows TQP's relational-algebra-on-tensors
mapping (PAPERS.md): a hash shuffle is a static-shape bucket pack + one
`all_to_all` per column, and the local join is argsort + searchsorted —
all fixed-shape XLA ops, so the whole exchange compiles into the same
shard_map program as the scans feeding it.

Static capacities: each (source shard -> destination shard) bucket holds
at most `cap` rows.  Data-dependent overflow cannot resize a compiled
program, so it is *counted* on device and surfaced as a scalar the host
checks — the MeshAggOverflow contract (copr/parallel.py) applied to
exchanges; the caller then steps down the join-strategy ladder.

Backend notes (mirrors copr/parallel.py): no 64-bit bitcasts (the axon
TPU x64 rewriter cannot lower them), so the partition hash stays in
int64 value arithmetic (wrapping multiply + arithmetic-shift xor), and
all_to_all payloads keep their widened column dtypes.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from .. import ops  # noqa: F401  (configures x64)
import jax
import jax.numpy as jnp

# splitmix64's multiplicative constants, wrapped into int64 — spreads
# clustered keys (sequential order keys, FK ranges) across partitions so
# the static bucket capacity sees near-uniform load
_MIX = np.int64(np.uint64(0x9E3779B97F4A7C15).astype(np.int64))
_MIX2 = np.int64(np.uint64(0xBF58476D1CE4E5B9).astype(np.int64))

I64_MAX = np.iinfo(np.int64).max


def partition_ids(key, n_parts: int):
    """[0, n_parts) partition id per int64 key, identical on both join
    sides (the ExchangeSender hash of tipb.ExchangeType_Hash).

    Two mixing rounds (splitmix64's finalizer shape, value arithmetic
    only — no 64-bit bitcasts): the single-round mix left small
    sequential key domains (dimension-table primary keys) piled onto
    half the buckets, overflowing static capacities and demoting joins
    to the broadcast rung for no reason (ISSUE 12)."""
    h = key * _MIX
    h = h ^ (h >> 31)  # arithmetic shift: sign bits only perturb, not bias
    h = h * _MIX2
    h = h ^ (h >> 29)
    return jnp.mod(h, n_parts)


def pack_buckets(pid, pack_mask, n_parts: int, cap: int,
                 arrays: Sequence) -> Tuple[List, object, object]:
    """Scatter local rows into [n_parts, cap] destination buckets.

    One argsort on partition id groups each destination's rows
    contiguously; bucket d then gathers rows [offset_d, offset_d+cap).
    Returns (bucketed arrays, bucket validity [n_parts, cap], overflow =
    max rows any bucket wanted minus cap, clamped at 0).  Rows beyond a
    bucket's capacity are DROPPED on device — the overflow scalar is how
    the host learns the result is incomplete and must fall back.
    """
    n = pid.shape[0]
    # unselected rows sort last (pid n_parts), never land in a bucket
    skey = jnp.where(pack_mask, pid, n_parts)
    order = jnp.argsort(skey)
    ssorted = skey[order]
    offsets = jnp.searchsorted(ssorted, jnp.arange(n_parts + 1))
    counts = offsets[1:] - offsets[:-1]
    overflow = jnp.maximum(counts.max() - cap, 0)
    slot = jnp.arange(cap)
    idx = offsets[:-1][:, None] + slot[None, :]          # [n_parts, cap]
    bucket_valid = slot[None, :] < counts[:, None]
    rows = order[jnp.clip(idx, 0, n - 1)]
    out = [a[rows] for a in arrays]
    return out, bucket_valid, overflow


def exchange(bucketed, axis_name: str = "dp"):
    """all_to_all one [S, cap] bucketed array: row d of the input is this
    shard's partition destined for shard d; row j of the output is the
    partition shard j sent here.  Flattened to [S*cap] local rows."""
    out = jax.lax.all_to_all(bucketed, axis_name, split_axis=0,
                             concat_axis=0, tiled=True)
    return out.reshape(-1)


def replicate(local, axis_name: str = "dp"):
    """all_gather a per-shard array to every shard (the broadcast-join
    rung: the build side is replicated instead of partitioned)."""
    return jax.lax.all_gather(local, axis_name).reshape(-1)


def combine_keys(keys):
    """Fold multiple int64 join-key columns into ONE int64 sort/partition
    key (identity for a single column, so single-key joins keep exact
    equality).  Multi-column combination is a mix-hash: colliding unequal
    keys land in the same sorted span, so callers must re-verify TRUE
    per-column equality on candidate matches (expand_matches emits the
    candidates; the engine filters)."""
    h = keys[0]
    for k in keys[1:]:
        h = (h * _MIX) ^ k ^ ((h >> 29) & 0x7FFFFFFF)
    return h


def pack_keys_exact(keys, los, cards):
    """EXACT compound-key composition (ISSUE 11): stats-bounded key
    columns pack into ONE int64 by stride multiplication — equal packed
    keys iff every column is equal, so no collision re-verify is needed
    and dropping candidates is sound for LEFT-OUTER joins (the mix-hash
    cannot promise that).  Callers guarantee prod(cards) <= 2**62 and
    that `los`/`cards` cover BOTH sides' value ranges (the union of
    per-side column stats)."""
    h = jnp.zeros_like(keys[0])
    for k, lo, card in zip(keys, los, cards):
        h = h * card + jnp.clip(k - lo, 0, card - 1)
    return h


def compound_pack_spec(stat_pairs, max_bits: int = 62):
    """(los, cards) for pack_keys_exact from per-key ((lo,hi), (lo,hi))
    stat pairs (probe side, build side), or None when the packed space
    exceeds 2**max_bits — callers then keep the mix-hash ladder."""
    los, cards = [], []
    total = 1
    for (p_lo, p_hi), (b_lo, b_hi) in stat_pairs:
        lo = min(p_lo, b_lo)
        hi = max(p_hi, b_hi)
        if hi < lo:
            lo, hi = 0, 0
        card = hi - lo + 1
        total *= card
        if total > (1 << max_bits):
            return None
        los.append(int(lo))
        cards.append(int(card))
    return los, cards


def sorted_build(keys, valid):
    """(sorted keys with invalid rows pushed to +inf, source order,
    valid count) — the device hash table: searchsorted probes against
    the sorted build keys (duplicates stay adjacent)."""
    sortk = jnp.where(valid, keys, I64_MAX)
    order = jnp.argsort(sortk)
    return sortk[order], order, valid.sum()


def expand_matches(sbk, bord, nb, probe_keys, probe_emit, probe_match_ok,
                   cap_out: int, louter: bool):
    """Two-pass count+emit join expansion over NON-UNIQUE build keys.

    Pass 1 (count): each probe row's match span in the sorted build keys
    is [lo, hi) via two searchsorteds; cnt = hi - lo candidate matches.
    Pass 2 (emit): output slot t maps back to its source probe row via
    searchsorted on the exclusive prefix sums — every (probe row, match
    ordinal) pair lands in one of `cap_out` static output slots.

    Left-outer probe rows with no match still emit ONE row (`matched`
    False there — the engine NULL-extends the build columns).  Total
    emissions beyond cap_out are DROPPED on device; the returned
    overflow scalar is how the host learns the result is incomplete.

    Returns (src, bidx, out_valid, matched, overflow): per-slot source
    probe row, matched build source row, slot-live mask, true-match-span
    mask, and the clamped overflow count.
    """
    n = probe_keys.shape[0]
    lo = jnp.searchsorted(sbk, probe_keys, side="left")
    hi = jnp.minimum(jnp.searchsorted(sbk, probe_keys, side="right"), nb)
    cnt = jnp.where(probe_match_ok, jnp.maximum(hi - lo, 0), 0)
    emit_cnt = (jnp.where(probe_emit, jnp.maximum(cnt, 1), 0)
                if louter else cnt)
    total = emit_cnt.sum().astype(jnp.int64)
    overflow = jnp.maximum(total - cap_out, 0)
    starts = jnp.cumsum(emit_cnt) - emit_cnt
    t = jnp.arange(cap_out, dtype=starts.dtype)
    src = jnp.clip(jnp.searchsorted(starts, t, side="right") - 1, 0, n - 1)
    j = t - starts[src]
    matched = j < cnt[src]
    bpos = jnp.clip(lo[src] + j, 0, sbk.shape[0] - 1)
    out_valid = t < total
    return src, bord[bpos], out_valid, matched & out_valid, overflow


# ---------------------------------------------------------------------------
# kernelcheck registration: abstract-trace the exchange + partitioned join
# ---------------------------------------------------------------------------


def _canonical_join_fn(S: int, cap: int, n_local: int, mode: str):
    """The canonical partition -> exchange -> local-join program shape
    the lint kernelcheck traces (no tables, no engine state): one int64
    key + one f64 payload per side, inner-join semantics with the
    production two-pass count+emit expansion (non-unique build keys)."""
    cap_out = S * cap if mode == "shuffle" else n_local

    def shard_fn(pk, pm, bk, bm, pv):
        if mode == "shuffle":
            bpid = partition_ids(bk, S)
            (bkb, bvb), bval, b_over = pack_buckets(
                bpid, bm, S, cap, (bk, pv))
            rbk = exchange(bkb)
            rbv = exchange(bvb)
            b_ok = exchange(bval)
            ppid = partition_ids(pk, S)
            (pkb,), pval, p_over = pack_buckets(ppid, pm, S, cap, (pk,))
            rpk = exchange(pkb)
            p_ok = exchange(pval)
        else:  # broadcast
            rbk = replicate(jnp.where(bm, bk, I64_MAX))
            rbv = replicate(pv)
            b_ok = replicate(bm)
            rpk, p_ok = pk, pm
            b_over = p_over = jnp.int64(0)
        sbk, bord, nb = sorted_build(rbk, b_ok)
        src, bidx, out_valid, matched, j_over = expand_matches(
            sbk, bord, nb, rpk, p_ok, p_ok, cap_out, False)
        payload = jnp.where(matched, rbv[bidx], 0.0)
        overflow = jax.lax.psum(b_over + p_over, "dp")
        jover = jax.lax.psum(j_over, "dp")
        return overflow, jover, matched, payload

    return shard_fn


def trace_exchange_kernel(mode: str = "shuffle"):
    """make_jaxpr stats for the canonical exchange join over a 1-device
    mesh (deterministic across environments regardless of how many
    virtual devices the harness exposes); used by lint.kernelcheck."""
    try:
        from jax import shard_map
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    S, cap, n_local = 1, 64, 256
    mesh = Mesh(np.array(jax.devices()[:1]), ("dp",))
    fn = shard_map(
        _canonical_join_fn(S, cap, n_local, mode), mesh=mesh,
        in_specs=(P("dp"),) * 5,
        out_specs=(P(), P(), P("dp"), P("dp")),
    )
    args = (
        jnp.zeros(n_local, jnp.int64), jnp.ones(n_local, jnp.bool_),
        jnp.zeros(n_local, jnp.int64), jnp.ones(n_local, jnp.bool_),
        jnp.zeros(n_local, jnp.float64),
    )
    return jax.make_jaxpr(fn)(*args)


def _canonical_tree_fn(S: int, cap: int, n_local: int, cap_out: int):
    """The canonical 3-way rung-ladder program shape (ISSUE 12,
    mpp/jointree.py): rung 0 joins base(key a, payload) against side B
    (key a -> key b mapping), rung 1 joins the DEVICE-RESIDENT
    intermediate against side C (key b, measure) — both rungs inside
    ONE traced program so kernelcheck guards the whole ladder's int64
    census.  Operand SHIFTS (the caller adds a constant to every key
    column) must trace to the IDENTICAL jaxpr: key values are runtime
    data, never compiled constants."""

    def one_rung(pk, pm, slots, bk, bm, b_payload):
        bpid = partition_ids(bk, S)
        packed, bval, b_over = pack_buckets(
            bpid, bm, S, cap, (bk, b_payload))
        rbk = exchange(packed[0])
        rbv = exchange(packed[1])
        b_ok = exchange(bval)
        ppid = partition_ids(pk, S)
        parrs = [pk] + [a for pair in slots for a in pair]
        packed_p, pval, p_over = pack_buckets(ppid, pm, S, cap, parrs)
        recv = [exchange(a) for a in packed_p]
        p_ok = exchange(pval)
        sbk, bord, nb = sorted_build(rbk, b_ok)
        src, bidx, out_valid, matched, j_over = expand_matches(
            sbk, bord, nb, recv[0], p_ok, p_ok, cap_out, False)
        out_slots = [(recv[1 + 2 * i][src], recv[2 + 2 * i][src])
                     for i in range(len(slots))]
        out_slots.append((rbv[bidx], matched))
        keep = out_valid & matched
        over = jax.lax.psum(p_over + b_over, "dp")
        jover = jax.lax.psum(j_over, "dp")
        return out_slots, keep, over, jover

    def shard_fn(ak, av, bk_a, bk_b, bm, ck, cv, cm):
        # rung 0: base(a_key, a_payload) ⋈ B(a_key -> b_key)
        slots0, keep0, ov0, jo0 = one_rung(
            ak, jnp.ones_like(ak, dtype=jnp.bool_),
            [(av, jnp.ones_like(ak, dtype=jnp.bool_))], bk_a, bm, bk_b)
        # rung 1: intermediate(b_key) ⋈ C(b_key, measure) — the
        # intermediate arrays feed straight in, no host boundary
        bkey = slots0[1][0].astype(jnp.int64)
        slots1, keep1, ov1, jo1 = one_rung(
            bkey, keep0 & slots0[1][1], slots0, ck, cm, cv)
        payload = jnp.where(keep1, slots1[0][0], 0.0)
        measure = jnp.where(keep1, slots1[-1][0], 0.0)
        total = jax.lax.psum((payload * measure).sum(), "dp")
        return ov0 + ov1, jo0 + jo1, keep1, total

    return shard_fn


#: canonical tree-kernel shape (S, cap, n_local, cap_out) — one source
#: for the shard_map builder AND the numpy oracle's input size, so a
#: retune can never make executed-parity compare different row counts
_TREE_KERNEL_SHAPE = (1, 256, 64, 1024)


def _tree_kernel_fn():
    """The canonical 3-way ladder wrapped in its 1-device shard_map —
    shared by trace_tree_join_kernel and run_tree_join_kernel so the
    traced jaxpr and the executed result can never diverge on mesh or
    spec constants.  Returns (fn, n_local)."""
    try:
        from jax import shard_map
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    S, cap, n_local, cap_out = _TREE_KERNEL_SHAPE
    mesh = Mesh(np.array(jax.devices()[:1]), ("dp",))
    fn = shard_map(
        _canonical_tree_fn(S, cap, n_local, cap_out), mesh=mesh,
        in_specs=(P("dp"),) * 8,
        out_specs=(P(), P(), P("dp"), P()),
    )
    return fn, n_local


def trace_tree_join_kernel(shift: int = 0):
    """make_jaxpr stats for the canonical 3-way ladder over a 1-device
    mesh; `shift` offsets every key operand — lint.kernelcheck traces
    two shifts and requires identical jaxprs (key VALUES must never
    shape the compiled ladder)."""
    fn, n_local = _tree_kernel_fn()
    args = _tree_kernel_args(n_local, shift)
    return jax.make_jaxpr(fn)(*args)


def _tree_kernel_args(n_local: int, shift: int = 0):
    rng = np.random.default_rng(5)
    ak = rng.integers(0, 16, n_local).astype(np.int64) + shift
    av = rng.uniform(0, 1, n_local)
    bk_a = rng.integers(0, 16, n_local).astype(np.int64) + shift
    bk_b = rng.integers(0, 8, n_local).astype(np.int64) + shift
    bm = rng.random(n_local) < 0.5
    ck = rng.integers(0, 8, n_local).astype(np.int64) + shift
    cv = rng.uniform(0, 1, n_local)
    cm = rng.random(n_local) < 0.8
    # host numpy: trace/run callers device_put, the oracle reads direct
    return (ak, av, bk_a, bk_b, bm, ck, cv, cm)


def run_tree_join_kernel(shift: int = 0):
    """Execute the canonical ladder concretely (1 device) and return the
    scalar result — kernelcheck compares it against the numpy oracle
    (`tree_join_oracle`) for executed parity."""
    fn, n_local = _tree_kernel_fn()
    over, jover, _keep, total = fn(*_tree_kernel_args(n_local, shift))
    return int(over), int(jover), float(total)


def tree_join_oracle(shift: int = 0) -> float:
    """Numpy reference for run_tree_join_kernel: the same 3-way join
    evaluated row-at-a-time on the host."""
    n_local = _TREE_KERNEL_SHAPE[2]
    ak, av, bk_a, bk_b, bm, ck, cv, cm = _tree_kernel_args(n_local, shift)
    total = 0.0
    for i in range(n_local):
        for j in range(n_local):
            if not bm[j] or bk_a[j] != ak[i]:
                continue
            for k in range(n_local):
                if cm[k] and ck[k] == np.int64(bk_b[j]):
                    total += av[i] * cv[k]
    return float(total)


def _canonical_grouped_fn(S: int, cap_out: int, cap_g: int):
    """Canonical grouped-partial + on-device-merge program: one int64
    group key + one f64 measure over cap_out joined rows — per-shard
    sort-group into cap_g slots, all_gather of the compacted
    (key, state) rows, second sort-merge, per-shard slice emission.
    The group BUDGET is the runtime scalar argument: kernelcheck
    asserts the traced jaxpr is IDENTICAL across budget values."""
    from ..copr.fusion import (grouped_partial_states,
                               merge_grouped_partials,
                               sort_group_segments)
    from ..expr.aggregation import AggDesc
    from ..types import FieldType, TypeKind

    f64 = FieldType(TypeKind.FLOAT)
    aggs = [AggDesc("count", [], False, FieldType(TypeKind.INT)),
            AggDesc("sum", [_CanonArg(f64)], False, f64)]
    gchunk = cap_g // S

    def shard_fn(gk, gv, meas, mm, gbudget):
        key_bits = [jnp.where(gv, gk, 0)]
        key_flags = [gv.astype(jnp.int64)]
        order, sm, skeys, seg, pos, n_uniq = sort_group_segments(
            key_bits, key_flags, mm, cap_g)
        states = grouped_partial_states(
            aggs, lambda e: (meas, mm), order, sm, seg, cap_g)
        out_keys = [k[pos] for k in skeys]
        over_l = jax.lax.psum(jnp.maximum(n_uniq - gbudget, 0), "dp")
        slot_ok = jnp.arange(cap_g, dtype=jnp.int64) \
            < jnp.minimum(n_uniq, cap_g)
        g_keys = [replicate(k) for k in out_keys]
        g_ok = replicate(slot_ok)
        g_states = jax.tree_util.tree_map(replicate, states)
        mn_uniq, m_keys, m_states = merge_grouped_partials(
            aggs, g_keys[:1], g_keys[1:], g_ok, g_states, cap_g)
        over_m = jnp.maximum(mn_uniq - gbudget, 0)
        shard = jax.lax.axis_index("dp")

        def slc(y):
            return jax.lax.dynamic_slice(y, (shard * gchunk,), (gchunk,))

        return (over_l, over_m.reshape(1), mn_uniq.reshape(1),
                tuple(slc(k) for k in m_keys),
                tuple(jax.tree_util.tree_map(slc, m_states)))

    return shard_fn


class _CanonArg:
    """Minimal expression stand-in for the canonical grouped kernel:
    grouped_partial_states only reads `.args[0].ftype` and calls the
    arg_fn closure, which ignores the expression object."""

    def __init__(self, ftype):
        self.ftype = ftype


def trace_grouped_agg_kernel(budget: int = 7):
    """make_jaxpr stats for the canonical grouped-partial + merge
    program over a 1-device mesh; `budget` rides the runtime scalar
    slot — lint.kernelcheck traces two budgets and requires identical
    jaxprs (the budget must never become a compiled constant)."""
    try:
        from jax import shard_map
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    S, cap_out, cap_g = 1, 256, 32
    mesh = Mesh(np.array(jax.devices()[:1]), ("dp",))
    fn = shard_map(
        _canonical_grouped_fn(S, cap_out, cap_g), mesh=mesh,
        in_specs=(P("dp"), P("dp"), P("dp"), P("dp"), P()),
        out_specs=(P(), P("dp"), P("dp"), (P("dp"),) * 2,
                   (P("dp"), (P("dp"), P("dp")))),
    )
    args = (
        jnp.zeros(cap_out, jnp.int64), jnp.ones(cap_out, jnp.bool_),
        jnp.zeros(cap_out, jnp.float64), jnp.ones(cap_out, jnp.bool_),
        jnp.int64(budget),
    )
    return jax.make_jaxpr(fn)(*args)
