"""Multi-way device-resident join pipelines: the rung-ladder engine.

ISSUE 12's tentpole, the execution half.  The planner's join-tree
compiler (planner/jointree.py) orders an n-way equi-join graph and emits
an `MPPJoinTreeSpec`: a base side plus a ladder of RUNGS, each joining
the current intermediate result against one more scan side.  This
module runs that ladder on the mesh:

- every rung is ONE shard_map program (partition/exchange the
  intermediate by the rung's key, filter+partition the build side,
  two-pass count+emit local join — the PR 8 exchange/local-join
  emitters, verbatim);
- the intermediate result BETWEEN rungs is a set of sharded device
  arrays (one (data, validity) pair per joined column plus a live-row
  mask): it never leaves HBM, so a k-way join is k dispatches with ZERO
  host transfers between them (trace-asserted: no `copr.transfer`
  spans between `mpp.rung` spans on a warm cache);
- semi / anti-semi rungs (decorrelated EXISTS/IN subqueries) filter the
  intermediate in place — a single searchsorted span-count when the key
  is single-column and unconditioned, the full pair expansion when
  correlated other-conds must evaluate per candidate pair;
- the final phase either reads the joined rows back, or runs the
  scalar/grouped partial aggregation ON DEVICE (the PR 8 sort-group +
  cross-shard merge emitters) so only O(G) rows leave.

Per-rung overflow steps down the existing ladder: a blown exchange
bucket or emission buffer retries THAT RUNG on the broadcast strategy
(build side replicated, intermediate stays local); a second overflow —
or any structural ineligibility — raises MPPIneligible and the caller
(MPPTreeReaderExec, mpp/reader.py) runs the same ladder as chained host
hash joins.  Grouped-aggregation budget overflow peels the agg to a
host tail over the still-device-resident joined rows, exactly like the
two-table engine's agg-peel rung.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np

from .. import ops  # noqa: F401  (configures x64)
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

try:  # jax >= 0.4.35 stable API
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

from ..chunk import Chunk, Column
from ..copr.device_health import classify_failure
from ..copr.jax_engine import (_fingerprint, _reindex_expr, _to_state_dtype,
                               rewrite_for_dict_resolved)
from ..copr.jax_eval import JaxUnsupported, compile_expr
from ..coord import CoordEpochMismatch
from ..copr.parallel import (
    DISPATCH_LOCK,
    MAX_MESH_ATTEMPTS,
    MESH_RANGE_SLOTS,
    _bounds_args,
    _check_membership_epoch,
    _handle_mesh_failure,
    _no_eligible_devices,
    _packed_jit,
    get_mesh,
)
from ..copr.ir import deserialize_expr, serialize_expr
from ..metrics import REGISTRY
from ..store.fault import FAILPOINTS
from ..types import TypeKind
from . import exchange as ex
from .engine import (
    _COMPILED,
    MPPGroupedAggOverflow,
    MPPIneligible,
    MPPJoinSide,
    OUT_CHUNK_ROWS,
    _pow2ceil,
    _shard_side,
    _SideState,
    _slack,
    grouped_pushdown_enabled,
)

#: chaos site: fires before each rung's exchange program (armed actions
#: inject device failures / overflow mid-ladder)
TREE_FAILPOINT = "mpp/tree_rung"


class MPPTreeOverflow(Exception):
    """One rung's exchange bucket or emission buffer blew its static
    capacity; carries the rung index and which capacity blew so the
    ladder can step down THAT rung (partition overflow -> broadcast,
    emission overflow -> boosted buffer)."""

    def __init__(self, rung: int, what: str, msg: str):
        super().__init__(msg)
        self.rung = rung
        self.what = what  # "partition" | "emit"


#: emission-buffer boost ceiling: a rung's cap_out may grow this many
#: times (×4 per overflow) before the ladder gives up on the device
MAX_EMIT_BOOST = 64


@dataclass
class TreeRung:
    """One ladder step: join the current intermediate against a side."""

    side: int                 # ordinal into MPPJoinTreeSpec.sides
    kind: str                 # inner | left_outer | semi | anti_semi
    left_slots: List[int]     # intermediate slot indices of the join keys
    build_key_pos: List[int]  # scan positions of the build-side keys
    # extra join conditions over the PAIR layout [slots..., build cols at
    # n_slots+j]; evaluated per candidate pair on device
    other_conds: List = field(default_factory=list)
    est_rows: float = 0.0     # planner estimate (EXPLAIN + budget sizing)


@dataclass
class MPPJoinTreeSpec:
    sides: List[MPPJoinSide]       # join order; side 0 is the base
    rungs: List[TreeRung]          # rung k joins sides[rungs[k].side]
    # final intermediate layout: per slot the (side ordinal, scan pos)
    # that produced it — slots appear in join order, semi/anti sides
    # contribute none
    slot_src: List[Tuple[int, int]]
    out_slots: List[int]           # rows mode: slots in output order
    out_ftypes: list               # ftypes aligned with out_slots
    ts: int = 0
    # final partial aggregation over the slot layout (positions = slots)
    aggs: Optional[list] = None
    group_by: Optional[list] = None
    group_budget: int = 0


# ---------------------------------------------------------------------------
# slot bookkeeping
# ---------------------------------------------------------------------------


def _slots_of_prefix(spec: MPPJoinTreeSpec, upto_rung: int) -> int:
    """Slot count available BEFORE rung `upto_rung` runs."""
    n = len(spec.sides[0].out_ftypes)
    for r in range(upto_rung):
        rung = spec.rungs[r]
        if rung.kind in ("inner", "left_outer"):
            n += len(spec.sides[rung.side].out_ftypes)
    return n


def _slot_resolver(spec: MPPJoinTreeSpec, states, n_slots: int,
                   build_state=None):
    """Pair-layout column resolver for rewrite_for_dict_resolved: slots
    resolve through slot_src to their owning side's (table, scan); the
    tail past n_slots is the active rung's build side."""

    def resolve(idx: int):
        if 0 <= idx < n_slots:
            side, sp = spec.slot_src[idx]
            st = states[side]
            return st.table, st.an.scan, sp
        if build_state is not None:
            sp = idx - n_slots
            if 0 <= sp < len(build_state.an.scan.columns):
                return build_state.table, build_state.an.scan, sp
        return None

    return resolve


# ---------------------------------------------------------------------------
# per-rung program
# ---------------------------------------------------------------------------


def _build_rung_fn(spec: MPPJoinTreeSpec, r: int, states, mesh, mode: str,
                   n_in: int, cap_p: int, cap_b: int, cap_out: int,
                   conds_rw, elide_probe: bool = False):
    """One rung's shard_map program.  Inputs: the intermediate arrays
    (rung 0 builds them inline from side 0's scan) + the build side's
    cached scan columns.  Outputs: the NEXT intermediate (still sharded,
    still on device) + overflow scalars.

    `elide_probe` (ISSUE 18 jointree (e)): the caller proved the
    intermediate is ALREADY hash-partitioned by this rung's key slots
    (the previous shuffle rung used the same ones), so the probe side
    skips pack+all-to-all and only the build side exchanges."""
    rung = spec.rungs[r]
    S = len(mesh.devices.ravel())
    bs = states[rung.side]
    first = r == 0
    n_slots = _slots_of_prefix(spec, r)
    kind = rung.kind
    emits = kind in ("inner", "left_outer")
    louter = kind == "left_outer"
    b_order = list(bs.col_order)
    b_key_pos = list(rung.build_key_pos)
    left_slots = list(rung.left_slots)
    multi = len(left_slots) > 1
    b_prep = _shard_side(bs.an, b_order, bs.n_local, MESH_RANGE_SLOTS)
    p_prep = (_shard_side(states[0].an, states[0].col_order,
                          states[0].n_local, MESH_RANGE_SLOTS)
              if first else None)
    # the fast span-count path: single-column key (exact equality after
    # combine_keys' identity) and no pair-level conditions to evaluate —
    # semi/anti rungs then never touch the emission buffer at all
    fast_filter = (kind in ("semi", "anti_semi") and not multi
                   and not conds_rw)

    def shard_fn(*args):
        off = 0
        if first:
            st0 = states[0]
            n0 = 4
            p_datas, p_valids, p_del, p_bounds = args[:n0]
            off = n0
            cols0, m0 = p_prep(p_datas, p_valids, p_del, p_bounds)
            slots = [cols0[ci] for ci in st0.col_order]
            mask = m0
        else:
            slots = []
            for _s in range(n_slots):
                slots.append((args[off], args[off + 1]))
                off += 2
            mask = args[off]
            off += 1
        b_datas, b_valids, b_del, b_bounds = args[off:off + 4]

        # ---- probe (intermediate) side -------------------------------
        keys = [slots[s][0].astype(jnp.int64) for s in left_slots]
        kv = slots[left_slots[0]][1]
        for s in left_slots[1:]:
            kv = kv & slots[s][1]
        mix = ex.combine_keys(keys)
        jk = mix
        if kind in ("inner", "semi"):
            psel = mask & kv
        else:  # left_outer / anti_semi keep NULL-key rows (unmatched)
            psel = mask
        p_arrays = [jnp.where(kv, jk, 0), kv]
        for d, v in slots:
            p_arrays.append(d)
            p_arrays.append(v)
        if mode == "shuffle" and not elide_probe:
            ppid = ex.partition_ids(jnp.where(kv, mix, 0), S)
            bucketed, pval, p_over = ex.pack_buckets(
                ppid, psel, S, cap_p, p_arrays)
            recv = [ex.exchange(a) for a in bucketed]
            p_ok = ex.exchange(pval)
        else:  # broadcast rung, or residency-elided re-shuffle: the
            # intermediate stays local (for elision the build side
            # below still exchanges — equal keys already co-reside)
            recv = p_arrays
            p_ok = psel
            p_over = jnp.int64(0)
        rpk, rkv = recv[0], recv[1]
        n_recv = rpk.shape[0]

        # ---- build side ----------------------------------------------
        b_cols, bm = b_prep(b_datas, b_valids, b_del, b_bounds)
        bkeys = [b_cols[kp][0].astype(jnp.int64) for kp in b_key_pos]
        bmix = ex.combine_keys(bkeys)
        bk_v = b_cols[b_key_pos[0]][1]
        for kp in b_key_pos[1:]:
            bk_v = bk_v & b_cols[kp][1]
        bsel = bm & bk_v  # NULL build keys never match
        b_arrays = [bmix]
        for ci in b_order:
            d, v = b_cols[ci]
            b_arrays.append(d)
            b_arrays.append(v)
        if mode == "shuffle":
            bpid = ex.partition_ids(bmix, S)
            bucketed, bval, b_over = ex.pack_buckets(
                bpid, bsel, S, cap_b, b_arrays)
            recv_b = [ex.exchange(a) for a in bucketed]
            b_ok = ex.exchange(bval)
        else:
            recv_b = [ex.replicate(a) for a in b_arrays]
            b_ok = ex.replicate(bsel)
            b_over = jnp.int64(0)
        sbk, bord, nb = ex.sorted_build(recv_b[0], b_ok)
        overflow = jax.lax.psum(p_over + b_over, "dp")

        # ---- fast span-count semi/anti (no expansion) ----------------
        if fast_filter:
            lo = jnp.searchsorted(sbk, rpk, side="left")
            hi = jnp.minimum(jnp.searchsorted(sbk, rpk, side="right"), nb)
            matched = (p_ok & rkv) & (hi > lo)
            keep = p_ok & (matched if kind == "semi" else ~matched)
            out_slots = []
            for s in range(n_slots):
                out_slots.append(recv[2 + 2 * s])
                out_slots.append(recv[3 + 2 * s])
            return overflow, jnp.int64(0), tuple(out_slots), keep

        # ---- two-pass count+emit expansion ---------------------------
        src, bidx, out_valid, matched, j_over = ex.expand_matches(
            sbk, bord, nb, rpk, p_ok, rkv & p_ok, cap_out, louter)
        jover = jax.lax.psum(j_over, "dp")
        hit = matched
        if multi:
            # mix-hash candidates: re-verify TRUE per-column equality
            for s, kp in zip(left_slots, b_key_pos):
                jb = b_order.index(kp)
                hit = hit & (
                    recv[2 + 2 * s][src].astype(jnp.int64)
                    == recv_b[1 + 2 * jb][bidx].astype(jnp.int64))
        if conds_rw:
            env = {}
            for s in range(n_slots):
                env[s] = (recv[2 + 2 * s][src], recv[3 + 2 * s][src])
            for j, ci in enumerate(b_order):
                env[n_slots + ci] = (recv_b[1 + 2 * j][bidx],
                                     hit & recv_b[2 + 2 * j][bidx])
            for c in conds_rw:
                d, v = compile_expr(c, env, cap_out)
                hit = hit & v & (d != 0)

        if kind in ("semi", "anti_semi"):
            counts = jnp.zeros(n_recv, dtype=jnp.int32).at[src].add(
                (hit & out_valid).astype(jnp.int32))
            matched_any = counts > 0
            keep = p_ok & (matched_any if kind == "semi"
                           else ~matched_any)
            out_slots = []
            for s in range(n_slots):
                out_slots.append(recv[2 + 2 * s])
                out_slots.append(recv[3 + 2 * s])
            return overflow, jover, tuple(out_slots), keep

        # inner / left_outer emission: gather probe slots, append build
        out_slots = []
        for s in range(n_slots):
            out_slots.append(recv[2 + 2 * s][src])
            out_slots.append(recv[3 + 2 * s][src])
        for j, _ci in enumerate(b_order):
            out_slots.append(recv_b[1 + 2 * j][bidx])
            out_slots.append(hit & recv_b[2 + 2 * j][bidx])
        keep = out_valid if louter else out_valid & hit
        return overflow, jover, tuple(out_slots), keep

    n_out_slots = n_slots + (len(b_order) if emits else 0)
    out_specs = (P(), P(), tuple(P("dp") for _ in range(2 * n_out_slots)),
                 P("dp"))
    if first:
        in_specs = (P("dp"), P("dp"), P("dp"),
                    tuple(P() for _ in range(2 * MESH_RANGE_SLOTS)))
    else:
        in_specs = tuple(P("dp") for _ in range(2 * n_slots)) + (P("dp"),)
    full_in = tuple(in_specs) + (
        P("dp"), P("dp"), P("dp"),
        tuple(P() for _ in range(2 * MESH_RANGE_SLOTS)))
    fn = shard_map(shard_fn, mesh=mesh, in_specs=full_in,
                   out_specs=out_specs, check_rep=False)
    return jax.jit(fn)


# ---------------------------------------------------------------------------
# final phase: rows readback or partial aggregation
# ---------------------------------------------------------------------------


def _tree_key_remaps(spec: MPPJoinTreeSpec, states):
    """Per-group-key dict-code remaps over the SLOT layout: computed
    keys reading a string column resolve to their owning side's store
    and the remap builds there (the tree analog of engine._mpp_key_remaps)."""
    from ..copr import fusion
    from ..copr.jax_engine import _string_leaf
    from ..expr.expression import ColumnExpr

    if spec.aggs is None or spec.group_by is None:
        return None
    remaps = []
    for g in spec.group_by:
        if isinstance(g, ColumnExpr) or not (
                g.ftype.kind == TypeKind.STRING or _string_leaf(g)):
            remaps.append(None)
            continue
        refs: set = set()

        def walk(x):
            if isinstance(x, ColumnExpr):
                refs.add(x.index)
            for c in getattr(x, "args", ()) or ():
                walk(c)

        walk(g)
        srcs = {spec.slot_src[i] for i in refs}
        if len(srcs) != 1:
            raise MPPIneligible(
                f"computed group key spans join sides: {g}")
        side, sp = next(iter(srcs))
        st = states[side]
        slot = next(iter(refs))
        try:
            rm = fusion.build_key_remap(
                st.table, st.an.scan, _reindex_expr(g, lambda _i: sp))
        except JaxUnsupported as e:
            raise MPPIneligible(str(e))
        remaps.append(fusion.KeyRemap(slot, rm.mapping, rm.cap,
                                      rm.out_dict))
    return remaps if any(r is not None for r in remaps) else None


def _build_final_fn(spec: MPPJoinTreeSpec, states, mesh, n_in: int,
                    cap_g: int, aggs_rw, group_rw, remaps):
    """The final partial-aggregation program over the finished
    intermediate (scalar psum or grouped sort-group + on-device merge —
    the PR 8 emitters over the slot layout)."""
    from ..copr import fusion
    from ..copr.fusion import (grouped_partial_states,
                               merge_grouped_partials, sort_group_segments)
    from ..copr.parallel import _key_device

    S = len(mesh.devices.ravel())
    n_slots = len(spec.slot_src)
    grouped = group_rw is not None
    nk = len(group_rw) if grouped else 0
    gchunk = cap_g // S if grouped else 0

    def shard_fn(*args):
        slots = []
        off = 0
        for _s in range(n_slots):
            slots.append((args[off], args[off + 1]))
            off += 2
        mask = args[off]
        off += 1
        env = {i: slots[i] for i in range(n_slots)}
        if grouped:
            gbudget = args[off]
            off += 1
            rvals = args[off:]
            key_bits, key_flags = [], []
            rslot = 0
            for gi, g in enumerate(group_rw):
                rem = remaps[gi] if remaps is not None else None
                if rem is not None:
                    d0, v = env[rem.src_idx]
                    d = fusion.remap_codes(d0, rvals[rslot], n_in)
                    rslot += 1
                else:
                    d, v = compile_expr(g, env, n_in)
                k = _key_device(d)
                zero = (jnp.float64(0.0) if k.dtype == jnp.float64
                        else jnp.int64(0))
                key_bits.append(jnp.where(v, k, zero))
                key_flags.append(v.astype(jnp.int64))
            order, sm, skeys, seg, pos, n_uniq = sort_group_segments(
                key_bits, key_flags, mask, cap_g)
            states_ = grouped_partial_states(
                aggs_rw, lambda e: compile_expr(e, env, n_in),
                order, sm, seg, cap_g)
            out_keys = [k[pos] for k in skeys]
            over_l = jax.lax.psum(jnp.maximum(n_uniq - gbudget, 0), "dp")
            slot_ok = jnp.arange(cap_g, dtype=jnp.int64) \
                < jnp.minimum(n_uniq, cap_g)
            g_keys = [ex.replicate(k) for k in out_keys]
            g_ok = ex.replicate(slot_ok)
            g_states = jax.tree_util.tree_map(ex.replicate, states_)
            mn_uniq, m_keys, m_states = merge_grouped_partials(
                aggs_rw, g_keys[:nk], g_keys[nk:], g_ok, g_states, cap_g)
            over_m = jnp.maximum(mn_uniq - gbudget, 0)
            shard = jax.lax.axis_index("dp")

            def slc(y):
                return jax.lax.dynamic_slice(y, (shard * gchunk,),
                                             (gchunk,))

            return (over_l, over_m.reshape(1), mn_uniq.reshape(1),
                    tuple(slc(k) for k in m_keys),
                    tuple(jax.tree_util.tree_map(slc, m_states)))

        # scalar partial aggregation
        states_ = []
        for a in aggs_rw:
            if a.name == "count":
                if a.args:
                    d, v = compile_expr(a.args[0], env, n_in)
                    states_.append(jax.lax.psum(
                        (mask & v).sum().astype(jnp.int64), "dp"))
                else:
                    states_.append(jax.lax.psum(
                        mask.sum().astype(jnp.int64), "dp"))
                continue
            d, v = compile_expr(a.args[0], env, n_in)
            mv = mask & v
            if a.name in ("sum", "avg"):
                st = a.partial_types()[0]
                dd = _to_state_dtype(d, a.args[0].ftype, st)
                states_.append((
                    jax.lax.psum(jnp.where(mv, dd, 0).sum(), "dp"),
                    jax.lax.psum(mv.sum().astype(jnp.int64), "dp"),
                ))
            else:  # min / max: per-shard partial, host merges
                if a.name == "min":
                    sent = (jnp.inf if jnp.issubdtype(d.dtype, jnp.floating)
                            else ex.I64_MAX)
                    part = jnp.where(mv, d, sent).min()
                else:
                    sent = (-jnp.inf if jnp.issubdtype(d.dtype,
                                                       jnp.floating)
                            else -ex.I64_MAX - 1)
                    part = jnp.where(mv, d, sent).max()
                states_.append((
                    part.reshape(1),
                    jax.lax.psum(mv.sum().astype(jnp.int64), "dp"),
                ))
        return (tuple(states_),)

    if grouped:
        out_states = []
        for a in aggs_rw:
            if a.name == "count":
                out_states.append(P("dp"))
            else:
                out_states.append((P("dp"), P("dp")))
        out_specs = (P(), P("dp"), P("dp"),
                     tuple(P("dp") for _ in range(2 * nk)),
                     tuple(out_states))
    else:
        out_states = []
        for a in aggs_rw:
            if a.name == "count":
                out_states.append(P())
            elif a.name in ("sum", "avg"):
                out_states.append((P(), P()))
            else:
                out_states.append((P("dp"), P()))
        out_specs = (tuple(out_states),)
    in_specs = tuple(P("dp") for _ in range(2 * n_slots)) + (P("dp"),)
    if grouped:
        in_specs = in_specs + (P(),)
        in_specs = in_specs + tuple(
            P() for r in (remaps or ()) if r is not None)
    fn = shard_map(shard_fn, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, check_rep=False)
    return _packed_jit(fn)


# ---------------------------------------------------------------------------
# host-side assembly
# ---------------------------------------------------------------------------


def _decode_slot(spec, states, slot: int, ft, data: np.ndarray,
                 valid: np.ndarray) -> Column:
    if ft.kind == TypeKind.STRING:
        from ..store.blockstore import _decode_dict

        side, sp = spec.slot_src[slot]
        st = states[side]
        store_ci = st.an.scan.columns[sp]
        obj = _decode_dict(data.astype(np.int64),
                           st.table.cols[store_ci].dictionary)
        return Column(ft, obj, valid)
    return Column(ft, data.astype(ft.np_dtype), valid)


def _assemble_tree_rows(spec, states, mask, flats) -> List[Chunk]:
    from ..copr.jax_engine import _np_tree

    sel = np.flatnonzero(mask)
    cols = []
    for ft, slot in zip(spec.out_ftypes, spec.out_slots):
        d, v = _np_tree((flats[2 * slot], flats[2 * slot + 1]))
        cols.append(_decode_slot(spec, states, slot, ft, d[sel],
                                 v[sel].astype(np.bool_)))
    big = Chunk(cols)
    return [c for c in big.split(OUT_CHUNK_ROWS) if c.num_rows]


def _assemble_tree_grouped(spec, states, n_uniq, keys, sts,
                           remaps=None) -> List[Chunk]:
    nk = len(spec.group_by)
    k = int(n_uniq[0])
    cols: List[Column] = []
    for i, g in enumerate(spec.group_by):
        bits = keys[i][:k]
        flags = keys[nk + i][:k].astype(np.bool_)
        ft = g.ftype
        rem = remaps[i] if remaps is not None else None
        if rem is not None and rem.out_dict is not None:
            from ..store.blockstore import _decode_dict

            data = _decode_dict(bits.astype(np.int64), rem.out_dict)
        elif ft.kind == TypeKind.FLOAT:
            data = bits.astype(np.float64, copy=False)
        elif ft.kind == TypeKind.STRING:
            from ..store.blockstore import _decode_dict

            side, sp = spec.slot_src[g.index]
            st = states[side]
            store_ci = st.an.scan.columns[sp]
            data = _decode_dict(bits.astype(np.int64),
                                st.table.cols[store_ci].dictionary)
        else:
            data = bits.astype(ft.np_dtype)
        cols.append(Column(ft, data, flags if not flags.all() else None))
    for a, st in zip(spec.aggs, sts):
        pts = a.partial_types()
        if a.name == "count":
            cols.append(Column(pts[0], st[:k].astype(np.int64)))
        elif a.name in ("sum", "avg"):
            s, c = st[0][:k], st[1][:k]
            cols.append(Column(pts[0], s.astype(pts[0].np_dtype), c > 0))
            if a.name == "avg":
                cols.append(Column(pts[1], c.astype(np.int64)))
        else:
            v, c = st[0][:k], st[1][:k]
            cols.append(Column(pts[0], v.astype(pts[0].np_dtype), c > 0))
    chunk = Chunk(cols)
    return [chunk] if chunk.num_rows else []


def _assemble_tree_partials(spec, sts, S: int) -> List[Chunk]:
    cols: List[Column] = []
    for a, st in zip(spec.aggs, sts):
        pts = a.partial_types()
        if a.name == "count":
            cols.append(Column(pts[0], np.array([int(st)], np.int64)))
        elif a.name in ("sum", "avg"):
            sm, c = st
            c = int(c)
            cols.append(Column(pts[0],
                               np.array([sm]).astype(pts[0].np_dtype),
                               np.array([c > 0])))
            if a.name == "avg":
                cols.append(Column(pts[1], np.array([c], np.int64)))
        else:
            part, c = st
            c = int(c)
            v = part.min() if a.name == "min" else part.max()
            cols.append(Column(pts[0],
                               np.array([v]).astype(pts[0].np_dtype),
                               np.array([c > 0])))
    return [Chunk(cols)]


# ---------------------------------------------------------------------------
# entry
# ---------------------------------------------------------------------------


def _clone_expr(e):
    return deserialize_expr(serialize_expr(e))


def _run_tree_once(storage, spec: MPPJoinTreeSpec, modes: List[str],
                   boosts: List[int]) -> List[Chunk]:
    import os as _os
    import time as _time

    from ..copr.chunking import observe_chunk
    from ..lifecycle import dispatch_admission, scope_check
    from ..trace import annotate, span

    mesh = get_mesh()
    S = len(mesh.devices.ravel())
    mesh_ids = tuple(d.id for d in mesh.devices.ravel())
    states = [_SideState(storage, s, spec.ts, mesh) for s in spec.sides]
    for st in states:
        st.load(mesh)

    slack = _slack()
    join_slack = float(_os.environ.get("TIDB_TPU_MPP_JOIN_SLACK", "1.0"))
    grouped = spec.aggs is not None and spec.group_by is not None
    budget, cap_g = 0, 0
    if grouped:
        budget = (int(_os.environ.get("TIDB_TPU_MPP_GROUP_BUDGET", "0"))
                  or spec.group_budget or 4096)
        cap_g0 = _pow2ceil(budget)
        cap_g = S * (-(-cap_g0 // S))
    remaps = _tree_key_remaps(spec, states) if grouped else None

    # dict-rewrite the per-rung other conds and final agg exprs against
    # each column's OWNING side (string constants -> codes, LIKE /
    # computed predicates -> code sets); rewritten trees enter the
    # program fingerprints, so dictionary changes recompile correctly
    rung_conds = []
    for r, rung in enumerate(spec.rungs):
        if rung.kind == "left_outer" and rung.other_conds:
            # a probe row whose every candidate pair fails the ON conds
            # must still NULL-extend; the emission path cannot express
            # that — the planner pushes build-side-only ON conds into
            # the scan instead, anything else stays host
            raise MPPIneligible("left-outer rung with pair conditions")
        if rung.kind == "left_outer" and len(rung.left_slots) > 1:
            # defense in depth behind the planner gate: multi-key
            # louter candidates are mix-hash (collision-prone), and a
            # dropped collision pair would still emit a spurious
            # NULL-extended row (keep=out_valid)
            raise MPPIneligible("multi-key left-outer rung")
        n_slots = _slots_of_prefix(spec, r)
        resolver = _slot_resolver(spec, states, n_slots,
                                  states[rung.side])
        try:
            rung_conds.append([
                rewrite_for_dict_resolved(_clone_expr(c), resolver)
                for c in rung.other_conds])
        except JaxUnsupported as e:
            raise MPPIneligible(f"rung {r} condition: {e}")
    aggs_rw = group_rw = None
    if spec.aggs is not None:
        from ..expr.aggregation import AggDesc

        resolver = _slot_resolver(spec, states, len(spec.slot_src))
        try:
            aggs_rw = [AggDesc(a.name,
                               [rewrite_for_dict_resolved(_clone_expr(x),
                                                          resolver)
                                for x in a.args],
                               a.distinct, a.ftype)
                       for a in spec.aggs]
            if grouped:
                group_rw = [
                    g if (remaps is not None
                          and remaps[i] is not None) else
                    rewrite_for_dict_resolved(_clone_expr(g), resolver)
                    for i, g in enumerate(spec.group_by)]
        except JaxUnsupported as e:
            raise MPPIneligible(f"final agg: {e}")

    # ---- run the ladder ---------------------------------------------
    import json as _json

    inter = None     # flat (data, valid) arrays per slot
    mask = None
    n_in = states[0].n_local
    # key-slot tuples the intermediate is hash-partitioned by (empty
    # until the first shuffle rung: rung 0's input is range-partitioned)
    residency: set = set()
    base_fp = (f"mpptree|S={S} devs={mesh_ids}"
               f"|base:{_fingerprint(states[0].an, 'filter')}"
               f"|Tl={states[0].Tl}|wire={states[0].wire_sig}")
    for r, rung in enumerate(spec.rungs):
        bs = states[rung.side]
        mode = modes[r]
        # residency elision (ISSUE 18 jointree (e)): a shuffle rung
        # whose key slots match the partitioning the PREVIOUS shuffle
        # rung left behind skips the probe-side exchange entirely —
        # equal keys (and bucket-0 NULL keys) already co-reside, so
        # only the build side moves
        elide = (mode == "shuffle" and inter is not None
                 and tuple(rung.left_slots) in residency)
        cap_p = min(_pow2ceil(int(slack * n_in / S) + 1), max(n_in, 16))
        cap_b = min(_pow2ceil(int(slack * bs.n_local / S) + 1),
                    bs.n_local)
        n_recv = (S * cap_p if mode == "shuffle" and not elide
                  else n_in)
        # emission buffer sized by the planner's rung estimate (whole
        # result could land on ONE shard when the base side is a single
        # tile), then boosted ×4 per runtime overflow
        est_cap = _pow2ceil(int(2 * max(rung.est_rows, 1)))
        cap_out = max(int(join_slack * n_recv), est_cap, 16) * boosts[r]
        conds_sig = _json.dumps(
            [serialize_expr(c) for c in rung_conds[r]], sort_keys=True)
        fp = (base_fp
              + f"|r{r}|{mode}|{rung.kind}|n_in={n_in}"
              f"|caps={cap_p},{cap_b},{cap_out}"
              f"|lk={rung.left_slots}|el={int(elide)}"
              f"|b:{_fingerprint(bs.an, 'filter')}|Tl={bs.Tl}"
              f"|k={rung.build_key_pos}|wire={bs.wire_sig}"
              f"|oc={conds_sig}")
        fn = _COMPILED.get(fp)
        if fn is None:
            fn = _build_rung_fn(spec, r, states, mesh, mode, n_in,
                                cap_p, cap_b, cap_out, rung_conds[r],
                                elide_probe=elide)
            _COMPILED.put(fp, fn)
        FAILPOINTS.hit(TREE_FAILPOINT, rung=r, mode=mode,
                       kind=rung.kind, device_ids=mesh_ids)
        # the rung ladder IS the chunk sequence on the MPP path: each
        # rung re-checks scope and resource-group admission, so KILL of
        # a deep join tree lands between rungs (ISSUE 17)
        FAILPOINTS.hit("copr/chunk_dispatch", kind="mpp", chunk=r,
                       total=len(spec.rungs), start=0, end=0)
        if inter is None:
            args = (tuple(states[0].datas), tuple(states[0].valids),
                    states[0].del_mask, _bounds_args(states[0].bounds))
        else:
            args = tuple(inter) + (mask,)
        args = args + (tuple(bs.datas), tuple(bs.valids), bs.del_mask,
                       _bounds_args(bs.bounds))
        _check_membership_epoch()
        scope_check()
        t0 = _time.perf_counter()
        with span("mpp.rung", idx=r, rung=mode, kind=rung.kind,
                  elided=int(elide), build_table=bs.side.table_id):
            with dispatch_admission(DISPATCH_LOCK):
                overflow, jover, out_slots, keep = fn(*args)
            overflow, jover = int(overflow), int(jover)
        observe_chunk("mpp", (_time.perf_counter() - t0) * 1000.0,
                      OUT_CHUNK_ROWS)
        if overflow:
            raise MPPTreeOverflow(
                r, "partition",
                f"rung {r}: {overflow} rows over partition capacity "
                f"(cap_p={cap_p}, cap_b={cap_b}, mode={mode})")
        if jover:
            raise MPPTreeOverflow(
                r, "emit",
                f"rung {r}: {jover} joined rows over the emission "
                f"buffer (cap_out={cap_out}, mode={mode})")
        inter = list(out_slots)
        mask = keep
        n_in = (n_recv if rung.kind in ("semi", "anti_semi")
                else cap_out)
        REGISTRY.inc("mpp_tree_rungs_total")
        if elide:
            REGISTRY.inc("mpp_tree_reshuffle_elided_total")
        if mode == "shuffle":
            # rows now co-reside hashed by this rung's key; for inner
            # rungs the appended build key columns carry the SAME
            # values (the planner canonicalizes later rungs onto any
            # member of the equality class), so they name the layout
            # too.  NOT valid for left_outer — unmatched rows carry
            # NULL build keys that a real shuffle would send to bucket
            # 0.  Broadcast rungs never move the probe side, so any
            # earlier residency still holds.
            residency = {tuple(rung.left_slots)}
            if rung.kind == "inner":
                base = _slots_of_prefix(spec, r)
                order = list(bs.col_order)
                residency.add(tuple(base + order.index(kp)
                                    for kp in rung.build_key_pos))

    from ..copr.device_health import DEVICE_HEALTH

    DEVICE_HEALTH.record_success(mesh_ids)

    # ---- final phase -------------------------------------------------
    if spec.aggs is None:
        from ..copr.jax_engine import _np_tree

        with span("mpp.tree.readback"):
            m = _np_tree(mask)
            return _assemble_tree_rows(spec, states, m, inter)
    fin_sig = _json.dumps(
        [[a.name] + [serialize_expr(x) for x in a.args]
         for a in aggs_rw]
        + ([serialize_expr(g) for g in group_rw] if grouped else []),
        sort_keys=True)
    fp = (base_fp + f"|final|n_in={n_in}|capg={cap_g}|agg={fin_sig}"
          + (f"|rcaps={[r.cap if r else None for r in remaps]}"
             if remaps else ""))
    fn = _COMPILED.get(fp)
    if fn is None:
        fn = _build_final_fn(spec, states, mesh, n_in, cap_g, aggs_rw,
                             group_rw, remaps)
        _COMPILED.put(fp, fn)
    args = tuple(inter) + (mask,)
    if grouped:
        args = args + (jnp.int64(budget),)
        for rm in (remaps or ()):
            if rm is not None:
                args = args + (jnp.asarray(rm.mapping),)
    _check_membership_epoch()
    scope_check()
    with span("mpp.tree.final", grouped=grouped):
        with dispatch_admission(DISPATCH_LOCK):
            out = fn(*args)
    if grouped:
        over_l, over_m = int(out[0]), int(np.max(out[1]))
        if over_l or over_m:
            raise MPPGroupedAggOverflow(
                f"tree: distinct groups over budget {budget} "
                f"(per-shard over {over_l}, merged over {over_m})")
        annotate(groups=int(out[2][0]), group_budget=budget)
        return _assemble_tree_grouped(spec, states, out[2], out[3],
                                      out[4], remaps=remaps)
    return _assemble_tree_partials(spec, out[0], S)


def run_mpp_jointree(storage,
                     spec: MPPJoinTreeSpec) -> Tuple[List[Chunk], str]:
    """Run the rung ladder over the mesh; (chunks, mode) on success,
    raises MPPIneligible when the host chain must serve it.  Per-rung
    overflow steps that rung down to broadcast; grouped-budget overflow
    peels the agg to a host tail over device-joined rows."""
    import dataclasses

    from ..trace import annotate, span

    modes = ["shuffle"] * len(spec.rungs)
    boosts = [1] * len(spec.rungs)
    attempts = 0
    peel = (spec.group_by is not None and spec.aggs is not None
            and not grouped_pushdown_enabled())
    while True:
        from ..lifecycle import current_scope

        FAILPOINTS.hit("exec/cancel", site="mpp", scope=current_scope())
        current_scope().check()
        if _no_eligible_devices():
            raise MPPIneligible("all device breakers open")
        run_spec = spec
        if peel:
            run_spec = dataclasses.replace(spec, aggs=None, group_by=None)
        try:
            with span("mpp.tree", rungs=len(spec.rungs),
                      grouped=bool(spec.group_by), peel=peel):
                chunks = _run_tree_once(storage, run_spec, modes, boosts)
            mode = "tree[" + ",".join(m[0] for m in modes) + "]"
            if peel:
                if spec.aggs is not None and spec.group_by is not None:
                    from .engine import _host_grouped_partials

                    with span("mpp.agg_peel", rung=mode):
                        chunks = _host_grouped_partials(spec, chunks)
                mode += "+agg-peel"
            elif spec.group_by is not None and spec.aggs is not None:
                mode += "+grouped"
            REGISTRY.inc("mpp_tree_joins_total")
            return chunks, mode
        except CoordEpochMismatch:
            attempts += 1
            if attempts >= MAX_MESH_ATTEMPTS:
                raise MPPIneligible(
                    "membership epoch flapping exhausted mesh attempts")
            continue
        except MPPGroupedAggOverflow as e:
            REGISTRY.inc("mpp_grouped_agg_overflow_total")
            REGISTRY.inc("mpp_grouped_agg_fallback_total")
            annotate(grouped_agg_overflow=str(e)[:120])
            peel = True
            continue
        except MPPTreeOverflow as e:
            if e.what == "emit":
                REGISTRY.inc("mpp_tree_emit_overflow_total")
                if boosts[e.rung] < MAX_EMIT_BOOST:
                    # genuine join fan-out: grow THIS rung's emission
                    # buffer and retry (duplicate keys expand the
                    # output past the received-row estimate)
                    boosts[e.rung] *= 4
                    continue
            if e.what == "partition":
                REGISTRY.inc("mpp_partition_overflow_total")
                if modes[e.rung] == "shuffle":
                    modes[e.rung] = "broadcast"  # immune to probe skew
                    continue
            raise MPPIneligible(f"tree rung overflow: {e}")
        except JaxUnsupported as e:
            # a rung/final program failed to compile (planner gates are
            # structural, not exhaustive): the host chain owns it
            raise MPPIneligible(str(e))
        except (MPPIneligible, KeyboardInterrupt, SystemExit,
                GeneratorExit):
            raise
        except BaseException as e:
            from ..errors import TiDBTPUError

            if isinstance(e, TiDBTPUError):
                raise
            if not _handle_mesh_failure(None, e, attempts):
                if classify_failure(e) is not None:
                    raise MPPIneligible(f"device failure: {e}")
                raise
            attempts += 1
