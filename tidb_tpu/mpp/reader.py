"""MPPReaderExec: the root executor driving the MPP exchange engine.

The role of executor/table_reader.go for MPP fragments: own the two cop
DAGs (probe + build), hand them to the device engine, and stream joined
chunks (or one scalar-partial chunk) to the parent.  When the engine
declines — ineligible shapes, partition overflow past the broadcast
rung, exhausted device retries — the SAME plan runs as a root
HashJoinExec over two TableReaderExecs, so the ladder always terminates
in a correct host join (EXPLAIN ANALYZE shows which rung served it).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..chunk import Chunk
from ..executor.base import ExecContext, Executor
from ..expr.expression import ColumnExpr
from ..metrics import REGISTRY
from .engine import MPPIneligible, MPPJoinSpec, run_mpp_join


class MPPReaderExec(Executor):
    def __init__(self, ctx: ExecContext, spec: MPPJoinSpec, ftypes,
                 plan_id: int = -1):
        super().__init__(ctx, ftypes, [], plan_id)
        self.spec = spec
        self._chunks: Optional[List[Chunk]] = None
        self._pos = 0
        self._fallback: Optional[Executor] = None

    def _open(self):
        self._chunks = None
        self._pos = 0
        self._fallback = None

    def _attribute(self, engine: str):
        if self.plan_id >= 0:
            self.ctx.op_stats(self.plan_id).engine = engine

    def _run(self):
        spec = self.spec
        spec.ts = self.ctx.snapshot_ts()
        if self.ctx.engine != "tpu":
            self._start_fallback("engine=cpu")
            return
        if spec.copartitions is not None:
            self._run_copartitioned()
            return
        try:
            self._chunks, mode = run_mpp_join(self.ctx.storage, spec)
            self._attribute(f"mpp-{mode}")
        except MPPIneligible as e:
            self._start_fallback(str(e))

    def _run_copartitioned(self):
        """Exchange elision: both sides hash-partitioned on the join
        key, so partition i joins ONLY partition i — one engine run per
        partition pair, no cross-partition exchange at all (TiFlash's
        same-zone optimization).  A pair the engine declines host-joins
        alone; pruned/empty pairs contribute nothing (inner join)."""
        import dataclasses

        from ..trace import span

        spec = self.spec
        REGISTRY.inc("mpp_exchange_elided_total")
        probe_rngs: dict = {}
        for kr in spec.probe.ranges:
            probe_rngs.setdefault(kr.table_id, []).append(kr)
        build_rngs: dict = {}
        for kr in spec.build.ranges:
            build_rngs.setdefault(kr.table_id, []).append(kr)
        chunks, modes = [], []
        for ppid, bpid in spec.copartitions:
            self.ctx.check_killed()  # seam between partition-pair runs
            pr = probe_rngs.get(ppid)
            br = build_rngs.get(bpid)
            if not pr or not br:
                continue  # partition pruned on one side: no matches
            pair = dataclasses.replace(
                spec, copartitions=None,
                probe=dataclasses.replace(spec.probe, table_id=ppid,
                                          ranges=pr),
                build=dataclasses.replace(spec.build, table_id=bpid,
                                          ranges=br))
            try:
                stores = [self.ctx.storage.table(pid)
                          for pid in (ppid, bpid)]
                if any(t.base_rows == 0 and not t.delta for t in stores):
                    continue  # empty partition pair
                with span("mpp.copart", probe=ppid, build=bpid):
                    out, mode = run_mpp_join(self.ctx.storage, pair)
                chunks.extend(out)
                modes.append(mode)
            except MPPIneligible as e:
                chunks.extend(self._host_join_pair(pair, str(e)))
                modes.append("host")
        self._chunks = chunks
        rungs = ",".join(sorted(set(modes))) if modes else "empty"
        self._attribute(f"mpp-elided[{rungs}]")

    # ---- host rung -----------------------------------------------------
    def _side_reader(self, side, probe_ir=None) -> Executor:
        from ..copr.ir import DAG
        from ..executor.readers import TableReaderExec

        dag = DAG.from_dict(side.dag)
        if probe_ir is not None:
            dag.executors.append(probe_ir)
        return TableReaderExec(self.ctx, dag, list(side.ranges),
                               dag.output_ftypes(), plan_id=-1)

    def _build_host_join(self, spec):
        """Root hash join over a spec's two cop DAGs (always correct:
        handles deltas, duplicates, overflow shapes).  Inner joins keep
        the MPP plan's selectivity win: the FIRST key's build-side
        distinct values ship to the probe scan as a runtime semi-join
        filter (JoinProbeIR — a superset filter under multi-column keys,
        the join re-checks full equality), so non-matching probe rows
        die in the coprocessor instead of streaming to the host join."""
        from ..copr.ir import JoinProbeIR
        from ..executor.join import HashJoinExec

        pks = [ColumnExpr(kp, spec.probe.out_ftypes[kp], "pk", -1)
               for kp in spec.probe.key_pos]
        bks = [ColumnExpr(kb, spec.build.out_ftypes[kb], "bk", -1)
               for kb in spec.build.key_pos]
        probe_ir = JoinProbeIR(pks[0], filter_id=0) \
            if spec.kind == "inner" else None
        probe = self._side_reader(spec.probe, probe_ir)
        build = self._side_reader(spec.build)
        return HashJoinExec(
            self.ctx, build, probe, spec.kind, bks, pks, [],
            probe_is_left=spec.probe_is_left, plan_id=-1,
            rf_reader=probe if probe_ir is not None else None,
            rf_key_idx=0, rf_filter_id=0)

    def _host_join_pair(self, pair, reason: str) -> List[Chunk]:
        """Host-join ONE co-partitioned pair to completion (collected:
        pairs are 1/N of the table by construction)."""
        REGISTRY.inc("mpp_fallback_total")
        from ..trace import span

        with span("mpp.host_join", reason=reason[:80]):
            join = self._build_host_join(pair)
            grouped = pair.aggs is not None and pair.group_by is not None
            folds = ([_AggFold(a) for a in pair.aggs]
                     if pair.aggs is not None and not grouped else None)
            out: List[Chunk] = []
            join.open()
            try:
                while True:
                    c = join.next()
                    if c is None:
                        break
                    if not c.num_rows:
                        continue
                    if grouped:
                        out.extend(_grouped_fold(pair, c))
                    elif folds is None:
                        out.append(c)
                    else:
                        for f in folds:
                            f.consume(c)
            finally:
                join.close()
            if folds is not None:
                out = [Chunk([col for f in folds for col in f.partials()])]
            return out

    def _start_fallback(self, reason: str):
        REGISTRY.inc("mpp_fallback_total")
        self._attribute(f"host-join [mpp rejected: {reason}]")
        spec = self.spec
        join = self._build_host_join(spec)
        if spec.aggs is None:
            self._fallback = join
            self._fallback.open()
            return
        # partial-agg pushdown plan: the parent is a FINAL HashAgg, so
        # the host rung must emit the same [keys..., states...] partial
        # layout.  Fold per chunk — an MPP-eligible join is big by
        # construction, so the joined rows must never materialize whole;
        # grouped plans emit per-chunk grouped partials (the final
        # HashAgg merges groups across chunks)
        grouped = spec.group_by is not None
        folds = [_AggFold(a) for a in spec.aggs] if not grouped else None
        chunks: List[Chunk] = []
        join.open()
        try:
            while True:
                c = join.next()
                if c is None:
                    break
                if not c.num_rows:
                    continue
                if grouped:
                    chunks.extend(_grouped_fold(spec, c))
                else:
                    for f in folds:
                        f.consume(c)
        finally:
            join.close()
        if grouped:
            self._chunks = chunks
        else:
            self._chunks = [
                Chunk([col for f in folds for col in f.partials()])]

    def _next(self) -> Optional[Chunk]:
        if self._fallback is not None:
            return self._fallback.next()
        if self._chunks is None:
            self._run()
            if self._fallback is not None:
                return self._fallback.next()
        if self._pos >= len(self._chunks):
            return None
        c = self._chunks[self._pos]
        self._pos += 1
        return c

    def _close(self):
        if self._fallback is not None:
            self._fallback.close()
            self._fallback = None


class MPPTreeReaderExec(Executor):
    """Root executor for the multi-way join-tree ladder (ISSUE 12): own
    every side's cop DAG, hand the rung ladder to the device engine
    (mpp/jointree.py), and stream joined rows or partial-agg chunks.
    When the engine declines, the SAME ladder runs as CHAINED host hash
    joins in the compiler's join order — correctness never depends on
    the mesh."""

    def __init__(self, ctx: ExecContext, spec, ftypes, plan_id: int = -1):
        super().__init__(ctx, ftypes, [], plan_id)
        self.spec = spec
        self._chunks: Optional[List[Chunk]] = None
        self._pos = 0

    def _open(self):
        self._chunks = None
        self._pos = 0

    def _attribute(self, engine: str):
        if self.plan_id >= 0:
            self.ctx.op_stats(self.plan_id).engine = engine

    def _slot_ftypes(self):
        spec = self.spec
        fts = []
        for side, sp in spec.slot_src:
            ft = spec.sides[side].out_ftypes[sp]
            fts.append(ft)
        return fts

    def _run(self):
        from .jointree import run_mpp_jointree

        spec = self.spec
        spec.ts = self.ctx.snapshot_ts()
        if self.ctx.engine != "tpu":
            self._run_host("engine=cpu")
            return
        from .engine import MPPIneligible

        try:
            self._chunks, mode = run_mpp_jointree(self.ctx.storage, spec)
            self._attribute(f"mpp-{mode}")
        except MPPIneligible as e:
            self._run_host(str(e))

    # ---- host rung: chained hash joins in the same join order --------
    def _side_reader(self, side) -> Executor:
        from ..copr.ir import DAG
        from ..executor.readers import TableReaderExec

        dag = DAG.from_dict(side.dag)
        return TableReaderExec(self.ctx, dag, list(side.ranges),
                               dag.output_ftypes(), plan_id=-1)

    def _build_host_chain(self) -> Executor:
        from ..executor.join import HashJoinExec

        spec = self.spec
        slot_fts = self._slot_ftypes()
        cur = self._side_reader(spec.sides[0])
        for rung in spec.rungs:
            side = spec.sides[rung.side]
            pkeys = [ColumnExpr(s, slot_fts[s], "pk", -1)
                     for s in rung.left_slots]
            bkeys = [ColumnExpr(kp, side.out_ftypes[kp], "bk", -1)
                     for kp in rung.build_key_pos]
            build = self._side_reader(side)
            cur = HashJoinExec(
                self.ctx, build, cur, rung.kind, bkeys, pkeys,
                list(rung.other_conds), probe_is_left=True, plan_id=-1)
        return cur

    def _run_host(self, reason: str):
        REGISTRY.inc("mpp_fallback_total")
        REGISTRY.inc("mpp_tree_fallback_total")
        self._attribute(f"host-tree [mpp rejected: {reason}]")
        from ..trace import span

        spec = self.spec
        grouped = spec.aggs is not None and spec.group_by is not None
        folds = ([_AggFold(a) for a in spec.aggs]
                 if spec.aggs is not None and not grouped else None)
        chunks: List[Chunk] = []
        join = self._build_host_chain()
        with span("mpp.host_join", reason=reason[:80]):
            join.open()
            try:
                while True:
                    c = join.next()
                    if c is None:
                        break
                    if not c.num_rows:
                        continue
                    if grouped:
                        chunks.extend(_grouped_fold(spec, c))
                    elif folds is not None:
                        for f in folds:
                            f.consume(c)
                    else:
                        chunks.append(self._project_rows(c))
            finally:
                join.close()
        if folds is not None:
            chunks = [Chunk([col for f in folds for col in f.partials()])]
        self._chunks = chunks

    def _project_rows(self, c: Chunk) -> Chunk:
        spec = self.spec
        if spec.out_slots == list(range(len(spec.slot_src))):
            return c
        return Chunk([c.columns[s] for s in spec.out_slots])

    def _next(self) -> Optional[Chunk]:
        if self._chunks is None:
            self._run()
        if self._pos >= len(self._chunks):
            return None
        c = self._chunks[self._pos]
        self._pos += 1
        return c


def _grouped_fold(spec, chunk: Chunk) -> List[Chunk]:
    """Host-rung grouped partials for one joined chunk (the shared
    copr recipe; the parent FINAL HashAgg merges across chunks)."""
    from ..copr.cpu_engine import grouped_partial_chunks

    return grouped_partial_chunks(spec.group_by, spec.aggs, [chunk])


class _AggFold:
    """Streaming scalar-partial accumulator for one AggDesc over joined
    chunks, emitting the device engine's partial layout
    (engine._assemble_partials) without materializing the join."""

    def __init__(self, a):
        self.a = a
        self.rows = 0      # count(*) input rows
        self.count = 0     # non-NULL arg rows
        self.sum = 0       # int or float, in the arg's physical domain
        self.minmax = None

    def consume(self, chunk: Chunk):
        a = self.a
        self.rows += chunk.num_rows
        if not a.args:
            return
        v = a.args[0].eval(chunk)
        data = v.data[v.validity()]
        c = len(data)
        self.count += c
        if not c:
            return
        if a.name in ("sum", "avg"):
            from ..types import TypeKind

            if a.partial_types()[0].kind == TypeKind.FLOAT:
                self.sum += float(data.astype(np.float64).sum())
            else:
                self.sum += int(data.astype(np.int64).sum())
        elif a.name in ("min", "max"):
            ext = data.min() if a.name == "min" else data.max()
            if self.minmax is None:
                self.minmax = ext
            else:
                self.minmax = (min(self.minmax, ext) if a.name == "min"
                               else max(self.minmax, ext))

    def partials(self) -> List:
        from ..chunk import Column
        from ..types import TypeKind

        a = self.a
        pts = a.partial_types()
        if a.name == "count":
            n = self.count if a.args else self.rows
            return [Column(pts[0], np.array([n], np.int64))]
        if a.name in ("sum", "avg"):
            st, arg_ft = pts[0], a.args[0].ftype
            sm = self.sum
            if self.count:
                if st.kind == TypeKind.FLOAT:
                    if arg_ft.kind == TypeKind.DECIMAL:
                        sm /= 10.0 ** arg_ft.scale
                else:
                    sm *= 10 ** (st.scale - arg_ft.scale)
            cols = [Column(pts[0], np.array([sm]).astype(st.np_dtype),
                           np.array([self.count > 0]))]
            if a.name == "avg":
                cols.append(Column(pts[1], np.array([self.count], np.int64)))
            return cols
        val = self.minmax if self.minmax is not None else 0
        return [Column(pts[0], np.array([val]).astype(pts[0].np_dtype),
                       np.array([self.count > 0]))]
