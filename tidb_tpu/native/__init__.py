"""Native host kernels (C++ via ctypes), with transparent Python fallback.

Build happens lazily on first import: g++ -O3 -shared into a cached .so next
to the source (keyed on source mtime).  Absence of a toolchain degrades to
the numpy fallbacks — behavior identical, just slower.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "hashkit.cpp")
_SO = os.path.join(_HERE, "_hashkit.so")

_lib = None
_lib_mu = threading.Lock()
_build_failed = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lib_mu:
        if _lib is not None or _build_failed:
            return _lib
        try:
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-o", _SO, _SRC],
                    check=True, capture_output=True, timeout=120,
                )
            lib = ctypes.CDLL(_SO)
            lib.ht64_new.restype = ctypes.c_void_p
            lib.ht64_new.argtypes = [ctypes.c_int64]
            lib.ht64_free.argtypes = [ctypes.c_void_p]
            lib.ht64_upsert.restype = ctypes.c_int64
            lib.ht64_upsert.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int64, ctypes.c_void_p,
            ]
            lib.ht64_lookup.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int64, ctypes.c_void_p,
            ]
            lib.encode_i64_memcomparable.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
            ]
            lib.decode_i64_memcomparable.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
            ]
            _lib = lib
        except Exception:
            _build_failed = True
    return _lib


def available() -> bool:
    return _load() is not None


class KeyTable:
    """Shared factorization table: build side upserts, probe side looks up.

    Native when possible; the numpy/dict fallback preserves semantics."""

    def __init__(self, expected: int = 1024):
        self._lib = _load()
        if self._lib is not None:
            self._h = self._lib.ht64_new(int(max(expected, 16)))
            if not self._h:
                self._lib = None
        if self._lib is None:
            self._py: dict = {}

    def __del__(self):
        if getattr(self, "_lib", None) is not None and self._h:
            self._lib.ht64_free(self._h)
            self._h = None

    def _bufs(self, keys: np.ndarray, valid: Optional[np.ndarray]):
        keys = np.ascontiguousarray(keys, dtype=np.int64)
        v = None
        if valid is not None:
            v = np.ascontiguousarray(valid, dtype=np.uint8)
        return keys, v

    def upsert(self, keys: np.ndarray,
               valid: Optional[np.ndarray] = None) -> np.ndarray:
        n = len(keys)
        codes = np.empty(n, dtype=np.int64)
        if self._lib is not None:
            keys, v = self._bufs(keys, valid)
            self._lib.ht64_upsert(
                self._h, keys.ctypes.data, 0 if v is None else v.ctypes.data,
                n, codes.ctypes.data,
            )
            return codes
        d = self._py
        for i in range(n):
            if valid is not None and not valid[i]:
                codes[i] = -1
                continue
            k = int(keys[i])
            c = d.get(k)
            if c is None:
                c = d[k] = len(d)
            codes[i] = c
        return codes

    def lookup(self, keys: np.ndarray,
               valid: Optional[np.ndarray] = None) -> np.ndarray:
        n = len(keys)
        codes = np.empty(n, dtype=np.int64)
        if self._lib is not None:
            keys, v = self._bufs(keys, valid)
            self._lib.ht64_lookup(
                self._h, keys.ctypes.data, 0 if v is None else v.ctypes.data,
                n, codes.ctypes.data,
            )
            return codes
        d = self._py
        for i in range(n):
            if valid is not None and not valid[i]:
                codes[i] = -1
            else:
                codes[i] = d.get(int(keys[i]), -1)
        return codes


def encode_i64_keys(arr: np.ndarray) -> bytes:
    """Order-preserving (memcomparable) encoding of an int64 array."""
    arr = np.ascontiguousarray(arr, dtype=np.int64)
    lib = _load()
    out = np.empty(len(arr) * 8, dtype=np.uint8)
    if lib is not None:
        lib.encode_i64_memcomparable(arr.ctypes.data, len(arr),
                                     out.ctypes.data)
        return out.tobytes()
    u = (arr.astype(np.uint64) ^ np.uint64(1 << 63))
    return u.byteswap().tobytes()


def decode_i64_keys(data: bytes) -> np.ndarray:
    n = len(data) // 8
    lib = _load()
    out = np.empty(n, dtype=np.int64)
    if lib is not None:
        buf = np.frombuffer(data, dtype=np.uint8)
        lib.decode_i64_memcomparable(buf.ctypes.data, n, out.ctypes.data)
        return out
    u = np.frombuffer(data, dtype=np.uint64).byteswap()
    return (u ^ np.uint64(1 << 63)).astype(np.int64)
