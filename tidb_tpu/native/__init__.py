"""Native host kernels (C++ via ctypes), with transparent Python fallback.

Build happens lazily on first import: g++ -O3 -shared into a cached .so next
to the source (keyed on source mtime).  Absence of a toolchain degrades to
the numpy fallbacks — behavior identical, just slower.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np
from ..util_concurrency import make_lock

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "hashkit.cpp")
_SO = os.path.join(_HERE, "_hashkit.so")

_lib = None
_lib_mu = make_lock("native:_lib_mu")
_build_failed = False


def _build_and_dlopen(src: str, so: str) -> ctypes.CDLL:
    """mtime-keyed lazy g++ build + dlopen (shared by all native kernels;
    callers hold _lib_mu and latch their own failure flag)."""
    if (not os.path.exists(so)
            or os.path.getmtime(so) < os.path.getmtime(src)):
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", so, src],
            check=True, capture_output=True, timeout=120,
        )
    return ctypes.CDLL(so)


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lib_mu:
        if _lib is not None or _build_failed:
            return _lib
        try:
            lib = _build_and_dlopen(_SRC, _SO)
            lib.ht64_new.restype = ctypes.c_void_p
            lib.ht64_new.argtypes = [ctypes.c_int64]
            lib.ht64_free.argtypes = [ctypes.c_void_p]
            lib.ht64_upsert.restype = ctypes.c_int64
            lib.ht64_upsert.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int64, ctypes.c_void_p,
            ]
            lib.ht64_lookup.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int64, ctypes.c_void_p,
            ]
            lib.encode_i64_memcomparable.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
            ]
            lib.decode_i64_memcomparable.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
            ]
            _lib = lib
        except Exception:
            _build_failed = True
    return _lib


def available() -> bool:
    return _load() is not None


class KeyTable:
    """Shared factorization table: build side upserts, probe side looks up.

    Native when possible; the numpy/dict fallback preserves semantics."""

    def __init__(self, expected: int = 1024):
        self._lib = _load()
        if self._lib is not None:
            self._h = self._lib.ht64_new(int(max(expected, 16)))
            if not self._h:
                self._lib = None
        if self._lib is None:
            self._py: dict = {}

    def __del__(self):
        if getattr(self, "_lib", None) is not None and self._h:
            self._lib.ht64_free(self._h)
            self._h = None

    def _bufs(self, keys: np.ndarray, valid: Optional[np.ndarray]):
        keys = np.ascontiguousarray(keys, dtype=np.int64)
        v = None
        if valid is not None:
            v = np.ascontiguousarray(valid, dtype=np.uint8)
        return keys, v

    def upsert(self, keys: np.ndarray,
               valid: Optional[np.ndarray] = None) -> np.ndarray:
        n = len(keys)
        codes = np.empty(n, dtype=np.int64)
        if self._lib is not None:
            keys, v = self._bufs(keys, valid)
            self._lib.ht64_upsert(
                self._h, keys.ctypes.data, 0 if v is None else v.ctypes.data,
                n, codes.ctypes.data,
            )
            return codes
        d = self._py
        for i in range(n):
            if valid is not None and not valid[i]:
                codes[i] = -1
                continue
            k = int(keys[i])
            c = d.get(k)
            if c is None:
                c = d[k] = len(d)
            codes[i] = c
        return codes

    def lookup(self, keys: np.ndarray,
               valid: Optional[np.ndarray] = None) -> np.ndarray:
        n = len(keys)
        codes = np.empty(n, dtype=np.int64)
        if self._lib is not None:
            keys, v = self._bufs(keys, valid)
            self._lib.ht64_lookup(
                self._h, keys.ctypes.data, 0 if v is None else v.ctypes.data,
                n, codes.ctypes.data,
            )
            return codes
        d = self._py
        for i in range(n):
            if valid is not None and not valid[i]:
                codes[i] = -1
            else:
                codes[i] = d.get(int(keys[i]), -1)
        return codes


def encode_i64_keys(arr: np.ndarray) -> bytes:
    """Order-preserving (memcomparable) encoding of an int64 array."""
    arr = np.ascontiguousarray(arr, dtype=np.int64)
    lib = _load()
    out = np.empty(len(arr) * 8, dtype=np.uint8)
    if lib is not None:
        lib.encode_i64_memcomparable(arr.ctypes.data, len(arr),
                                     out.ctypes.data)
        return out.tobytes()
    u = (arr.astype(np.uint64) ^ np.uint64(1 << 63))
    return u.byteswap().tobytes()


def decode_i64_keys(data: bytes) -> np.ndarray:
    n = len(data) // 8
    lib = _load()
    out = np.empty(n, dtype=np.int64)
    if lib is not None:
        buf = np.frombuffer(data, dtype=np.uint8)
        lib.decode_i64_memcomparable(buf.ctypes.data, n, out.ctypes.data)
        return out
    u = np.frombuffer(data, dtype=np.uint64).byteswap()
    return (u ^ np.uint64(1 << 63)).astype(np.int64)


# ---------------------------------------------------------------------------
# native CSV -> columnar parser (csvkit.cpp); LOAD DATA's bulk fast path
# ---------------------------------------------------------------------------

_CSV_SRC = os.path.join(_HERE, "csvkit.cpp")
_CSV_SO = os.path.join(_HERE, "_csvkit.so")
_csv_lib = None
_csv_failed = False


def _load_csv() -> Optional[ctypes.CDLL]:
    global _csv_lib, _csv_failed
    if _csv_lib is not None or _csv_failed:
        return _csv_lib
    with _lib_mu:
        if _csv_lib is not None or _csv_failed:
            return _csv_lib
        try:
            lib = _build_and_dlopen(_CSV_SRC, _CSV_SO)
            lib.csv_parse.restype = ctypes.c_int64
            lib.csv_parse.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.c_char,
                ctypes.c_int32, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,
            ]
            _csv_lib = lib
        except Exception:
            _csv_failed = True
    return _csv_lib


# FieldType.kind -> csvkit kind code (None = unsupported, take Python path)
_CSV_KINDS = {
    "INT": 0, "UINT": 0, "BOOL": 0, "FLOAT": 1, "STRING": 2,
    "DATE": 3, "DATETIME": 4, "DECIMAL": 5,
}


def csv_parse_columns(buf: bytes, ftypes, delim: str):
    """One native pass over a CSV buffer -> (arrays, valids) in storage
    representation, or None when ineligible (quotes present, unsupported
    column kind, no toolchain) — the caller falls back to Python csv.

    DATE columns land as int64 here; the caller downcasts to the storage
    dtype.  Wide decimals are ineligible (int64-only parser)."""
    lib = _load_csv()
    if lib is None or b'"' in buf:
        return None
    if len(buf) >= (1 << 31):
        # string slices travel as int32 offsets; past 2 GiB they would
        # wrap — the Python path streams instead
        return None
    kinds = []
    scales = []
    for ft in ftypes:
        code = _CSV_KINDS.get(ft.kind.name)
        if code is None or (code == 5 and ft.is_wide_decimal):
            return None
        kinds.append(code)
        scales.append(ft.scale)
    n_rows = buf.count(b"\n") + (0 if buf.endswith(b"\n") or not buf else 1)
    if n_rows == 0:
        return [], []
    ncols = len(ftypes)
    n_str = sum(1 for k in kinds if k == 2)
    cols = []
    valids = []
    ptrs = (ctypes.c_void_p * ncols)()
    vptrs = (ctypes.c_void_p * ncols)()
    for ci, k in enumerate(kinds):
        if k == 1:
            arr = np.zeros(n_rows, dtype=np.float64)
        else:
            arr = np.zeros(n_rows, dtype=np.int64)  # strings: unused slot
        v = np.zeros(n_rows, dtype=np.uint8)
        cols.append(arr)
        valids.append(v)
        ptrs[ci] = arr.ctypes.data
        vptrs[ci] = v.ctypes.data
    str_offs = np.zeros(max(n_rows * max(n_str, 1), 1), dtype=np.int32)
    str_lens = np.zeros_like(str_offs)
    kinds_arr = np.asarray(kinds, dtype=np.int32)
    scales_arr = np.asarray(scales, dtype=np.int32)
    got = lib.csv_parse(
        buf, len(buf), delim.encode()[:1], ncols,
        kinds_arr.ctypes.data, scales_arr.ctypes.data, n_rows,
        ptrs, vptrs, str_offs.ctypes.data, str_lens.ctypes.data,
        max(n_str, 1),
    )
    if got < 0:
        return None
    out_arrays = []
    out_valids = []
    str_slot = 0
    for ci, k in enumerate(kinds):
        valid = valids[ci][:got].astype(bool)
        if k == 2:
            offs = str_offs[: got * max(n_str, 1)].reshape(got, max(n_str, 1))
            lens = str_lens[: got * max(n_str, 1)].reshape(got, max(n_str, 1))
            data = np.empty(got, dtype=object)
            o_col = offs[:, str_slot]
            l_col = lens[:, str_slot]
            for i in range(got):
                data[i] = buf[o_col[i]: o_col[i] + l_col[i]].decode(
                    "utf-8", "replace") if valid[i] else ""
            str_slot += 1
            out_arrays.append(data)
        elif k == 1:
            out_arrays.append(cols[ci][:got])
        else:
            arr = cols[ci][:got]
            if ftypes[ci].np_dtype != np.int64:
                arr = arr.astype(ftypes[ci].np_dtype)
            out_arrays.append(arr)
        out_valids.append(valid)
    return out_arrays, out_valids
