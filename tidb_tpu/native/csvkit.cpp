// Native bulk CSV -> columnar parser for LOAD DATA.
//
// Reference role: executor/load_data.go's field splitting + kv encode hot
// loop (Go, row-at-a-time).  Here one C++ pass over the raw buffer emits
// columnar arrays directly — the shape bulk_load_arrays wants — so ingest
// feeds the TPU-facing block store without a Python-per-field loop.
//
// Contract (see native/__init__.py csv_parse):
//   kinds[c]: 0=int64  1=float64  2=string  3=date(YYYY-MM-DD -> days)
//             4=datetime -> micros  5=decimal(scale) -> scaled int64
//   numeric-ish cols write int64/f64 into caller-allocated [max_rows]
//   arrays; string cols write (offset,len) int32 pairs into str_offs/
//   str_lens at [row * n_str_cols + str_slot].
//   Empty fields and \N parse as NULL (valid=0).
//   Returns the number of rows parsed, or -1 on structural error.
//   Quoted fields are NOT handled here: the caller routes buffers
//   containing '"' through the Python csv path.

#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace {

// Howard Hinnant's days_from_civil (public-domain algorithm)
inline int64_t days_from_civil(int64_t y, unsigned m, unsigned d) {
    y -= m <= 2;
    const int64_t era = (y >= 0 ? y : y - 399) / 400;
    const unsigned yoe = static_cast<unsigned>(y - era * 400);
    const unsigned doy = (153 * (m + (m > 2 ? -3 : 9)) + 2) / 5 + d - 1;
    const unsigned doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
    return era * 146097 + static_cast<int64_t>(doe) - 719468;
}

constexpr int64_t kMaxI64 = 9223372036854775807LL;

inline bool acc_digit(int64_t* v, char c) {
    // overflow-checked v = v*10 + d (signed overflow is UB; reject instead)
    int64_t d = c - '0';
    if (*v > (kMaxI64 - d) / 10) return false;
    *v = *v * 10 + d;
    return true;
}

inline bool parse_int(const char* p, const char* e, int64_t* out) {
    if (p == e) return false;
    bool neg = false;
    if (*p == '-' || *p == '+') { neg = (*p == '-'); ++p; }
    if (p == e) return false;
    int64_t v = 0;
    for (; p != e; ++p) {
        if (*p < '0' || *p > '9') return false;
        if (!acc_digit(&v, *p)) return false;  // out of int64: NULL
    }
    *out = neg ? -v : v;
    return true;
}

// decimal text -> scaled int64 at `scale`, half-away-from-zero on excess
// fractional digits (mydecimal.go FromString semantics, narrow range)
inline bool parse_decimal(const char* p, const char* e, int scale,
                          int64_t* out) {
    if (p == e) return false;
    bool neg = false;
    if (*p == '-' || *p == '+') { neg = (*p == '-'); ++p; }
    if (p == e) return false;
    int64_t v = 0;
    int frac_seen = -1;  // -1: before '.', else count of frac digits taken
    int64_t round_add = 0;
    for (; p != e; ++p) {
        if (*p == '.') {
            if (frac_seen >= 0) return false;
            frac_seen = 0;
            continue;
        }
        if (*p < '0' || *p > '9') return false;
        if (frac_seen < 0) {
            if (!acc_digit(&v, *p)) return false;
        } else if (frac_seen < scale) {
            if (!acc_digit(&v, *p)) return false;
            ++frac_seen;
        } else if (frac_seen == scale) {
            round_add = (*p >= '5') ? 1 : 0;
            ++frac_seen;  // swallow the rest
        }
    }
    int pad = scale - (frac_seen < 0 ? 0 : (frac_seen > scale ? scale
                                                              : frac_seen));
    for (int i = 0; i < pad; ++i) {
        if (v > kMaxI64 / 10) return false;
        v *= 10;
    }
    if (v == kMaxI64 && round_add) return false;
    v += round_add;
    *out = neg ? -v : v;
    return true;
}

inline bool parse_date_days(const char* p, const char* e, int64_t* out) {
    // YYYY-MM-DD (lengths 8-10 tolerated for 1-digit month/day)
    int64_t y = 0, m = 0, d = 0;
    const char* q = p;
    while (q != e && *q != '-') { if (*q < '0' || *q > '9') return false;
        y = y * 10 + (*q - '0'); ++q; }
    if (q == e) return false; ++q;
    while (q != e && *q != '-') { if (*q < '0' || *q > '9') return false;
        m = m * 10 + (*q - '0'); ++q; }
    if (q == e) return false; ++q;
    while (q != e) { if (*q < '0' || *q > '9') return false;
        d = d * 10 + (*q - '0'); ++q; }
    if (m < 1 || m > 12 || d < 1 || d > 31) return false;
    *out = days_from_civil(y, static_cast<unsigned>(m),
                           static_cast<unsigned>(d));
    return true;
}

inline bool parse_datetime_us(const char* p, const char* e, int64_t* out) {
    // "YYYY-MM-DD[ HH:MM:SS[.ffffff]]"
    const char* sp = p;
    while (sp != e && *sp != ' ' && *sp != 'T') ++sp;
    int64_t days;
    if (!parse_date_days(p, sp, &days)) return false;
    int64_t us = days * 86400000000LL;
    if (sp != e) {
        ++sp;
        int64_t h = 0, mi = 0, s = 0, frac = 0; int fdig = 0;
        const char* q = sp;
        while (q != e && *q != ':') { if (*q < '0' || *q > '9') return false;
            h = h * 10 + (*q - '0'); ++q; }
        if (q != e) { ++q;
            while (q != e && *q != ':') { if (*q < '0' || *q > '9')
                return false; mi = mi * 10 + (*q - '0'); ++q; }
            if (q != e) { ++q;
                while (q != e && *q != '.') { if (*q < '0' || *q > '9')
                    return false; s = s * 10 + (*q - '0'); ++q; }
                if (q != e) { ++q;
                    while (q != e && fdig < 6) { if (*q < '0' || *q > '9')
                        return false; frac = frac * 10 + (*q - '0');
                        ++fdig; ++q; }
                }
            }
        }
        while (fdig < 6) { frac *= 10; ++fdig; }
        us += (h * 3600 + mi * 60 + s) * 1000000LL + frac;
    }
    *out = us;
    return true;
}

}  // namespace

extern "C" {

// out_cols: ncols pointers; int64* for kinds 0/3/4/5, double* for kind 1,
// ignored (may be null) for kind 2.  out_valid: ncols pointers to uint8
// [max_rows].  str_offs/str_lens: int32 [max_rows * n_str_cols].
int64_t csv_parse(const char* buf, int64_t len, char delim, int32_t ncols,
                  const int32_t* kinds, const int32_t* scales,
                  int64_t max_rows, void** out_cols, uint8_t** out_valid,
                  int32_t* str_offs, int32_t* str_lens,
                  int32_t n_str_cols) {
    int64_t row = 0;
    int64_t i = 0;
    while (i < len && row < max_rows) {
        // one record
        int32_t col = 0, str_slot = 0;
        while (col < ncols) {
            int64_t start = i;
            while (i < len && buf[i] != delim && buf[i] != '\n')
                ++i;
            int64_t end = i;
            // CRLF: the \r belongs to the terminator, not the field
            if (end > start && i < len && buf[i] == '\n'
                && buf[end - 1] == '\r')
                --end;
            const char* p = buf + start;
            const char* e = buf + end;
            bool is_null = (start == end) ||
                (end - start == 2 && p[0] == '\\' && p[1] == 'N');
            uint8_t ok = 0;
            switch (kinds[col]) {
                case 0: {  // int64
                    int64_t v;
                    if (!is_null && parse_int(p, e, &v)) {
                        reinterpret_cast<int64_t*>(out_cols[col])[row] = v;
                        ok = 1;
                    } else {
                        reinterpret_cast<int64_t*>(out_cols[col])[row] = 0;
                    }
                    break;
                }
                case 1: {  // float64
                    if (!is_null) {
                        char tmp[64];
                        int64_t n = end - start;
                        if (n > 0 && n < 63) {
                            memcpy(tmp, p, n);
                            tmp[n] = 0;
                            char* endp = nullptr;
                            double v = strtod(tmp, &endp);
                            if (endp == tmp + n) {
                                reinterpret_cast<double*>(
                                    out_cols[col])[row] = v;
                                ok = 1;
                            }
                        }
                    }
                    if (!ok)
                        reinterpret_cast<double*>(out_cols[col])[row] = 0.0;
                    break;
                }
                case 2: {  // string: record the slice.  Only \N is NULL —
                    // an empty field is the empty string (LOAD DATA rule)
                    bool null_str = (end - start == 2 && p[0] == '\\'
                                     && p[1] == 'N');
                    str_offs[row * n_str_cols + str_slot] =
                        static_cast<int32_t>(null_str ? 0 : start);
                    str_lens[row * n_str_cols + str_slot] =
                        static_cast<int32_t>(null_str ? 0 : end - start);
                    ok = null_str ? 0 : 1;
                    ++str_slot;
                    break;
                }
                case 3: {  // date -> days
                    int64_t v;
                    if (!is_null && parse_date_days(p, e, &v)) {
                        reinterpret_cast<int64_t*>(out_cols[col])[row] = v;
                        ok = 1;
                    } else {
                        reinterpret_cast<int64_t*>(out_cols[col])[row] = 0;
                    }
                    break;
                }
                case 4: {  // datetime -> micros
                    int64_t v;
                    if (!is_null && parse_datetime_us(p, e, &v)) {
                        reinterpret_cast<int64_t*>(out_cols[col])[row] = v;
                        ok = 1;
                    } else {
                        reinterpret_cast<int64_t*>(out_cols[col])[row] = 0;
                    }
                    break;
                }
                case 5: {  // decimal(scale) -> scaled int64
                    int64_t v;
                    if (!is_null && parse_decimal(p, e, scales[col], &v)) {
                        reinterpret_cast<int64_t*>(out_cols[col])[row] = v;
                        ok = 1;
                    } else {
                        reinterpret_cast<int64_t*>(out_cols[col])[row] = 0;
                    }
                    break;
                }
                default:
                    return -1;
            }
            out_valid[col][row] = ok;
            ++col;
            if (i < len && buf[i] == delim) {
                ++i;
                if (col == ncols) return -2;  // too many fields
            } else {
                break;  // end of record (or buffer)
            }
        }
        // missing trailing fields -> NULL
        for (; col < ncols; ++col) {
            out_valid[col][row] = 0;
            if (kinds[col] == 2) {
                str_offs[row * n_str_cols + str_slot] = 0;
                str_lens[row * n_str_cols + str_slot] = 0;
                ++str_slot;
            } else if (kinds[col] == 1) {
                reinterpret_cast<double*>(out_cols[col])[row] = 0.0;
            } else {
                reinterpret_cast<int64_t*>(out_cols[col])[row] = 0;
            }
        }
        // consume the record terminator (records end at \n only)
        if (i < len && buf[i] == '\n') ++i;
        ++row;
    }
    return row;
}

}  // extern "C"
