// Native host kernels for the root executor runtime.
//
// Reference rationale: the reference's performance-critical storage half is
// native (TiKV/Rust, outside its repo); here the device compute path is
// JAX/XLA and THIS file is the native runtime piece for host-side hot loops
// the device cannot take: hash-join key factorization and memcomparable key
// encoding (util/codec analog).
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in the image).
// Build: tidb_tpu/native/build.py (gcc -O3 -shared -fPIC, cached .so).

#include <cstdint>
#include <cstring>
#include <cstdlib>

extern "C" {

// Open-addressing hash table over int64 keys.  Factorizes `keys[n]` into
// dense codes [0, n_distinct): codes_out[i] = dense id of keys[i].
// Returns n_distinct, or -1 on allocation failure.
//
// The join build+probe both call this with a SHARED table handle so probe
// keys map into the build key space (unseen probe keys get code -1).

typedef struct {
    int64_t *slots;   // key per slot
    int64_t *codes;   // dense code per slot
    uint8_t *used;    // occupancy per slot (no sentinel key value: every
                      // int64 is a legal key)
    uint64_t mask;    // capacity - 1
    int64_t n;        // distinct count
} ht64;

static inline uint64_t mix64(uint64_t x) {
    x += 0x9E3779B97F4A7C15ull;
    x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
    x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
    return x ^ (x >> 31);
}

ht64 *ht64_new(int64_t expected) {
    uint64_t cap = 16;
    while (cap < (uint64_t)(expected * 2 + 1)) cap <<= 1;
    ht64 *h = (ht64 *)malloc(sizeof(ht64));
    if (!h) return nullptr;
    h->slots = (int64_t *)malloc(cap * sizeof(int64_t));
    h->codes = (int64_t *)malloc(cap * sizeof(int64_t));
    h->used = (uint8_t *)calloc(cap, 1);
    if (!h->slots || !h->codes || !h->used) {
        free(h->slots); free(h->codes); free(h->used); free(h);
        return nullptr;
    }
    h->mask = cap - 1;
    h->n = 0;
    return h;
}

void ht64_free(ht64 *h) {
    if (!h) return;
    free(h->slots);
    free(h->codes);
    free(h->used);
    free(h);
}

// grow to the next power of two and rehash; returns 0 on OOM.
static int ht64_grow(ht64 *h) {
    uint64_t old_cap = h->mask + 1;
    uint64_t cap = old_cap << 1;
    int64_t *slots = (int64_t *)malloc(cap * sizeof(int64_t));
    int64_t *codes = (int64_t *)malloc(cap * sizeof(int64_t));
    uint8_t *used = (uint8_t *)calloc(cap, 1);
    if (!slots || !codes || !used) { free(slots); free(codes); free(used); return 0; }
    uint64_t mask = cap - 1;
    for (uint64_t i = 0; i < old_cap; i++) {
        if (!h->used[i]) continue;
        int64_t k = h->slots[i];
        uint64_t pos = mix64((uint64_t)k) & mask;
        while (used[pos]) pos = (pos + 1) & mask;
        slots[pos] = k;
        codes[pos] = h->codes[i];
        used[pos] = 1;
    }
    free(h->slots); free(h->codes); free(h->used);
    h->slots = slots; h->codes = codes; h->used = used; h->mask = mask;
    return 1;
}

// insert-or-get codes for keys; valid[i]==0 rows get code -1.
// Returns n_distinct, or -1 on allocation failure during growth.
int64_t ht64_upsert(ht64 *h, const int64_t *keys, const uint8_t *valid,
                    int64_t n, int64_t *codes_out) {
    for (int64_t i = 0; i < n; i++) {
        if (valid && !valid[i]) { codes_out[i] = -1; continue; }
        // keep load factor < 0.75 so the probe loop always terminates
        if ((uint64_t)h->n * 4 >= (h->mask + 1) * 3) {
            if (!ht64_grow(h)) return -1;
        }
        int64_t k = keys[i];
        uint64_t pos = mix64((uint64_t)k) & h->mask;
        for (;;) {
            if (!h->used[pos]) {
                h->slots[pos] = k;
                h->codes[pos] = h->n;
                h->used[pos] = 1;
                codes_out[i] = h->n;
                h->n++;
                break;
            }
            if (h->slots[pos] == k) { codes_out[i] = h->codes[pos]; break; }
            pos = (pos + 1) & h->mask;
        }
    }
    return h->n;
}

// lookup-only: unseen keys -> -1 (probe side).
void ht64_lookup(const ht64 *h, const int64_t *keys, const uint8_t *valid,
                 int64_t n, int64_t *codes_out) {
    for (int64_t i = 0; i < n; i++) {
        if (valid && !valid[i]) { codes_out[i] = -1; continue; }
        int64_t k = keys[i];
        uint64_t pos = mix64((uint64_t)k) & h->mask;
        for (;;) {
            if (!h->used[pos]) { codes_out[i] = -1; break; }
            if (h->slots[pos] == k) { codes_out[i] = h->codes[pos]; break; }
            pos = (pos + 1) & h->mask;
        }
    }
}

// ---------------------------------------------------------------------------
// memcomparable codec (util/codec analog): order-preserving encoding of
// int64 keys so encoded byte strings sort like the integers (sign-flipped
// big-endian).  Used by the KV checkpoint format and the wire protocol.
// dst must hold 8*n bytes.
void encode_i64_memcomparable(const int64_t *src, int64_t n, uint8_t *dst) {
    for (int64_t i = 0; i < n; i++) {
        uint64_t u = (uint64_t)src[i] ^ 0x8000000000000000ull;
        uint8_t *d = dst + i * 8;
        d[0] = (uint8_t)(u >> 56); d[1] = (uint8_t)(u >> 48);
        d[2] = (uint8_t)(u >> 40); d[3] = (uint8_t)(u >> 32);
        d[4] = (uint8_t)(u >> 24); d[5] = (uint8_t)(u >> 16);
        d[6] = (uint8_t)(u >> 8);  d[7] = (uint8_t)u;
    }
}

void decode_i64_memcomparable(const uint8_t *src, int64_t n, int64_t *dst) {
    for (int64_t i = 0; i < n; i++) {
        const uint8_t *s = src + i * 8;
        uint64_t u = ((uint64_t)s[0] << 56) | ((uint64_t)s[1] << 48) |
                     ((uint64_t)s[2] << 40) | ((uint64_t)s[3] << 32) |
                     ((uint64_t)s[4] << 24) | ((uint64_t)s[5] << 16) |
                     ((uint64_t)s[6] << 8) | (uint64_t)s[7];
        dst[i] = (int64_t)(u ^ 0x8000000000000000ull);
    }
}

}  // extern "C"
