"""Device kernels (jax).

Importing this package configures jax for the framework:
- x64 enabled: SQL semantics need int64 handles/sums and float64 agg
  accumulation (XLA emulates 64-bit on TPU; elementwise hot loops below keep
  32-bit types where safe and widen only at the reduction boundary).
"""

import os

import jax

jax.config.update("jax_enable_x64", True)

# Persistent compilation cache: compiles on the tunneled TPU go through a
# remote AOT helper and cost seconds-to-minutes; caching them on disk makes
# warm-up across processes ~instant (measured 67s -> 0.95s).  Opt out with
# TIDB_TPU_COMPILE_CACHE=0 or point elsewhere with =<dir>.
_cc = os.environ.get("TIDB_TPU_COMPILE_CACHE", "")
if _cc != "0":
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            _cc or os.path.join(
                os.path.expanduser("~"), ".cache", "tidb_tpu_xla"),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception as _e:  # older jax without the knobs
        if _cc:  # the user explicitly asked for a cache dir: say why not
            import warnings

            warnings.warn(
                f"TIDB_TPU_COMPILE_CACHE={_cc!r} requested but the jax "
                f"persistent compilation cache could not be enabled: {_e}")

from .segment import (  # noqa: E402
    masked_segment_sum,
    masked_segment_count,
    masked_segment_min,
    masked_segment_max,
    masked_segment_argfirst,
    segment_min,
)
from .topk import masked_top_k  # noqa: E402

__all__ = [
    "masked_segment_sum",
    "masked_segment_count",
    "masked_segment_min",
    "masked_segment_max",
    "masked_segment_argfirst",
    "segment_min",
    "masked_top_k",
]
