"""Device kernels (jax).

Importing this package configures jax for the framework:
- x64 enabled: SQL semantics need int64 handles/sums and float64 agg
  accumulation (XLA emulates 64-bit on TPU; elementwise hot loops below keep
  32-bit types where safe and widen only at the reduction boundary).
"""

import jax

jax.config.update("jax_enable_x64", True)

from .segment import (  # noqa: E402
    masked_segment_sum,
    masked_segment_count,
    masked_segment_min,
    masked_segment_max,
    masked_segment_argfirst,
    segment_min,
)
from .topk import masked_top_k  # noqa: E402

__all__ = [
    "masked_segment_sum",
    "masked_segment_count",
    "masked_segment_min",
    "masked_segment_max",
    "masked_segment_argfirst",
    "segment_min",
    "masked_top_k",
]
