"""Segmented reductions with selection masks.

The TPU-native replacement for the reference's hash-aggregation inner loops
(executor/aggregate.go partial workers; mocktikv row-at-a-time aggregation):
group codes are dense ints, so partial aggregation is a segment reduction —
an operation XLA compiles to efficient scatter/one-hot-matmul kernels on the
MXU instead of a hash table.  Reference pattern: "partial aggregates"
two-phase split (planner/core/task.go agg pushdown; DrJAX mapreduce
primitives, PAPERS.md).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def masked_segment_sum(data, gidx, mask, num_segments: int):
    """sum of data[i] into segment gidx[i] where mask[i]."""
    zero = jnp.zeros((), dtype=data.dtype)
    contrib = jnp.where(mask, data, zero)
    return jax.ops.segment_sum(contrib, gidx, num_segments=num_segments)


def masked_segment_count(gidx, mask, num_segments: int):
    return jax.ops.segment_sum(
        mask.astype(jnp.int64), gidx, num_segments=num_segments
    )


def masked_segment_min(data, gidx, mask, num_segments: int):
    big = _extreme(data.dtype, True)
    contrib = jnp.where(mask, data, big)
    return jax.ops.segment_min(contrib, gidx, num_segments=num_segments)


def masked_segment_max(data, gidx, mask, num_segments: int):
    small = _extreme(data.dtype, False)
    contrib = jnp.where(mask, data, small)
    return jax.ops.segment_max(contrib, gidx, num_segments=num_segments)


def masked_segment_argfirst(gidx, mask, num_segments: int):
    """Index of the first masked row per segment (for FIRST_ROW);
    num_rows (= len(gidx)) where the segment is empty."""
    n = gidx.shape[0]
    idx = jnp.arange(n, dtype=jnp.int64)
    contrib = jnp.where(mask, idx, n)
    return jax.ops.segment_min(contrib, gidx, num_segments=num_segments)


def _extreme(dtype, want_max: bool):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf if want_max else -jnp.inf, dtype=dtype)
    info = jnp.iinfo(dtype)
    return jnp.array(info.max if want_max else info.min, dtype=dtype)
