"""Segmented reductions with selection masks.

The TPU-native replacement for the reference's hash-aggregation inner loops
(executor/aggregate.go partial workers; mocktikv row-at-a-time aggregation):
group codes are dense ints, so partial aggregation is a segment reduction —
an operation XLA compiles to efficient scatter/one-hot-matmul kernels on the
MXU instead of a hash table.  Reference pattern: "partial aggregates"
two-phase split (planner/core/task.go agg pushdown; DrJAX mapreduce
primitives, PAPERS.md).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


# Group-count threshold below which segment reductions unroll into one
# masked full reduction per group instead of a scatter.  TPU scatter over
# millions of colliding updates is catastrophically slow on v5e (~300-500ms
# per 4M-row 64-bit scatter measured through the XLA emulation path), while
# XLA fuses G unrolled where+reduce passes into a single data traversal
# (~10ms for a full Q1-shaped aggregation at G=6).  Typical analytical GROUP
# BYs (TPC-H Q1/Q12/Q14...) have tiny G; high-NDV aggregations take the
# sort-based mesh path instead.
UNROLL_G = 32


def masked_segment_sum(data, gidx, mask, num_segments: int):
    """sum of data[i] into segment gidx[i] where mask[i]."""
    zero = jnp.zeros((), dtype=data.dtype)
    if num_segments <= UNROLL_G:
        return jnp.stack([
            jnp.sum(jnp.where(mask & (gidx == g), data, zero))
            for g in range(num_segments)
        ])
    contrib = jnp.where(mask, data, zero)
    return jax.ops.segment_sum(contrib, gidx, num_segments=num_segments)


def masked_segment_count(gidx, mask, num_segments: int):
    if num_segments <= UNROLL_G:
        return jnp.stack([
            jnp.sum((mask & (gidx == g)).astype(jnp.int64))
            for g in range(num_segments)
        ])
    return jax.ops.segment_sum(
        mask.astype(jnp.int64), gidx, num_segments=num_segments
    )


def masked_segment_min(data, gidx, mask, num_segments: int):
    big = _extreme(data.dtype, True)
    if num_segments <= UNROLL_G:
        return jnp.stack([
            jnp.min(jnp.where(mask & (gidx == g), data, big))
            for g in range(num_segments)
        ])
    contrib = jnp.where(mask, data, big)
    return jax.ops.segment_min(contrib, gidx, num_segments=num_segments)


def masked_segment_max(data, gidx, mask, num_segments: int):
    small = _extreme(data.dtype, False)
    if num_segments <= UNROLL_G:
        return jnp.stack([
            jnp.max(jnp.where(mask & (gidx == g), data, small))
            for g in range(num_segments)
        ])
    contrib = jnp.where(mask, data, small)
    return jax.ops.segment_max(contrib, gidx, num_segments=num_segments)


def masked_segment_argfirst(gidx, mask, num_segments: int):
    """Index of the first masked row per segment (for FIRST_ROW);
    num_rows (= len(gidx)) where the segment is empty."""
    n = gidx.shape[0]
    idx = jnp.arange(n, dtype=jnp.int64)
    if num_segments <= UNROLL_G:
        return jnp.stack([
            jnp.min(jnp.where(mask & (gidx == g), idx, n))
            for g in range(num_segments)
        ])
    contrib = jnp.where(mask, idx, n)
    return jax.ops.segment_min(contrib, gidx, num_segments=num_segments)


def _extreme(dtype, want_max: bool):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf if want_max else -jnp.inf, dtype=dtype)
    info = jnp.iinfo(dtype)
    return jnp.array(info.max if want_max else info.min, dtype=dtype)


def segment_min(data, gidx, num_segments: int):
    """Plain segment min with the same small-G unrolling as the masked ops."""
    if num_segments <= UNROLL_G:
        big = _extreme(data.dtype, True)
        return jnp.stack([
            jnp.min(jnp.where(gidx == g, data, big))
            for g in range(num_segments)
        ])
    return jax.ops.segment_min(data, gidx, num_segments=num_segments)
