"""Masked top-k for TopN pushdown.

Reference: TopN coprocessor executor (mocktikv/topn.go).  On device: build a
single sortable key per row, mask invalid rows to -inf, lax.top_k, return
flat row indices for the host to gather.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def masked_top_k(key, mask, k: int, descending: bool):
    """Return (indices, count) of the top/bottom-k masked rows by `key`.

    key: float64/int64 [n]; mask: bool [n].  Ties broken by row index
    (ascending) for deterministic results.
    """
    kf = key.astype(jnp.float64)
    if not descending:
        kf = -kf
    neg_inf = jnp.array(-jnp.inf, dtype=jnp.float64)
    kf = jnp.where(mask, kf, neg_inf)
    # tie-break on row index: subtract tiny monotonic epsilon
    n = key.shape[0]
    idxf = jnp.arange(n, dtype=jnp.float64)
    kf = kf - idxf * 1e-18
    _, idx = jax.lax.top_k(kf, k)
    valid_count = jnp.minimum(mask.sum(), k)
    return idx, valid_count
