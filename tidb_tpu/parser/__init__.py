from .parser import parse, parse_one
from . import ast

__all__ = ["parse", "parse_one", "ast"]
