"""AST node definitions.

Reference model: pingcap/parser's ast package (ast.StmtNode consumed at
session/session.go:982).  Plain dataclasses; the planner walks these.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple, Union


class Node:
    pass


class Expr(Node):
    pass


# ---------------- expressions ----------------


@dataclass
class Literal(Expr):
    value: object  # int | float | str | bool | None
    type_hint: str = ""  # "", "date", "datetime", "decimal"


@dataclass
class ColumnRef(Expr):
    name: str
    table: str = ""
    db: str = ""

    def __str__(self):
        parts = [p for p in (self.db, self.table, self.name) if p]
        return ".".join(parts)


@dataclass
class Star(Expr):
    table: str = ""  # t.* when set


@dataclass
class BinaryOp(Expr):
    op: str  # +,-,*,/,div,%,=,<,>,<=,>=,!=,and,or,like,is,is not,xor,<<,>>,&,|,^
    left: Expr
    right: Expr


@dataclass
class UnaryOp(Expr):
    op: str  # -, not, ~, +
    operand: Expr


@dataclass
class FrameBound(Node):
    kind: str  # unbounded_preceding|preceding|current|following|unbounded_following
    offset: int = 0


@dataclass
class WindowSpec(Node):
    partition_by: List["Expr"] = field(default_factory=list)
    order_by: List["OrderItem"] = field(default_factory=list)
    unit: str = ""  # "", "rows", "range"
    start: Optional[FrameBound] = None
    end: Optional[FrameBound] = None


@dataclass
class FuncCall(Expr):
    name: str  # lowercase
    args: List[Expr]
    distinct: bool = False  # COUNT(DISTINCT x)
    over: Optional[WindowSpec] = None  # window function when set


@dataclass
class CaseWhen(Expr):
    operand: Optional[Expr]  # CASE x WHEN... vs CASE WHEN...
    branches: List[Tuple[Expr, Expr]]
    else_expr: Optional[Expr]


@dataclass
class Cast(Expr):
    expr: Expr
    type_name: str  # "signed", "unsigned", "char", "double", "decimal(p,s)", "date", "datetime"
    precision: int = 0
    scale: int = 0


@dataclass
class InList(Expr):
    expr: Expr
    items: List[Expr]
    negated: bool = False


@dataclass
class InSubquery(Expr):
    expr: Expr
    query: "SelectStmt"
    negated: bool = False


@dataclass
class Between(Expr):
    expr: Expr
    low: Expr
    high: Expr
    negated: bool = False


@dataclass
class Exists(Expr):
    query: "SelectStmt"
    negated: bool = False


@dataclass
class ScalarSubquery(Expr):
    query: "SelectStmt"


@dataclass
class Interval(Expr):
    value: Expr
    unit: str  # day, month, year, hour, minute, second, week, quarter


@dataclass
class Variable(Expr):
    name: str
    is_global: bool = False
    is_system: bool = False  # @@x vs @x


@dataclass
class Default(Expr):
    pass


@dataclass
class Param(Expr):
    """A `?` placeholder in a prepared statement."""

    index: int


# ---------------- table refs ----------------


@dataclass
class TableName(Node):
    name: str
    db: str = ""
    alias: str = ""


@dataclass
class SubqueryRef(Node):
    query: "SelectStmt"
    alias: str


@dataclass
class Join(Node):
    kind: str  # inner, left, right, cross
    left: Node
    right: Node
    on: Optional[Expr] = None
    using: List[str] = field(default_factory=list)


# ---------------- statements ----------------


class Stmt(Node):
    pass


@dataclass
class SelectField(Node):
    expr: Expr
    alias: str = ""


@dataclass
class OrderItem(Node):
    expr: Expr
    desc: bool = False


@dataclass
class SelectStmt(Stmt):
    fields: List[SelectField]
    from_clause: Optional[Node] = None  # TableName | SubqueryRef | Join
    where: Optional[Expr] = None
    group_by: List[Expr] = field(default_factory=list)
    having: Optional[Expr] = None
    order_by: List[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None
    offset: int = 0
    distinct: bool = False
    for_update: bool = False


@dataclass
class UnionStmt(Stmt):
    selects: List[SelectStmt]
    all: bool = False  # UNION ALL vs UNION (distinct)
    order_by: List[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None
    offset: int = 0


@dataclass
class ColumnDef(Node):
    name: str
    type_name: str  # normalized lowercase: bigint, double, varchar, decimal, date, datetime, ...
    precision: int = 0
    scale: int = 0
    not_null: bool = False
    primary_key: bool = False
    unique: bool = False
    default: Optional[Expr] = None
    auto_increment: bool = False
    elems: List[str] = field(default_factory=list)  # ENUM/SET members


@dataclass
class IndexDef(Node):
    name: str
    columns: List[str]
    unique: bool = False
    primary: bool = False


@dataclass
class PartitionDefAst(Node):
    name: str
    less_than: Optional[int] = None  # None = MAXVALUE


@dataclass
class PartitionByAst(Node):
    kind: str  # "range" | "hash"
    column: str
    defs: List[PartitionDefAst] = field(default_factory=list)
    num: int = 0  # HASH ... PARTITIONS n


@dataclass
class FkDef(Node):
    """FOREIGN KEY metadata (stored, displayed, not enforced — matching
    the reference's FK support level, ddl_api.go:3509)."""

    name: str = ""
    columns: List[str] = field(default_factory=list)
    ref_table: "TableName" = None
    ref_columns: List[str] = field(default_factory=list)


@dataclass
class CreateTableStmt(Stmt):
    table: TableName
    columns: List[ColumnDef]
    indexes: List[IndexDef] = field(default_factory=list)
    if_not_exists: bool = False
    partition_by: Optional[PartitionByAst] = None
    foreign_keys: List[FkDef] = field(default_factory=list)


@dataclass
class DropTableStmt(Stmt):
    tables: List[TableName]
    if_exists: bool = False
    is_view: bool = False


@dataclass
class TruncateTableStmt(Stmt):
    table: TableName


@dataclass
class CreateIndexStmt(Stmt):
    index_name: str
    table: TableName
    columns: List[str]
    unique: bool = False


@dataclass
class DropIndexStmt(Stmt):
    index_name: str
    table: TableName


@dataclass
class AlterTableStmt(Stmt):
    table: TableName
    action: str  # add_column, drop_column, add_index, drop_index, rename,
    # modify_column, add_partition, drop_partition, truncate_partition,
    # coalesce_partition
    column: Optional[ColumnDef] = None
    index: Optional[IndexDef] = None
    name: str = ""  # drop target / rename target
    part_defs: List["PartitionDefAst"] = field(default_factory=list)
    names: List[str] = field(default_factory=list)  # partition names
    number: int = 0  # COALESCE PARTITION n / ADD PARTITION PARTITIONS n /
    # AUTO_INCREMENT rebase value
    fk: Optional["FkDef"] = None  # ADD FOREIGN KEY


@dataclass
class CreateRoleStmt(Stmt):
    roles: List[str] = field(default_factory=list)
    if_not_exists: bool = False


@dataclass
class DropRoleStmt(Stmt):
    roles: List[str] = field(default_factory=list)
    if_exists: bool = False


@dataclass
class GrantRoleStmt(Stmt):
    roles: List[str] = field(default_factory=list)
    users: List[str] = field(default_factory=list)


@dataclass
class RevokeRoleStmt(Stmt):
    roles: List[str] = field(default_factory=list)
    users: List[str] = field(default_factory=list)


@dataclass
class SetRoleStmt(Stmt):
    mode: str = "list"  # list | all | none | default
    roles: List[str] = field(default_factory=list)


@dataclass
class SetDefaultRoleStmt(Stmt):
    mode: str = "list"  # list | all | none
    roles: List[str] = field(default_factory=list)
    users: List[str] = field(default_factory=list)


@dataclass
class DropStatsStmt(Stmt):
    table: TableName = None


@dataclass
class RepairTableStmt(Stmt):
    table: TableName = None


@dataclass
class RenameTableStmt(Stmt):
    old: TableName = None
    new: TableName = None


@dataclass
class CreateDatabaseStmt(Stmt):
    name: str
    if_not_exists: bool = False


@dataclass
class DropDatabaseStmt(Stmt):
    name: str
    if_exists: bool = False


@dataclass
class CreateViewStmt(Stmt):
    name: TableName = None
    query: Stmt = None
    or_replace: bool = False


@dataclass
class InsertStmt(Stmt):
    table: TableName
    columns: List[str]
    values: List[List[Expr]] = field(default_factory=list)
    query: Optional[Stmt] = None  # INSERT ... SELECT
    replace: bool = False
    ignore: bool = False
    on_dup_update: List[Tuple[str, Expr]] = field(default_factory=list)


@dataclass
class UpdateStmt(Stmt):
    table: TableName
    assignments: List[Tuple[str, Expr]] = field(default_factory=list)
    where: Optional[Expr] = None
    order_by: List[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None


@dataclass
class DeleteStmt(Stmt):
    table: TableName
    where: Optional[Expr] = None
    order_by: List[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None


@dataclass
class ExplainStmt(Stmt):
    target: Stmt
    analyze: bool = False
    format: str = "row"


@dataclass
class TraceStmt(Stmt):
    target: Stmt
    fmt: str = "row"  # TRACE FORMAT='row'|'json'


@dataclass
class SetStmt(Stmt):
    assignments: List[Tuple[str, bool, Expr]]  # (name, is_global, value)


@dataclass
class ShowStmt(Stmt):
    kind: str  # tables, databases, columns, create_table, variables, index, warnings, ...
    target: str = ""
    db: str = ""
    like: Optional[str] = None
    where: Optional[Expr] = None
    is_global: bool = False
    full: bool = False


@dataclass
class UseStmt(Stmt):
    db: str


@dataclass
class BeginStmt(Stmt):
    pass


@dataclass
class CommitStmt(Stmt):
    pass


@dataclass
class RollbackStmt(Stmt):
    pass


@dataclass
class AnalyzeTableStmt(Stmt):
    tables: List[TableName]


@dataclass
class LoadDataStmt(Stmt):
    path: str
    table: TableName
    fields_terminated: str = "\t"
    lines_terminated: str = "\n"
    ignore_lines: int = 0


@dataclass
class PrepareStmt(Stmt):
    name: str
    sql: str


@dataclass
class ExecuteStmt(Stmt):
    name: str
    using: List[str] = field(default_factory=list)  # user variable names


@dataclass
class DeallocateStmt(Stmt):
    name: str


@dataclass
class KillStmt(Stmt):
    conn_id: int
    query_only: bool = False


@dataclass
class AdminStmt(Stmt):
    kind: str  # check_table, show_ddl, show_ddl_jobs, recover_index, ...
    tables: List[TableName] = field(default_factory=list)
    index: str = ""  # RECOVER/CLEANUP INDEX target


@dataclass
class RecoverTableStmt(Stmt):
    table: TableName = None


@dataclass
class SplitRegionStmt(Stmt):
    table: TableName = None
    num: int = 0


@dataclass
class GrantStmt(Stmt):
    privs: List[str] = field(default_factory=list)
    level: str = "*.*"
    user: str = ""


@dataclass
class RevokeStmt(Stmt):
    privs: List[str] = field(default_factory=list)
    level: str = "*.*"
    user: str = ""


@dataclass
class CreateUserStmt(Stmt):
    user: str = ""
    password: str = ""
    if_not_exists: bool = False


@dataclass
class DropUserStmt(Stmt):
    user: str = ""
    if_exists: bool = False


@dataclass
class SetPasswordStmt(Stmt):
    user: str = ""
    password: str = ""


@dataclass
class ResourceGroupStmt(Stmt):
    """CREATE/ALTER/DROP RESOURCE GROUP (TiDB resource control DDL).

    For ALTER, None option fields mean "leave unchanged"."""
    kind: str = "create"     # create | alter | drop
    name: str = ""
    ru_per_sec: Optional[int] = None
    burstable: Optional[bool] = None
    query_limit_ms: Optional[int] = None
    priority: Optional[int] = None
    if_not_exists: bool = False
    if_exists: bool = False


@dataclass
class AlterUserResourceGroupStmt(Stmt):
    """ALTER USER u RESOURCE GROUP g — bind a user to a group."""
    user: str = ""
    group: str = ""


@dataclass
class LockTablesStmt(Stmt):
    items: List[Tuple[TableName, str]] = field(default_factory=list)  # (t, read|write)


@dataclass
class UnlockTablesStmt(Stmt):
    pass


@dataclass
class FlushStmt(Stmt):
    what: str = "privileges"


@dataclass
class DescTableStmt(Stmt):
    table: TableName = None
