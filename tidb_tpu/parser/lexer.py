"""SQL lexer (MySQL dialect subset).

Reference: the external yacc-based pingcap/parser (consumed at
session/session.go:982).  We hand-roll: a token stream with positions for
error messages, MySQL quoting rules (single-quoted strings with '' and \\
escapes, backtick-quoted identifiers, # and -- comments).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

from ..errors import ParseError


class T(enum.Enum):
    IDENT = "IDENT"
    QIDENT = "QIDENT"  # `quoted`
    INT = "INT"
    FLOAT = "FLOAT"
    STRING = "STRING"
    OP = "OP"
    EOF = "EOF"


@dataclass
class Token:
    kind: T
    value: str
    line: int
    col: int

    def __repr__(self):
        return f"{self.kind.name}({self.value!r})"


_TWO_CHAR_OPS = {"<=", ">=", "<>", "!=", ":=", "||", "&&", "<<", ">>"}
_ONE_CHAR_OPS = set("+-*/%(),.;=<>!@^&|~?")


def tokenize(sql: str) -> list[Token]:
    toks: list[Token] = []
    i, n = 0, len(sql)
    line, col = 1, 1

    def adv(k: int = 1):
        nonlocal i, line, col
        for _ in range(k):
            if i < n and sql[i] == "\n":
                line += 1
                col = 1
            else:
                col += 1
            i += 1

    while i < n:
        c = sql[i]
        if c in " \t\r\n":
            adv()
            continue
        # comments
        if c == "#" or sql.startswith("--", i):
            while i < n and sql[i] != "\n":
                adv()
            continue
        if sql.startswith("/*", i):
            start_line, start_col = line, col
            adv(2)
            while i < n and not sql.startswith("*/", i):
                adv()
            if i >= n:
                raise ParseError("unterminated comment", start_line, start_col)
            adv(2)
            continue
        tl, tc = line, col
        # strings
        if c in ("'", '"'):
            q = c
            adv()
            buf = []
            while i < n:
                if sql[i] == "\\" and i + 1 < n:
                    esc = sql[i + 1]
                    buf.append(
                        {"n": "\n", "t": "\t", "r": "\r", "0": "\0", "\\": "\\",
                         "'": "'", '"': '"'}.get(esc, esc)
                    )
                    adv(2)
                elif sql[i] == q:
                    if i + 1 < n and sql[i + 1] == q:  # '' escape
                        buf.append(q)
                        adv(2)
                    else:
                        break
                else:
                    buf.append(sql[i])
                    adv()
            if i >= n:
                raise ParseError("unterminated string", tl, tc)
            adv()  # closing quote
            toks.append(Token(T.STRING, "".join(buf), tl, tc))
            continue
        # backtick identifiers
        if c == "`":
            adv()
            buf = []
            while i < n and sql[i] != "`":
                buf.append(sql[i])
                adv()
            if i >= n:
                raise ParseError("unterminated identifier", tl, tc)
            adv()
            toks.append(Token(T.QIDENT, "".join(buf), tl, tc))
            continue
        # numbers
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            isfloat = False
            while j < n and (sql[j].isdigit() or sql[j] == "."):
                if sql[j] == ".":
                    if isfloat:
                        break
                    isfloat = True
                j += 1
            if j < n and sql[j] in "eE":
                k = j + 1
                if k < n and sql[k] in "+-":
                    k += 1
                if k < n and sql[k].isdigit():
                    isfloat = True
                    j = k
                    while j < n and sql[j].isdigit():
                        j += 1
            text = sql[i:j]
            adv(j - i)
            toks.append(Token(T.FLOAT if isfloat else T.INT, text, tl, tc))
            continue
        # identifiers / keywords
        if c.isalpha() or c == "_" or c == "$":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] in "_$"):
                j += 1
            text = sql[i:j]
            adv(j - i)
            toks.append(Token(T.IDENT, text, tl, tc))
            continue
        # operators
        if sql[i : i + 2] in _TWO_CHAR_OPS:
            toks.append(Token(T.OP, sql[i : i + 2], tl, tc))
            adv(2)
            continue
        if c in _ONE_CHAR_OPS:
            toks.append(Token(T.OP, c, tl, tc))
            adv()
            continue
        raise ParseError(f"unexpected character {c!r}", line, col)
    toks.append(Token(T.EOF, "", line, col))
    return toks
