"""Recursive-descent SQL parser (MySQL dialect subset).

Reference: external pingcap/parser (yacc).  Hand-rolled here; covers the
statement surface the planner/executor implement — the full TPC-H/SSB query
shapes plus DDL/DML/txn/utility statements (see SURVEY.md Appendix A).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..errors import ParseError
from . import ast
from .lexer import T, Token, tokenize

_INTERVAL_UNITS = {
    "microsecond", "second", "minute", "hour", "day", "week",
    "month", "quarter", "year",
}

_TYPE_ALIASES = {
    "int": "bigint", "integer": "bigint", "bigint": "bigint",
    "smallint": "bigint", "tinyint": "bigint", "mediumint": "bigint",
    "bool": "bigint", "boolean": "bigint",
    "float": "double", "double": "double", "real": "double",
    "decimal": "decimal", "numeric": "decimal", "dec": "decimal",
    "varchar": "varchar", "char": "varchar", "text": "varchar",
    "tinytext": "varchar", "mediumtext": "varchar", "longtext": "varchar",
    "blob": "varchar", "string": "varchar",
    "date": "date", "datetime": "datetime", "timestamp": "datetime",
    "time": "time", "year": "bigint",
    "enum": "enum", "set": "set", "bit": "bit", "json": "json",
}


class Parser:
    def __init__(self, sql: str):
        self.sql = sql
        self.toks = tokenize(sql)
        self.pos = 0
        self.n_params = 0

    # ---- token helpers -------------------------------------------------
    def peek(self, k: int = 0) -> Token:
        i = min(self.pos + k, len(self.toks) - 1)
        return self.toks[i]

    def next(self) -> Token:
        t = self.toks[self.pos]
        if t.kind != T.EOF:
            self.pos += 1
        return t

    def at_kw(self, *kws: str) -> bool:
        t = self.peek()
        return t.kind == T.IDENT and t.value.lower() in kws

    def accept_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.next()
            return True
        return False

    def expect_kw(self, kw: str):
        t = self.peek()
        if t.kind == T.IDENT and t.value.lower() == kw:
            self.next()
            return
        raise ParseError(f"expected {kw.upper()}, got {t.value!r}", t.line, t.col)

    def at_op(self, *ops: str) -> bool:
        t = self.peek()
        return t.kind == T.OP and t.value in ops

    def accept_op(self, *ops: str) -> bool:
        if self.at_op(*ops):
            self.next()
            return True
        return False

    def expect_op(self, op: str):
        t = self.peek()
        if t.kind == T.OP and t.value == op:
            self.next()
            return
        raise ParseError(f"expected {op!r}, got {t.value!r}", t.line, t.col)

    def ident(self, what: str = "identifier") -> str:
        t = self.peek()
        if t.kind in (T.IDENT, T.QIDENT):
            self.next()
            return t.value
        raise ParseError(f"expected {what}, got {t.value!r}", t.line, t.col)

    # ---- entry ---------------------------------------------------------
    def parse_statements(self) -> List[ast.Stmt]:
        stmts = []
        while self.peek().kind != T.EOF:
            if self.accept_op(";"):
                continue
            stmts.append(self.parse_statement())
            if self.peek().kind != T.EOF:
                self.expect_op(";")
        return stmts

    def parse_statement(self) -> ast.Stmt:
        t = self.peek()
        if t.kind != T.IDENT and not (t.kind == T.OP and t.value == "("):
            raise ParseError(f"unexpected {t.value!r}", t.line, t.col)
        kw = t.value.lower() if t.kind == T.IDENT else "("
        if kw in ("select", "("):
            return self.parse_select_or_union()
        method = getattr(self, f"_parse_{kw}", None)
        if method is None:
            raise ParseError(f"unsupported statement {t.value!r}", t.line, t.col)
        return method()

    # ---- SELECT ---------------------------------------------------------
    def parse_select_or_union(self) -> ast.Stmt:
        first = self.parse_select_core()
        selects = [first]
        all_flags = []
        while self.at_kw("union"):
            self.next()
            all_flags.append(self.accept_kw("all"))
            if not self.accept_kw("distinct"):
                pass
            selects.append(self.parse_select_core())
        if len(selects) == 1:
            sel = selects[0]
            # trailing ORDER BY / LIMIT may already be attached
            return sel
        # MySQL: mixed UNION/UNION ALL — distinct wins overall if any plain UNION
        union = ast.UnionStmt(selects=selects, all=bool(all_flags) and all(all_flags))
        # Trailing ORDER BY / LIMIT parsed into the last branch apply to the
        # whole union (MySQL grammar).
        last = selects[-1]
        if last.order_by and not union.order_by:
            union.order_by, last.order_by = last.order_by, []
        if last.limit is not None:
            union.limit, union.offset = last.limit, last.offset
            last.limit, last.offset = None, 0
        if self.accept_kw("order"):
            self.expect_kw("by")
            union.order_by = self.parse_order_items()
        if self.accept_kw("limit"):
            union.limit, union.offset = self.parse_limit_tail()
        return union

    def parse_select_core(self) -> ast.SelectStmt:
        # allow parenthesized select
        if self.accept_op("("):
            sel = self.parse_select_or_union()
            self.expect_op(")")
            if not isinstance(sel, ast.SelectStmt):
                raise ParseError("nested UNION in parentheses unsupported here")
            return sel
        self.expect_kw("select")
        stmt = ast.SelectStmt(fields=[])
        stmt.distinct = self.accept_kw("distinct")
        self.accept_kw("all")
        # fields
        stmt.fields.append(self.parse_select_field())
        while self.accept_op(","):
            stmt.fields.append(self.parse_select_field())
        if self.accept_kw("from"):
            stmt.from_clause = self.parse_table_refs()
        if self.accept_kw("where"):
            stmt.where = self.parse_expr()
        if self.accept_kw("group"):
            self.expect_kw("by")
            stmt.group_by.append(self.parse_expr())
            while self.accept_op(","):
                stmt.group_by.append(self.parse_expr())
        if self.accept_kw("having"):
            stmt.having = self.parse_expr()
        if self.accept_kw("order"):
            self.expect_kw("by")
            stmt.order_by = self.parse_order_items()
        if self.accept_kw("limit"):
            stmt.limit, stmt.offset = self.parse_limit_tail()
        if self.accept_kw("for"):
            self.expect_kw("update")
            stmt.for_update = True
        return stmt

    def parse_select_field(self) -> ast.SelectField:
        if self.at_op("*"):
            self.next()
            return ast.SelectField(ast.Star())
        # t.* / db.t.*
        if self.peek().kind in (T.IDENT, T.QIDENT):
            save = self.pos
            name1 = self.ident()
            if self.at_op("."):
                if self.peek(1).kind == T.OP and self.peek(1).value == "*":
                    self.next()
                    self.next()
                    return ast.SelectField(ast.Star(table=name1))
            self.pos = save
        expr = self.parse_expr()
        alias = ""
        if self.accept_kw("as"):
            alias = self.ident("alias")
        elif self.peek().kind in (T.IDENT, T.QIDENT) and not self.at_kw(
            "from", "where", "group", "having", "order", "limit", "union", "for",
            "inner", "left", "right", "join", "cross", "on", "using", "into",
        ):
            alias = self.ident()
        return ast.SelectField(expr, alias)

    def parse_order_items(self) -> List[ast.OrderItem]:
        items = [self.parse_order_item()]
        while self.accept_op(","):
            items.append(self.parse_order_item())
        return items

    def parse_order_item(self) -> ast.OrderItem:
        e = self.parse_expr()
        desc = False
        if self.accept_kw("desc"):
            desc = True
        else:
            self.accept_kw("asc")
        return ast.OrderItem(e, desc)

    def parse_limit_tail(self) -> Tuple[int, int]:
        t = self.peek()
        if t.kind != T.INT:
            raise ParseError("LIMIT expects integer", t.line, t.col)
        self.next()
        a = int(t.value)
        if self.accept_op(","):
            t2 = self.next()
            return int(t2.value), a  # LIMIT offset, count
        if self.accept_kw("offset"):
            t2 = self.next()
            return a, int(t2.value)
        return a, 0

    # ---- table refs ------------------------------------------------------
    def parse_table_refs(self):
        left = self.parse_table_ref()
        while True:
            if self.accept_op(","):
                right = self.parse_table_ref()
                left = ast.Join("cross", left, right)
            elif self.at_kw("join", "inner", "cross", "left", "right", "straight_join"):
                kind = "inner"
                if self.accept_kw("left"):
                    kind = "left"
                    self.accept_kw("outer")
                elif self.accept_kw("right"):
                    kind = "right"
                    self.accept_kw("outer")
                elif self.accept_kw("cross"):
                    kind = "cross"
                elif self.accept_kw("inner"):
                    kind = "inner"
                elif self.accept_kw("straight_join"):
                    kind = "inner"
                self.accept_kw("join")
                right = self.parse_table_ref()
                join = ast.Join(kind, left, right)
                if self.accept_kw("on"):
                    join.on = self.parse_expr()
                elif self.accept_kw("using"):
                    self.expect_op("(")
                    join.using.append(self.ident())
                    while self.accept_op(","):
                        join.using.append(self.ident())
                    self.expect_op(")")
                left = join
            else:
                return left

    def parse_table_ref(self):
        if self.accept_op("("):
            if self.at_kw("select"):
                q = self.parse_select_or_union()
                self.expect_op(")")
                self.accept_kw("as")
                alias = self.ident("subquery alias")
                return ast.SubqueryRef(q, alias)
            refs = self.parse_table_refs()
            self.expect_op(")")
            return refs
        db = ""
        name = self.ident("table name")
        if self.accept_op("."):
            db, name = name, self.ident("table name")
        alias = ""
        if self.accept_kw("as"):
            alias = self.ident("alias")
        elif self.peek().kind in (T.IDENT, T.QIDENT) and not self.at_kw(
            "where", "group", "having", "order", "limit", "union", "for", "on",
            "inner", "left", "right", "join", "cross", "using", "set", "straight_join",
        ):
            alias = self.ident()
        return ast.TableName(name, db, alias)

    # ---- expressions (precedence climbing) ------------------------------
    def parse_expr(self) -> ast.Expr:
        return self.parse_or()

    def parse_or(self) -> ast.Expr:
        left = self.parse_xor()
        while self.at_kw("or") or self.at_op("||"):
            self.next()
            left = ast.BinaryOp("or", left, self.parse_xor())
        return left

    def parse_xor(self) -> ast.Expr:
        left = self.parse_and()
        while self.at_kw("xor"):
            self.next()
            left = ast.BinaryOp("xor", left, self.parse_and())
        return left

    def parse_and(self) -> ast.Expr:
        left = self.parse_not()
        while self.at_kw("and") or self.at_op("&&"):
            self.next()
            left = ast.BinaryOp("and", left, self.parse_not())
        return left

    def parse_not(self) -> ast.Expr:
        if self.accept_kw("not") or self.accept_op("!"):
            return ast.UnaryOp("not", self.parse_not())
        return self.parse_predicate()

    def parse_predicate(self) -> ast.Expr:
        left = self.parse_bitor()
        while True:
            if self.at_op("=", "<", ">", "<=", ">=", "<>", "!="):
                op = self.next().value
                if op == "<>":
                    op = "!="
                left = ast.BinaryOp(op, left, self.parse_bitor())
                continue
            if self.at_kw("is"):
                self.next()
                negated = self.accept_kw("not")
                if self.accept_kw("null"):
                    left = ast.BinaryOp("is not" if negated else "is", left,
                                        ast.Literal(None))
                elif self.accept_kw("true"):
                    left = ast.BinaryOp("is not" if negated else "is", left,
                                        ast.Literal(True))
                elif self.accept_kw("false"):
                    left = ast.BinaryOp("is not" if negated else "is", left,
                                        ast.Literal(False))
                else:
                    t = self.peek()
                    raise ParseError("expected NULL/TRUE/FALSE after IS", t.line, t.col)
                continue
            negated = False
            save = self.pos
            if self.accept_kw("not"):
                negated = True
            if self.accept_kw("in"):
                self.expect_op("(")
                if self.at_kw("select"):
                    q = self.parse_select_or_union()
                    self.expect_op(")")
                    left = ast.InSubquery(left, q, negated)
                else:
                    items = [self.parse_expr()]
                    while self.accept_op(","):
                        items.append(self.parse_expr())
                    self.expect_op(")")
                    left = ast.InList(left, items, negated)
                continue
            if self.accept_kw("like"):
                left = ast.BinaryOp("not like" if negated else "like",
                                    left, self.parse_bitor())
                continue
            if self.accept_kw("between"):
                low = self.parse_bitor()
                self.expect_kw("and")
                high = self.parse_bitor()
                left = ast.Between(left, low, high, negated)
                continue
            if negated:
                self.pos = save
            return left

    def parse_bitor(self) -> ast.Expr:
        left = self.parse_bitand()
        while self.at_op("|"):
            self.next()
            left = ast.BinaryOp("|", left, self.parse_bitand())
        return left

    def parse_bitand(self) -> ast.Expr:
        left = self.parse_shift()
        while self.at_op("&"):
            self.next()
            left = ast.BinaryOp("&", left, self.parse_shift())
        return left

    def parse_shift(self) -> ast.Expr:
        left = self.parse_additive()
        while self.at_op("<<", ">>"):
            op = self.next().value
            left = ast.BinaryOp(op, left, self.parse_additive())
        return left

    def parse_additive(self) -> ast.Expr:
        left = self.parse_multiplicative()
        while self.at_op("+", "-"):
            op = self.next().value
            # date + INTERVAL n unit
            right = self.parse_multiplicative()
            left = ast.BinaryOp(op, left, right)
        return left

    def parse_multiplicative(self) -> ast.Expr:
        left = self.parse_bitxor()
        while True:
            if self.at_op("*", "/", "%"):
                op = self.next().value
                left = ast.BinaryOp(op, left, self.parse_bitxor())
            elif self.at_kw("div"):
                self.next()
                left = ast.BinaryOp("div", left, self.parse_bitxor())
            elif self.at_kw("mod"):
                self.next()
                left = ast.BinaryOp("%", left, self.parse_bitxor())
            else:
                return left

    def parse_bitxor(self) -> ast.Expr:
        left = self.parse_unary()
        while self.at_op("^"):
            self.next()
            left = ast.BinaryOp("^", left, self.parse_unary())
        return left

    def parse_unary(self) -> ast.Expr:
        if self.at_op("-"):
            self.next()
            return ast.UnaryOp("-", self.parse_unary())
        if self.at_op("+"):
            self.next()
            return self.parse_unary()
        if self.at_op("~"):
            self.next()
            return ast.UnaryOp("~", self.parse_unary())
        return self.parse_primary()

    def parse_primary(self) -> ast.Expr:
        t = self.peek()
        if t.kind == T.INT:
            self.next()
            return ast.Literal(int(t.value))
        if t.kind == T.FLOAT:
            self.next()
            if "e" in t.value or "E" in t.value:
                return ast.Literal(float(t.value))
            # MySQL: a numeric literal with a decimal point and no exponent
            # is a DECIMAL, not a DOUBLE (exact comparisons against decimal
            # columns depend on this; parser repo analog: ast.NewDecimal)
            return ast.Literal(t.value, type_hint="decimal")
        if t.kind == T.STRING:
            self.next()
            return ast.Literal(t.value)
        if t.kind == T.OP and t.value == "(":
            self.next()
            if self.at_kw("select"):
                q = self.parse_select_or_union()
                self.expect_op(")")
                return ast.ScalarSubquery(q)
            e = self.parse_expr()
            if self.at_op(","):
                # row expression (a, b) — only supported in IN; model as FuncCall
                items = [e]
                while self.accept_op(","):
                    items.append(self.parse_expr())
                self.expect_op(")")
                return ast.FuncCall("row", items)
            self.expect_op(")")
            return e
        if t.kind == T.OP and t.value == "?":
            self.next()
            p = ast.Param(self.n_params)
            self.n_params += 1
            return p
        if t.kind == T.OP and t.value == "@":
            self.next()
            if self.accept_op("@"):
                is_global = self.accept_kw("global")
                if is_global:
                    self.expect_op(".")
                else:
                    if self.accept_kw("session"):
                        self.expect_op(".")
                return ast.Variable(self.ident("variable"), is_global, True)
            return ast.Variable(self.ident("variable"), False, False)
        if t.kind == T.QIDENT:
            return self._parse_ident_expr()
        if t.kind == T.IDENT:
            kw = t.value.lower()
            if kw == "null":
                self.next()
                return ast.Literal(None)
            if kw == "true":
                self.next()
                return ast.Literal(True)
            if kw == "false":
                self.next()
                return ast.Literal(False)
            if kw == "case":
                return self._parse_case()
            if kw == "cast":
                return self._parse_cast()
            if kw == "exists":
                self.next()
                self.expect_op("(")
                q = self.parse_select_or_union()
                self.expect_op(")")
                return ast.Exists(q)
            if kw == "interval":
                self.next()
                v = self.parse_additive()
                unit = self.ident("interval unit").lower()
                if unit not in _INTERVAL_UNITS:
                    raise ParseError(f"bad interval unit {unit!r}", t.line, t.col)
                return ast.Interval(v, unit)
            if kw in ("date", "time", "timestamp") and self.peek(1).kind == T.STRING:
                self.next()
                s = self.next().value
                return ast.Literal(s, "datetime" if kw == "timestamp" else kw)
            if kw == "not":
                self.next()
                return ast.UnaryOp("not", self.parse_not())
            if kw == "default" and not (
                self.peek(1).kind == T.OP and self.peek(1).value == "("
            ):
                self.next()
                return ast.Default()
            return self._parse_ident_expr()
        raise ParseError(f"unexpected token {t.value!r} in expression", t.line, t.col)

    def _parse_ident_expr(self) -> ast.Expr:
        name1 = self.ident()
        # function call?
        if self.at_op("(") :
            return self._parse_func_call(name1)
        if self.accept_op("."):
            name2 = self.ident("column")
            if self.accept_op("."):
                name3 = self.ident("column")
                return ast.ColumnRef(name3, name2, name1)
            return ast.ColumnRef(name2, name1)
        return ast.ColumnRef(name1)

    def _parse_func_call(self, name: str) -> ast.Expr:
        name = name.lower()
        self.expect_op("(")
        distinct = False
        args: List[ast.Expr] = []
        if self.at_op("*") and name == "count":
            self.next()
            self.expect_op(")")
            return ast.FuncCall("count", [ast.Star()],
                                over=self._maybe_over())
        if self.accept_kw("distinct"):
            distinct = True
        if not self.at_op(")"):
            args.append(self.parse_expr())
            while self.accept_op(","):
                args.append(self.parse_expr())
        # EXTRACT(unit FROM x) style — not supported; substring(x FROM a FOR b):
        if name in ("substring", "substr") and self.accept_kw("from"):
            args.append(self.parse_expr())
            if self.accept_kw("for"):
                args.append(self.parse_expr())
        self.expect_op(")")
        return ast.FuncCall(name, args, distinct, over=self._maybe_over())

    def _maybe_over(self):
        """`OVER ([PARTITION BY ...] [ORDER BY ...] [ROWS|RANGE frame])`."""
        if not self.accept_kw("over"):
            return None
        self.expect_op("(")
        spec = ast.WindowSpec()
        if self.accept_kw("partition"):
            self.expect_kw("by")
            spec.partition_by.append(self.parse_expr())
            while self.accept_op(","):
                spec.partition_by.append(self.parse_expr())
        if self.accept_kw("order"):
            self.expect_kw("by")
            while True:
                e = self.parse_expr()
                desc = False
                if self.accept_kw("desc"):
                    desc = True
                else:
                    self.accept_kw("asc")
                spec.order_by.append(ast.OrderItem(e, desc))
                if not self.accept_op(","):
                    break
        if self.at_kw("rows") or self.at_kw("range"):
            spec.unit = self.next().value.lower()
            if self.accept_kw("between"):
                spec.start = self._frame_bound()
                self.expect_kw("and")
                spec.end = self._frame_bound()
            else:
                spec.start = self._frame_bound()
                spec.end = ast.FrameBound("current")
        self.expect_op(")")
        return spec

    def _frame_bound(self) -> "ast.FrameBound":
        if self.accept_kw("unbounded"):
            if self.accept_kw("preceding"):
                return ast.FrameBound("unbounded_preceding")
            self.expect_kw("following")
            return ast.FrameBound("unbounded_following")
        if self.accept_kw("current"):
            self.expect_kw("row")
            return ast.FrameBound("current")
        n = int(self.next().value)
        if self.accept_kw("preceding"):
            return ast.FrameBound("preceding", n)
        self.expect_kw("following")
        return ast.FrameBound("following", n)

    def _parse_case(self) -> ast.Expr:
        self.expect_kw("case")
        operand = None
        if not self.at_kw("when"):
            operand = self.parse_expr()
        branches = []
        while self.accept_kw("when"):
            cond = self.parse_expr()
            self.expect_kw("then")
            val = self.parse_expr()
            branches.append((cond, val))
        else_expr = None
        if self.accept_kw("else"):
            else_expr = self.parse_expr()
        self.expect_kw("end")
        return ast.CaseWhen(operand, branches, else_expr)

    def _parse_cast(self) -> ast.Expr:
        self.expect_kw("cast")
        self.expect_op("(")
        e = self.parse_expr()
        self.expect_kw("as")
        tname = self.ident("type").lower()
        prec = scale = 0
        if self.accept_op("("):
            prec = int(self.next().value)
            if self.accept_op(","):
                scale = int(self.next().value)
            self.expect_op(")")
        self.expect_op(")")
        return ast.Cast(e, tname, prec, scale)

    # ---- DDL -------------------------------------------------------------
    def _parse_create(self) -> ast.Stmt:
        self.expect_kw("create")
        if self.accept_kw("database", "schema"):
            ine = self._if_not_exists()
            return ast.CreateDatabaseStmt(self.ident("database"), ine)
        if self.accept_kw("unique"):
            self.expect_kw("index")
            return self._parse_create_index(unique=True)
        if self.accept_kw("index"):
            return self._parse_create_index(unique=False)
        if self.accept_kw("or"):
            self.expect_kw("replace")
            self.expect_kw("view")
            return self._parse_create_view(or_replace=True)
        if self.accept_kw("view"):
            return self._parse_create_view(or_replace=False)
        if self.accept_kw("user"):
            ine = self._if_not_exists()
            user = self._parse_user_name()
            password = ""
            if self.accept_kw("identified"):
                self.expect_kw("by")
                password = self.next().value
            return ast.CreateUserStmt(user, password, ine)
        if self.accept_kw("role"):
            ine = self._if_not_exists()
            roles = [self._parse_user_name()]
            while self.accept_op(","):
                roles.append(self._parse_user_name())
            return ast.CreateRoleStmt(roles, ine)
        if self.accept_kw("resource"):
            self.expect_kw("group")
            ine = self._if_not_exists()
            st = ast.ResourceGroupStmt(
                kind="create", name=self.ident("resource group"),
                if_not_exists=ine)
            self._parse_resgroup_options(st)
            return st
        self.expect_kw("table")
        ine = self._if_not_exists()
        table = self._parse_table_name()
        if self.at_kw("like"):
            self.next()
            src = self._parse_table_name()
            return ast.CreateTableStmt(table, [], [], ine)  # LIKE: resolved in exec
        self.expect_op("(")
        cols: List[ast.ColumnDef] = []
        indexes: List[ast.IndexDef] = []
        fks: List[ast.FkDef] = []
        while True:
            if self.at_kw("primary"):
                self.next()
                self.expect_kw("key")
                self.expect_op("(")
                names = [self.ident()]
                while self.accept_op(","):
                    names.append(self.ident())
                self.expect_op(")")
                indexes.append(ast.IndexDef("primary", names, True, True))
            elif self.at_kw("unique"):
                self.next()
                self.accept_kw("key") or self.accept_kw("index")
                idx_name = ""
                if self.peek().kind in (T.IDENT, T.QIDENT) and not self.at_op("("):
                    idx_name = self.ident()
                self.expect_op("(")
                names = [self.ident()]
                while self.accept_op(","):
                    names.append(self.ident())
                self.expect_op(")")
                indexes.append(ast.IndexDef(idx_name or f"uk_{names[0]}", names, True))
            elif self.at_kw("key", "index"):
                self.next()
                idx_name = ""
                if self.peek().kind in (T.IDENT, T.QIDENT) and not self.at_op("("):
                    idx_name = self.ident()
                self.expect_op("(")
                names = [self.ident()]
                while self.accept_op(","):
                    names.append(self.ident())
                self.expect_op(")")
                indexes.append(ast.IndexDef(idx_name or f"idx_{names[0]}", names))
            elif self.at_kw("foreign", "constraint"):
                cname = ""
                if self.accept_kw("constraint"):
                    if not self.at_kw("foreign"):
                        cname = self.ident("constraint")
                if self.at_kw("foreign"):
                    fks.append(self._parse_fk_tail(cname))
                else:
                    # CHECK / other constraint kinds: skipped (unenforced)
                    self._skip_balanced_until_comma()
            elif self.at_kw("check"):
                self._skip_balanced_until_comma()
            else:
                cols.append(self._parse_column_def())
            if not self.accept_op(","):
                break
        self.expect_op(")")
        # swallow table options (ENGINE=..., CHARSET=..., etc.)
        while (self.peek().kind == T.IDENT and not self.at_op(";")
               and not self.at_kw("partition")):
            self.next()
            if self.accept_op("="):
                self.next()
        part = None
        if self.accept_kw("partition"):
            self.expect_kw("by")
            part = self._parse_partition_by()
        return ast.CreateTableStmt(table, cols, indexes, ine, part, fks)

    def _parse_fk_tail(self, cname: str = "") -> "ast.FkDef":
        """FOREIGN KEY [name] (cols) REFERENCES tbl (cols) [ON ...]."""
        self.expect_kw("foreign")
        self.expect_kw("key")
        name = cname
        if self.peek().kind in (T.IDENT, T.QIDENT) and not self.at_op("("):
            name = self.ident("fk name")
        self.expect_op("(")
        cols = [self.ident()]
        while self.accept_op(","):
            cols.append(self.ident())
        self.expect_op(")")
        self.expect_kw("references")
        ref = self._parse_table_name()
        self.expect_op("(")
        rcols = [self.ident()]
        while self.accept_op(","):
            rcols.append(self.ident())
        self.expect_op(")")
        # referential actions parse and are recorded as unenforced
        while self.accept_kw("on"):
            self.next()  # delete | update
            if self.accept_kw("set"):
                self.next()  # null | default
            elif self.accept_kw("no"):
                self.next()  # action
            else:
                self.next()  # cascade | restrict
        return ast.FkDef(name or f"fk_{cols[0]}", cols, ref, rcols)

    def _parse_partition_by(self) -> "ast.PartitionByAst":
        """PARTITION BY RANGE (col) (PARTITION p VALUES LESS THAN (n)|
        MAXVALUE, ...) | PARTITION BY HASH (col) PARTITIONS n"""
        if self.accept_kw("hash"):
            self.expect_op("(")
            col = self.ident("column")
            self.expect_op(")")
            self.expect_kw("partitions")
            n = int(self.next().value)
            if n < 1:
                t = self.peek()
                raise ParseError("PARTITIONS must be >= 1", t.line, t.col)
            return ast.PartitionByAst("hash", col, num=n)
        self.expect_kw("range")
        self.expect_op("(")
        col = self.ident("column")
        self.expect_op(")")
        self.expect_op("(")
        defs = self._parse_partition_defs()
        self.expect_op(")")
        return ast.PartitionByAst("range", col, defs)

    def _parse_partition_defs(self) -> List["ast.PartitionDefAst"]:
        """PARTITION p VALUES LESS THAN (n)|MAXVALUE [, ...] — shared by
        CREATE TABLE ... PARTITION BY RANGE and ALTER ... ADD PARTITION."""
        defs: List[ast.PartitionDefAst] = []
        while True:
            self.expect_kw("partition")
            name = self.ident("partition")
            self.expect_kw("values")
            self.expect_kw("less")
            self.expect_kw("than")
            if self.accept_kw("maxvalue"):
                defs.append(ast.PartitionDefAst(name, None))
            else:
                self.expect_op("(")
                neg = bool(self.accept_op("-"))
                v = int(self.next().value)
                self.expect_op(")")
                defs.append(ast.PartitionDefAst(name, -v if neg else v))
            if not self.accept_op(","):
                break
        return defs

    def _skip_balanced_until_comma(self):
        depth = 0
        while True:
            t = self.peek()
            if t.kind == T.EOF:
                return
            if t.kind == T.OP:
                if t.value == "(":
                    depth += 1
                elif t.value == ")":
                    if depth == 0:
                        return
                    depth -= 1
                elif t.value == "," and depth == 0:
                    return
            self.next()

    def _parse_column_def(self) -> ast.ColumnDef:
        name = self.ident("column name")
        tname_raw = self.ident("type").lower()
        tname = _TYPE_ALIASES.get(tname_raw)
        if tname is None:
            raise ParseError(f"unsupported column type {tname_raw!r}")
        prec = scale = 0
        elems: List[str] = []
        if tname in ("enum", "set"):
            self.expect_op("(")
            elems.append(str(self.next().value))
            while self.accept_op(","):
                elems.append(str(self.next().value))
            self.expect_op(")")
        elif self.accept_op("("):
            prec = int(self.next().value)
            if self.accept_op(","):
                scale = int(self.next().value)
            self.expect_op(")")
        col = ast.ColumnDef(name, tname, prec, scale, elems=elems)
        # unsigned marker folds into bigint
        while True:
            if self.accept_kw("unsigned", "signed", "zerofill"):
                continue
            if self.accept_kw("character"):
                self.expect_kw("set")
                self.ident()
                continue
            if self.accept_kw("collate"):
                self.ident()
                continue
            if self.at_kw("not"):
                self.next()
                self.expect_kw("null")
                col.not_null = True
                continue
            if self.accept_kw("null"):
                continue
            if self.accept_kw("default"):
                col.default = self.parse_unary() if not self.at_kw("null") else (
                    self.next() and ast.Literal(None)
                )
                continue
            if self.at_kw("primary"):
                self.next()
                self.expect_kw("key")
                col.primary_key = True
                col.not_null = True
                continue
            if self.accept_kw("unique"):
                self.accept_kw("key")
                col.unique = True
                continue
            if self.accept_kw("auto_increment"):
                col.auto_increment = True
                continue
            if self.accept_kw("comment"):
                self.next()
                continue
            break
        return col

    def _parse_create_index(self, unique: bool) -> ast.CreateIndexStmt:
        name = self.ident("index name")
        self.expect_kw("on")
        table = self._parse_table_name()
        self.expect_op("(")
        cols = [self.ident()]
        while self.accept_op(","):
            cols.append(self.ident())
        self.expect_op(")")
        return ast.CreateIndexStmt(name, table, cols, unique)

    def _parse_create_view(self, or_replace: bool) -> ast.CreateViewStmt:
        name = self._parse_table_name()
        self.expect_kw("as")
        q = self.parse_select_or_union()
        return ast.CreateViewStmt(name, q, or_replace)

    def _parse_table_name(self) -> ast.TableName:
        db = ""
        name = self.ident("table name")
        if self.accept_op("."):
            db, name = name, self.ident("table name")
        return ast.TableName(name, db)

    def _parse_user_name(self) -> str:
        t = self.peek()
        if t.kind in (T.IDENT, T.QIDENT, T.STRING):
            self.next()
            user = t.value
        else:
            raise ParseError("expected user name", t.line, t.col)
        if self.accept_op("@"):
            t2 = self.next()
            user = f"{user}@{t2.value}"
        return user

    def _if_not_exists(self) -> bool:
        if self.at_kw("if"):
            self.next()
            self.expect_kw("not")
            self.expect_kw("exists")
            return True
        return False

    def _if_exists(self) -> bool:
        if self.at_kw("if"):
            self.next()
            self.expect_kw("exists")
            return True
        return False

    def _parse_drop(self) -> ast.Stmt:
        self.expect_kw("drop")
        if self.accept_kw("database", "schema"):
            ie = self._if_exists()
            return ast.DropDatabaseStmt(self.ident("database"), ie)
        if self.accept_kw("index"):
            name = self.ident("index name")
            self.expect_kw("on")
            return ast.DropIndexStmt(name, self._parse_table_name())
        if self.accept_kw("stats"):
            return ast.DropStatsStmt(self._parse_table_name())
        if self.accept_kw("user"):
            ie = self._if_exists()
            return ast.DropUserStmt(self._parse_user_name(), ie)
        if self.accept_kw("role"):
            ie = self._if_exists()
            roles = [self._parse_user_name()]
            while self.accept_op(","):
                roles.append(self._parse_user_name())
            return ast.DropRoleStmt(roles, ie)
        if self.accept_kw("resource"):
            self.expect_kw("group")
            ie = self._if_exists()
            return ast.ResourceGroupStmt(
                kind="drop", name=self.ident("resource group"),
                if_exists=ie)
        is_view = bool(self.accept_kw("view"))
        if not is_view:
            self.expect_kw("table")
        ie = self._if_exists()
        tables = [self._parse_table_name()]
        while self.accept_op(","):
            tables.append(self._parse_table_name())
        return ast.DropTableStmt(tables, ie, is_view)

    def _parse_truncate(self) -> ast.Stmt:
        self.expect_kw("truncate")
        self.accept_kw("table")
        return ast.TruncateTableStmt(self._parse_table_name())

    def _parse_rename(self) -> ast.Stmt:
        self.expect_kw("rename")
        self.expect_kw("table")
        old = self._parse_table_name()
        self.expect_kw("to")
        return ast.RenameTableStmt(old, self._parse_table_name())

    def _parse_resgroup_options(self, st: "ast.ResourceGroupStmt"):
        """RU_PER_SEC = n | BURSTABLE [= TRUE|FALSE] |
        PRIORITY = n | QUERY_LIMIT = n |
        QUERY_LIMIT = (EXEC_ELAPSED = n), in any order, optionally
        comma-separated (TiDB resource-control grammar, with the limit
        in device-milliseconds and the priority a weighted-fair
        admission weight)."""
        while True:
            if self.accept_kw("ru_per_sec"):
                self.accept_op("=")
                st.ru_per_sec = int(self.next().value)
            elif self.accept_kw("priority"):
                self.accept_op("=")
                st.priority = int(self.next().value)
            elif self.accept_kw("burstable"):
                if self.accept_op("="):
                    st.burstable = self.next().value.lower() in (
                        "true", "1")
                else:
                    st.burstable = True
            elif self.accept_kw("query_limit"):
                self.accept_op("=")
                if self.accept_op("("):
                    self.expect_kw("exec_elapsed")
                    self.expect_op("=")
                    st.query_limit_ms = int(self.next().value)
                    self.expect_op(")")
                else:
                    st.query_limit_ms = int(self.next().value)
            else:
                break
            self.accept_op(",")

    def _parse_alter(self) -> ast.Stmt:
        self.expect_kw("alter")
        if self.accept_kw("resource"):
            self.expect_kw("group")
            st = ast.ResourceGroupStmt(
                kind="alter", name=self.ident("resource group"))
            self._parse_resgroup_options(st)
            return st
        if self.accept_kw("user"):
            user = self._parse_user_name()
            self.expect_kw("resource")
            self.expect_kw("group")
            return ast.AlterUserResourceGroupStmt(
                user, self.ident("resource group"))
        self.expect_kw("table")
        table = self._parse_table_name()
        if self.accept_kw("add"):
            if self.accept_kw("partition"):
                # ALTER TABLE t ADD PARTITION PARTITIONS n        (HASH)
                # ALTER TABLE t ADD PARTITION (PARTITION p VALUES
                #   LESS THAN (v)|MAXVALUE, ...)                  (RANGE)
                if self.accept_kw("partitions"):
                    n = int(self.next().value)
                    return ast.AlterTableStmt(table, "add_partition",
                                              number=n)
                self.expect_op("(")
                defs = self._parse_partition_defs()
                self.expect_op(")")
                return ast.AlterTableStmt(table, "add_partition",
                                          part_defs=defs)
            if self.accept_kw("index", "key"):
                idx_name = ""
                if not self.at_op("("):
                    idx_name = self.ident()
                self.expect_op("(")
                cols = [self.ident()]
                while self.accept_op(","):
                    cols.append(self.ident())
                self.expect_op(")")
                return ast.AlterTableStmt(
                    table, "add_index",
                    index=ast.IndexDef(idx_name or f"idx_{cols[0]}", cols),
                )
            if self.accept_kw("unique"):
                self.accept_kw("index") or self.accept_kw("key")
                idx_name = ""
                if not self.at_op("("):
                    idx_name = self.ident()
                self.expect_op("(")
                cols = [self.ident()]
                while self.accept_op(","):
                    cols.append(self.ident())
                self.expect_op(")")
                return ast.AlterTableStmt(
                    table, "add_index",
                    index=ast.IndexDef(idx_name or f"uk_{cols[0]}", cols, True),
                )
            if self.at_kw("foreign", "constraint"):
                cname = ""
                if self.accept_kw("constraint"):
                    if not self.at_kw("foreign"):
                        cname = self.ident("constraint")
                return ast.AlterTableStmt(table, "add_fk",
                                          fk=self._parse_fk_tail(cname))
            self.accept_kw("column")
            return ast.AlterTableStmt(table, "add_column",
                                      column=self._parse_column_def())
        if self.accept_kw("drop"):
            if self.accept_kw("partition"):
                names = [self.ident("partition")]
                while self.accept_op(","):
                    names.append(self.ident("partition"))
                return ast.AlterTableStmt(table, "drop_partition",
                                          names=names)
            if self.accept_kw("foreign"):
                self.expect_kw("key")
                return ast.AlterTableStmt(table, "drop_fk",
                                          name=self.ident("fk name"))
            if self.accept_kw("index", "key"):
                return ast.AlterTableStmt(table, "drop_index", name=self.ident())
            self.accept_kw("column")
            return ast.AlterTableStmt(table, "drop_column", name=self.ident())
        if self.accept_kw("truncate"):
            self.expect_kw("partition")
            names = [self.ident("partition")]
            while self.accept_op(","):
                names.append(self.ident("partition"))
            return ast.AlterTableStmt(table, "truncate_partition",
                                      names=names)
        if self.accept_kw("coalesce"):
            self.expect_kw("partition")
            n = int(self.next().value)
            return ast.AlterTableStmt(table, "coalesce_partition", number=n)
        if self.accept_kw("modify"):
            self.accept_kw("column")
            return ast.AlterTableStmt(table, "modify_column",
                                      column=self._parse_column_def())
        if self.accept_kw("change"):
            # CHANGE [COLUMN] old_name new_def (rename + retype)
            self.accept_kw("column")
            old = self.ident("column")
            return ast.AlterTableStmt(table, "change_column", name=old,
                                      column=self._parse_column_def())
        if self.accept_kw("rename"):
            if self.accept_kw("index", "key"):
                old = self.ident("index")
                self.expect_kw("to")
                return ast.AlterTableStmt(table, "rename_index",
                                          names=[old, self.ident("index")])
            self.accept_kw("to") or self.accept_kw("as")
            return ast.AlterTableStmt(table, "rename",
                                      name=self._parse_table_name().name)
        if self.accept_kw("auto_increment"):
            self.accept_op("=")
            return ast.AlterTableStmt(table, "auto_increment",
                                      number=int(self.next().value))
        if self.accept_kw("comment"):
            self.accept_op("=")
            return ast.AlterTableStmt(table, "comment",
                                      name=str(self.next().value))
        t = self.peek()
        raise ParseError(f"unsupported ALTER TABLE action {t.value!r}", t.line, t.col)

    # ---- DML -------------------------------------------------------------
    def _parse_insert(self, replace: bool = False) -> ast.InsertStmt:
        self.next()  # insert | replace
        ignore = self.accept_kw("ignore")
        self.accept_kw("into")
        table = self._parse_table_name()
        columns: List[str] = []
        if self.accept_op("("):
            columns.append(self.ident())
            while self.accept_op(","):
                columns.append(self.ident())
            self.expect_op(")")
        stmt = ast.InsertStmt(table, columns, replace=replace, ignore=ignore)
        if self.at_kw("select"):
            stmt.query = self.parse_select_or_union()
        else:
            self.expect_kw("values") if self.at_kw("values") else self.expect_kw("value")
            while True:
                self.expect_op("(")
                row = [self.parse_expr()]
                while self.accept_op(","):
                    row.append(self.parse_expr())
                self.expect_op(")")
                stmt.values.append(row)
                if not self.accept_op(","):
                    break
        if self.accept_kw("on"):
            self.expect_kw("duplicate")
            self.expect_kw("key")
            self.expect_kw("update")
            while True:
                col = self.ident()
                self.expect_op("=")
                stmt.on_dup_update.append((col, self.parse_expr()))
                if not self.accept_op(","):
                    break
        return stmt

    def _parse_replace(self) -> ast.InsertStmt:
        return self._parse_insert(replace=True)

    def _parse_update(self) -> ast.UpdateStmt:
        self.expect_kw("update")
        table = self._parse_table_name()
        if self.peek().kind in (T.IDENT, T.QIDENT) and not self.at_kw("set"):
            table.alias = self.ident()
        self.expect_kw("set")
        assignments = []
        while True:
            col = self.ident("column")
            if self.accept_op("."):
                col = self.ident("column")
            self.expect_op("=")
            assignments.append((col, self.parse_expr()))
            if not self.accept_op(","):
                break
        stmt = ast.UpdateStmt(table, assignments)
        if self.accept_kw("where"):
            stmt.where = self.parse_expr()
        if self.accept_kw("order"):
            self.expect_kw("by")
            stmt.order_by = self.parse_order_items()
        if self.accept_kw("limit"):
            stmt.limit, _ = self.parse_limit_tail()
        return stmt

    def _parse_delete(self) -> ast.DeleteStmt:
        self.expect_kw("delete")
        self.expect_kw("from")
        table = self._parse_table_name()
        stmt = ast.DeleteStmt(table)
        if self.accept_kw("where"):
            stmt.where = self.parse_expr()
        if self.accept_kw("order"):
            self.expect_kw("by")
            stmt.order_by = self.parse_order_items()
        if self.accept_kw("limit"):
            stmt.limit, _ = self.parse_limit_tail()
        return stmt

    def _parse_load(self) -> ast.LoadDataStmt:
        self.expect_kw("load")
        self.expect_kw("data")
        self.accept_kw("local")
        self.expect_kw("infile")
        path = self.next().value
        self.expect_kw("into")
        self.expect_kw("table")
        table = self._parse_table_name()
        stmt = ast.LoadDataStmt(path, table)
        if self.accept_kw("fields"):
            self.expect_kw("terminated")
            self.expect_kw("by")
            stmt.fields_terminated = self.next().value
        if self.accept_kw("lines"):
            self.expect_kw("terminated")
            self.expect_kw("by")
            stmt.lines_terminated = self.next().value
        if self.accept_kw("ignore"):
            stmt.ignore_lines = int(self.next().value)
            self.accept_kw("lines") or self.accept_kw("rows")
        return stmt

    # ---- utility statements ---------------------------------------------
    def _parse_explain(self) -> ast.Stmt:
        self.expect_kw("explain")
        analyze = self.accept_kw("analyze")
        fmt = "row"
        if self.accept_kw("format"):
            self.expect_op("=")
            fmt = self.next().value.lower()
        return ast.ExplainStmt(self.parse_statement(), analyze, fmt)

    def _parse_desc(self) -> ast.Stmt:
        self.next()
        if self.at_kw("select", "insert", "update", "delete"):
            return ast.ExplainStmt(self.parse_statement())
        return ast.DescTableStmt(self._parse_table_name())

    _parse_describe = _parse_desc

    def _parse_trace(self) -> ast.Stmt:
        self.expect_kw("trace")
        fmt = "row"
        if self.accept_kw("format"):
            self.expect_op("=")
            t = self.next()
            fmt = str(t.value).lower()
            if fmt not in ("row", "json"):
                raise ParseError(f"unknown TRACE format {fmt!r}",
                                 t.line, t.col)
        return ast.TraceStmt(self.parse_statement(), fmt)

    def _parse_set(self) -> ast.Stmt:
        self.expect_kw("set")
        if self.accept_kw("role"):
            if self.accept_kw("none"):
                return ast.SetRoleStmt("none")
            if self.accept_kw("all"):
                return ast.SetRoleStmt("all")
            if self.accept_kw("default"):
                return ast.SetRoleStmt("default")
            roles = [self._parse_user_name()]
            while self.accept_op(","):
                roles.append(self._parse_user_name())
            return ast.SetRoleStmt("list", roles)
        if self.accept_kw("default"):
            self.expect_kw("role")
            mode, roles = "list", []
            if self.accept_kw("none"):
                mode = "none"
            elif self.accept_kw("all"):
                mode = "all"
            else:
                roles = [self._parse_user_name()]
                while self.accept_op(","):
                    roles.append(self._parse_user_name())
            self.expect_kw("to")
            users = [self._parse_user_name()]
            while self.accept_op(","):
                users.append(self._parse_user_name())
            return ast.SetDefaultRoleStmt(mode, roles, users)
        if self.accept_kw("password"):
            user = ""
            if self.accept_kw("for"):
                user = self._parse_user_name()
            self.expect_op("=")
            return ast.SetPasswordStmt(user, self.next().value)
        if self.at_kw("transaction"):
            # SET TRANSACTION ISOLATION LEVEL ... — accept & ignore
            while self.peek().kind != T.EOF and not self.at_op(";"):
                self.next()
            return ast.SetStmt([])
        assignments = []
        while True:
            is_global = False
            if self.accept_op("@"):
                if self.accept_op("@"):
                    if self.accept_kw("global"):
                        self.expect_op(".")
                        is_global = True
                    elif self.accept_kw("session"):
                        self.expect_op(".")
                name = self.ident("variable")
            else:
                if self.accept_kw("global"):
                    is_global = True
                else:
                    self.accept_kw("session")
                if self.accept_kw("names"):
                    self.next()  # charset name
                    if self.peek().kind != T.EOF and not self.at_op(";", ","):
                        pass
                    if not self.accept_op(","):
                        break
                    continue
                name = self.ident("variable")
            if not (self.accept_op("=") or self.accept_op(":=")):
                t = self.peek()
                raise ParseError("expected = in SET", t.line, t.col)
            assignments.append((name.lower(), is_global, self.parse_expr()))
            if not self.accept_op(","):
                break
        return ast.SetStmt(assignments)

    def _parse_show(self) -> ast.ShowStmt:
        self.expect_kw("show")
        full = self.accept_kw("full")
        is_global = self.accept_kw("global")
        self.accept_kw("session")
        stmt = ast.ShowStmt("", is_global=is_global, full=full)
        if self.accept_kw("databases", "schemas"):
            stmt.kind = "databases"
        elif self.accept_kw("tables"):
            stmt.kind = "tables"
            if self.accept_kw("from", "in"):
                stmt.db = self.ident()
        elif self.accept_kw("columns", "fields"):
            stmt.kind = "columns"
            self.expect_kw("from") if self.at_kw("from") else self.expect_kw("in")
            t = self._parse_table_name()
            stmt.target, stmt.db = t.name, t.db
            if self.accept_kw("from", "in"):
                stmt.db = self.ident()
        elif self.accept_kw("index", "indexes", "keys"):
            stmt.kind = "index"
            self.accept_kw("from") or self.accept_kw("in")
            t = self._parse_table_name()
            stmt.target, stmt.db = t.name, t.db
        elif self.accept_kw("create"):
            if self.accept_kw("table"):
                stmt.kind = "create_table"
                t = self._parse_table_name()
                stmt.target, stmt.db = t.name, t.db
            elif self.accept_kw("database"):
                stmt.kind = "create_database"
                stmt.target = self.ident()
        elif self.accept_kw("variables"):
            stmt.kind = "variables"
        elif self.accept_kw("status"):
            stmt.kind = "status"
        elif self.accept_kw("warnings"):
            stmt.kind = "warnings"
        elif self.accept_kw("errors"):
            stmt.kind = "errors"
        elif self.accept_kw("processlist"):
            stmt.kind = "processlist"
        elif self.accept_kw("engines"):
            stmt.kind = "engines"
        elif self.accept_kw("collation"):
            stmt.kind = "collation"
        elif self.accept_kw("charset"):
            stmt.kind = "charset"
        elif self.accept_kw("character"):
            self.expect_kw("set")
            stmt.kind = "charset"
        elif self.accept_kw("grants"):
            stmt.kind = "grants"
            if self.accept_kw("for"):
                stmt.target = self._parse_user_name()
        elif self.accept_kw("stats_meta"):
            stmt.kind = "stats_meta"
        elif self.accept_kw("stats_histograms"):
            stmt.kind = "stats_histograms"
        elif self.accept_kw("stats_buckets"):
            stmt.kind = "stats_buckets"
        elif self.accept_kw("stats_healthy"):
            stmt.kind = "stats_healthy"
        elif self.accept_kw("analyze"):
            self.expect_kw("status")
            stmt.kind = "analyze_status"
        elif self.accept_kw("table"):
            self.expect_kw("regions")
            stmt.kind = "regions"
            t = self._parse_table_name()
            stmt.target, stmt.db = t.name, t.db
        elif self.accept_kw("bindings"):
            stmt.kind = "bindings"
        else:
            t = self.peek()
            raise ParseError(f"unsupported SHOW {t.value!r}", t.line, t.col)
        if self.accept_kw("like"):
            stmt.like = self.next().value
        elif self.accept_kw("where"):
            stmt.where = self.parse_expr()
        return stmt

    def _parse_use(self) -> ast.UseStmt:
        self.expect_kw("use")
        return ast.UseStmt(self.ident("database"))

    def _parse_begin(self) -> ast.BeginStmt:
        self.expect_kw("begin")
        return ast.BeginStmt()

    def _parse_start(self) -> ast.BeginStmt:
        self.expect_kw("start")
        self.expect_kw("transaction")
        return ast.BeginStmt()

    def _parse_commit(self) -> ast.CommitStmt:
        self.expect_kw("commit")
        return ast.CommitStmt()

    def _parse_rollback(self) -> ast.RollbackStmt:
        self.expect_kw("rollback")
        return ast.RollbackStmt()

    def _parse_analyze(self) -> ast.AnalyzeTableStmt:
        self.expect_kw("analyze")
        self.expect_kw("table")
        tables = [self._parse_table_name()]
        while self.accept_op(","):
            tables.append(self._parse_table_name())
        return ast.AnalyzeTableStmt(tables)

    def _parse_prepare(self) -> ast.PrepareStmt:
        self.expect_kw("prepare")
        name = self.ident("statement name")
        self.expect_kw("from")
        return ast.PrepareStmt(name, self.next().value)

    def _parse_execute(self) -> ast.ExecuteStmt:
        self.expect_kw("execute")
        name = self.ident("statement name")
        using = []
        if self.accept_kw("using"):
            self.expect_op("@")
            using.append(self.ident())
            while self.accept_op(","):
                self.expect_op("@")
                using.append(self.ident())
        return ast.ExecuteStmt(name, using)

    def _parse_deallocate(self) -> ast.DeallocateStmt:
        self.expect_kw("deallocate")
        self.expect_kw("prepare")
        return ast.DeallocateStmt(self.ident())

    def _parse_kill(self) -> ast.KillStmt:
        self.expect_kw("kill")
        query_only = self.accept_kw("query")
        self.accept_kw("tidb") or self.accept_kw("connection")
        t = self.next()
        return ast.KillStmt(int(t.value), query_only)

    def _parse_admin(self) -> ast.AdminStmt:
        self.expect_kw("admin")
        if self.accept_kw("check"):
            self.expect_kw("table")
            tables = [self._parse_table_name()]
            while self.accept_op(","):
                tables.append(self._parse_table_name())
            return ast.AdminStmt("check_table", tables)
        if self.accept_kw("show"):
            if self.accept_kw("ddl"):
                if self.accept_kw("jobs"):
                    return ast.AdminStmt("show_ddl_jobs")
                return ast.AdminStmt("show_ddl")
            if self.accept_kw("slow"):
                while self.peek().kind != T.EOF and not self.at_op(";"):
                    self.next()
                return ast.AdminStmt("show_slow")
            # ADMIN SHOW t NEXT_ROW_ID
            tbl = self._parse_table_name()
            self.expect_kw("next_row_id")
            return ast.AdminStmt("show_next_row_id", [tbl])
        if self.accept_kw("checksum"):
            self.expect_kw("table")
            tables = [self._parse_table_name()]
            while self.accept_op(","):
                tables.append(self._parse_table_name())
            return ast.AdminStmt("checksum_table", tables)
        if self.accept_kw("recover"):
            self.expect_kw("index")
            tables = [self._parse_table_name()]
            name = self.ident("index name")
            return ast.AdminStmt("recover_index", tables, index=name)
        if self.accept_kw("cleanup"):
            self.expect_kw("index")
            tables = [self._parse_table_name()]
            name = self.ident("index name")
            return ast.AdminStmt("cleanup_index", tables, index=name)
        t = self.peek()
        raise ParseError(f"unsupported ADMIN {t.value!r}", t.line, t.col)

    def _parse_repair(self) -> "ast.RepairTableStmt":
        """REPAIR TABLE t — re-derive every index artifact and verify
        (util/admin.go RepairTable role for derived indexes)."""
        self.expect_kw("repair")
        self.expect_kw("table")
        return ast.RepairTableStmt(self._parse_table_name())

    def _parse_recover(self) -> "ast.RecoverTableStmt":
        """RECOVER TABLE t — flashback the most recently dropped `t` from
        the catalog's recycle bin (ddl_api.go:1457 RecoverTable role)."""
        self.expect_kw("recover")
        self.expect_kw("table")
        return ast.RecoverTableStmt(self._parse_table_name())

    def _parse_split(self) -> ast.SplitRegionStmt:
        self.expect_kw("split")
        self.expect_kw("table")
        table = self._parse_table_name()
        num = 0
        if self.accept_kw("between"):
            # SPLIT TABLE t BETWEEN (a) AND (b) REGIONS n
            self._skip_balanced_until_comma()
            if self.accept_kw("and"):
                self._skip_balanced_until_comma()
            if self.accept_kw("regions"):
                num = int(self.next().value)
        elif self.accept_kw("regions"):
            num = int(self.next().value)
        return ast.SplitRegionStmt(table, num)

    def _parse_priv_name(self) -> str:
        p = self.ident().lower()
        if p == "all" and self.accept_kw("privileges"):
            return "all"
        if p == "create" and self.accept_kw("user"):
            return "create user"
        if p == "create" and self.accept_kw("view"):
            return "create view"
        if p == "grant" and self.accept_kw("option"):
            return "grant option"
        return p

    def _role_form_ahead(self) -> bool:
        """After GRANT/REVOKE: the role form has TO/FROM before any ON —
        decided by lookahead so role names keep their case and quoting
        (privilege names lowercase; role names are identifiers)."""
        for k in range(self.pos, len(self.toks)):
            t = self.toks[k]
            if t.kind == T.IDENT:
                v = t.value.lower()
                if v == "on":
                    return False
                if v in ("to", "from"):
                    return True
            if t.kind == T.EOF:
                return False
        return False

    def _parse_grant(self) -> "ast.Stmt":
        self.expect_kw("grant")
        if self._role_form_ahead():
            # GRANT role[, role]... TO user[, user]... (no ON clause)
            roles = [self._parse_user_name()]
            while self.accept_op(","):
                roles.append(self._parse_user_name())
            self.expect_kw("to")
            users = [self._parse_user_name()]
            while self.accept_op(","):
                users.append(self._parse_user_name())
            return ast.GrantRoleStmt(roles, users)
        privs = [self._parse_priv_name()]
        while self.accept_op(","):
            privs.append(self._parse_priv_name())
        self.expect_kw("on")
        level = ""
        while not self.at_kw("to"):
            level += self.next().value
        self.expect_kw("to")
        return ast.GrantStmt(privs, level, self._parse_user_name())

    def _parse_revoke(self) -> "ast.Stmt":
        self.expect_kw("revoke")
        if self._role_form_ahead():
            roles = [self._parse_user_name()]
            while self.accept_op(","):
                roles.append(self._parse_user_name())
            self.expect_kw("from")
            users = [self._parse_user_name()]
            while self.accept_op(","):
                users.append(self._parse_user_name())
            return ast.RevokeRoleStmt(roles, users)
        privs = [self._parse_priv_name()]
        while self.accept_op(","):
            privs.append(self._parse_priv_name())
        self.expect_kw("on")
        level = ""
        while not self.at_kw("from"):
            level += self.next().value
        self.expect_kw("from")
        return ast.RevokeStmt(privs, level, self._parse_user_name())

    def _parse_lock(self) -> ast.LockTablesStmt:
        self.expect_kw("lock")
        self.accept_kw("tables", "table") or self.expect_kw("tables")
        items = []
        while True:
            tn = self._parse_table_name()
            if self.accept_kw("write"):
                mode = "write"
            else:
                self.expect_kw("read")
                self.accept_kw("local")
                mode = "read"
            items.append((tn, mode))
            if not self.accept_op(","):
                break
        return ast.LockTablesStmt(items)

    def _parse_unlock(self) -> ast.UnlockTablesStmt:
        self.expect_kw("unlock")
        self.accept_kw("tables", "table")
        return ast.UnlockTablesStmt()

    def _parse_flush(self) -> ast.FlushStmt:
        self.expect_kw("flush")
        what = self.ident("flush target").lower()
        return ast.FlushStmt(what)


def parse(sql: str) -> List[ast.Stmt]:
    return Parser(sql).parse_statements()


def parse_one(sql: str) -> ast.Stmt:
    stmts = parse(sql)
    if len(stmts) != 1:
        raise ParseError(f"expected one statement, got {len(stmts)}")
    return stmts[0]
