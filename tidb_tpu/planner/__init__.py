from .build import PlanBuilder
from .columns import Schema, SchemaCol, next_uid
from .optimizer import finish_plan, plan_statement
from .physical import PhysicalContext, PhysicalPlan, explain_text

__all__ = [
    "PlanBuilder", "Schema", "SchemaCol", "next_uid",
    "plan_statement", "finish_plan", "PhysicalContext", "PhysicalPlan",
    "explain_text",
]
