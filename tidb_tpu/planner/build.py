"""AST statement -> logical plan.

Reference: planner/core/planbuilder.go (PlanBuilder.Build) +
logical_plan_builder.go (buildSelect/buildJoin/buildAggregation) +
expression_rewriter.go (subquery rewrites to semi-joins).
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Callable, List, Optional, Tuple

import numpy as np

from ..catalog import InfoSchema, TableInfo
from ..chunk import Chunk, Column
from ..errors import PlanError, UnknownColumnError
from ..expr.aggregation import AGG_FUNCS, AggDesc
from ..expr.expression import ColumnExpr, Constant, Expression, ScalarFunc
from ..parser import ast
from ..types import merge_types, ty_int
from .columns import Schema, SchemaCol, next_uid
from .expr_build import (
    CorrelatedColumn,
    ExprBuilder,
    expr_uids as _expr_uids,
    fold_constant,
    literal_to_constant,
    split_and,
)
from .logical import (
    LogicalAggregation,
    LogicalDataSource,
    LogicalDual,
    LogicalJoin,
    LogicalLimit,
    LogicalMaxOneRow,
    LogicalPlan,
    LogicalProjection,
    LogicalSelection,
    LogicalSort,
    LogicalTopN,
    LogicalUnion,
)

DEFAULT_MARKER = object()  # DEFAULT keyword in INSERT values


# ---------------------------------------------------------------------------
# DML plan containers (root-task only; built into executors directly)
# ---------------------------------------------------------------------------


@dataclass
class InsertPlan:
    db: str
    table: TableInfo
    col_offsets: List[int]
    rows: Optional[List[list]] = None
    select_plan: Optional[LogicalPlan] = None
    replace: bool = False
    ignore: bool = False
    on_dup_update: List[Tuple[int, Expression]] = dc_field(default_factory=list)


@dataclass
class UpdatePlan:
    db: str
    table: TableInfo
    assignments: List[Tuple[int, Expression]]  # positions over full row
    conditions: List[Expression]  # positions over full row


@dataclass
class DeletePlan:
    db: str
    table: TableInfo
    conditions: List[Expression]


@dataclass
class LoadDataPlan:
    db: str
    table: TableInfo
    path: str
    fields_terminated: str
    ignore_lines: int


class PlanBuilder:
    def __init__(self, infoschema: InfoSchema, current_db: str = "",
                 exec_subplan: Optional[Callable] = None,
                 param_values: Optional[list] = None):
        self.infoschema = infoschema
        self.current_db = current_db
        self.exec_subplan = exec_subplan  # fn(LogicalPlan) -> List[tuple]
        self.param_values = param_values

    # ------------------------------------------------------------------
    def build(self, stmt: ast.Stmt):
        if isinstance(stmt, ast.SelectStmt):
            return self.build_select(stmt)
        if isinstance(stmt, ast.UnionStmt):
            return self.build_union(stmt)
        if isinstance(stmt, ast.InsertStmt):
            return self.build_insert(stmt)
        if isinstance(stmt, ast.UpdateStmt):
            return self.build_update(stmt)
        if isinstance(stmt, ast.DeleteStmt):
            return self.build_delete(stmt)
        if isinstance(stmt, ast.LoadDataStmt):
            t = self._table_info(stmt.table)
            return LoadDataPlan(
                stmt.table.db or self.current_db, t, stmt.path,
                stmt.fields_terminated, stmt.ignore_lines,
            )
        raise PlanError(f"no plan for {type(stmt).__name__}")

    # ------------------------------------------------------------------
    # FROM clause
    # ------------------------------------------------------------------
    def _table_info(self, tn: ast.TableName) -> TableInfo:
        db = tn.db or self.current_db
        if not db:
            raise PlanError("no database selected")
        return self.infoschema.table(db, tn.name)

    def build_from(self, node, outer: List[Schema]) -> LogicalPlan:
        if node is None:
            return LogicalDual(Schema([]), 1)
        if isinstance(node, ast.TableName):
            db = (node.db or self.current_db).lower()
            if db in ("information_schema", "performance_schema", "mysql"):
                from ..infoschema_tables import MEMTABLES
                from .logical import LogicalMemTable

                key = (f"mysql.{node.name.lower()}" if db == "mysql"
                       else node.name.lower())
                spec = MEMTABLES.get(key)
                if spec is None:
                    if db == "mysql":
                        pass  # ordinary user tables may live in `mysql`
                    else:
                        raise PlanError(
                            f"unknown memtable {db}.{node.name}"
                        )
                else:
                    cols, _provider = spec
                    alias = node.alias or node.name
                    sch = Schema([
                        SchemaCol(next_uid(), n, ft, alias, n, i)
                        for i, (n, ft) in enumerate(cols)
                    ])
                    return LogicalMemTable(key, sch)
            t = self._table_info(node)
            if t.is_view:
                sel = t.view_select
                if isinstance(sel, str):
                    from ..parser import parse_one

                    sel = parse_one(sel)
                sub = (self.build_union(sel, outer)
                       if isinstance(sel, ast.UnionStmt)
                       else self.build_select(sel, outer))
                alias = node.alias or node.name
                return _aliased(sub, alias)
            alias = node.alias or node.name
            cols = [
                SchemaCol(next_uid(), c.name, c.ftype, alias, c.name, c.offset)
                for c in t.public_columns()
            ]
            return LogicalDataSource(node.db or self.current_db, t, alias,
                                     Schema(cols))
        if isinstance(node, ast.SubqueryRef):
            sub = self.build_select(node.query, outer) \
                if isinstance(node.query, ast.SelectStmt) \
                else self.build_union(node.query, outer)
            return _aliased(sub, node.alias)
        if isinstance(node, ast.Join):
            return self.build_join(node, outer)
        raise PlanError(f"unsupported FROM node {type(node).__name__}")

    def build_join(self, node: ast.Join, outer: List[Schema]) -> LogicalPlan:
        left = self.build_from(node.left, outer)
        right = self.build_from(node.right, outer)
        kind = {"inner": "inner", "cross": "inner", "left": "left_outer",
                "right": "right_outer"}[node.kind]
        if kind == "right_outer":
            # normalize: RIGHT JOIN a b == LEFT JOIN b a; a projection
            # below restores the user-visible column order
            left, right = right, left
            kind = "left_outer"
        merged = Schema(
            left.schema.cols
            + ([_nullable(c) for c in right.schema.cols]
               if kind == "left_outer" else right.schema.cols)
        )
        eq, other = [], []
        conds: List[Expression] = []
        eb = ExprBuilder(merged, outer_schemas=outer,
                         param_values=self.param_values,
                         subquery_handler=self._mk_subquery_handler(merged, outer))
        if node.using:
            for name in node.using:
                lc = left.schema.resolve(name)
                rc = right.schema.resolve(name)
                eq.append((lc.to_expr(), rc.to_expr()))
        elif node.on is not None:
            for conj in split_and(node.on):
                conds.append(eb.build(conj))
        left_uids = set(left.schema.uids())
        right_uids = set(right.schema.uids())
        for c in conds:
            pair = _as_eq_key(c, left_uids, right_uids)
            if pair is not None:
                eq.append(pair)
            else:
                other.append(c)
        if node.kind == "right":
            # schema order: original left (now the null-extended right child)
            # first; a projection restores the user-visible column order
            out_schema = Schema(
                list(merged.cols[len(left.schema.cols):])
                + list(merged.cols[:len(left.schema.cols)])
            )
            j = LogicalJoin(left, right, kind, eq, other, merged)
            exprs = [c.to_expr() for c in out_schema.cols]
            return LogicalProjection(j, exprs, out_schema)
        return LogicalJoin(left, right, kind, eq, other, merged)

    # ------------------------------------------------------------------
    # subqueries (expression_rewriter.go handleInSubquery/buildSemiApply)
    # ------------------------------------------------------------------
    def _mk_subquery_handler(self, schema: Schema, outer: List[Schema],
                             plan_holder: Optional[list] = None):
        """plan_holder: 1-element mutable list with the plan being filtered;
        correlated scalar subqueries decorrelate by REPLACING it with a
        left-join against the grouped inner (rule_decorrelate.go analog)."""

        def handler(query, kind, negated, operand):
            if kind == "scalar":
                outer_uids = set(schema.uids())
                if plan_holder is not None and \
                        self._is_correlated_agg(query, schema, outer):
                    return self._decorrelate_scalar(
                        query, schema, outer, plan_holder
                    )
                sub = self.build_select(query, [schema] + outer)
                used = set()
                for node in _walk_exprs(sub):
                    node.collect_columns(used)
                if used & outer_uids:
                    raise PlanError(
                        "correlated scalar subquery of this shape is not "
                        "supported (only aggregated subqueries with "
                        "equality correlation decorrelate)"
                    )
                if len(sub.schema) != 1:
                    raise PlanError("scalar subquery must return one column")
                rows = self._eval_subplan(sub)
                if len(rows) > 1:
                    raise PlanError("subquery returns more than 1 row")
                v = rows[0][0] if rows else None
                ft = sub.schema.col(0).ftype.with_nullable(True)
                return Constant(v, ft)
            raise PlanError(
                "IN/EXISTS subquery allowed only as a top-level WHERE conjunct"
            )

        return handler

    def _is_correlated_agg(self, query, schema: Schema, outer) -> bool:
        from .decorrelate import is_correlated_agg

        return is_correlated_agg(self, query, schema, outer)

    def _decorrelate_scalar(self, query, schema: Schema, outer,
                            plan_holder):
        from .decorrelate import decorrelate_scalar

        return decorrelate_scalar(self, query, schema, outer, plan_holder)

    def _eval_subplan(self, plan: LogicalPlan) -> List[tuple]:
        if self.exec_subplan is None:
            raise PlanError("subquery execution not available in this context")
        return self.exec_subplan(plan)

    # ------------------------------------------------------------------
    # SELECT
    # ------------------------------------------------------------------
    def build_select(self, sel: ast.SelectStmt,
                     outer: Optional[List[Schema]] = None) -> LogicalPlan:
        outer = outer or []
        p = self.build_from(sel.from_clause, outer)
        from_schema = p.schema

        # ---- WHERE (with IN/EXISTS conjuncts becoming semi-joins) -----
        if sel.where is not None:
            p = self._build_filter(p, sel.where, outer)

        # ---- expand stars into field list -----------------------------
        fields: List[ast.SelectField] = []
        for f in sel.fields:
            if isinstance(f.expr, ast.Star):
                cols = (
                    [c for c in p.schema.cols
                     if not f.expr.table
                     or c.table.lower() == f.expr.table.lower()]
                )
                if not cols:
                    raise PlanError(f"bad *: {f.expr.table}")
                for c in cols:
                    ref = ast.ColumnRef(c.name, c.table)
                    fields.append(ast.SelectField(ref, c.display or c.name))
            else:
                fields.append(f)

        # ---- aggregate detection --------------------------------------
        has_agg = bool(sel.group_by) or any(
            _contains_agg(f.expr) for f in fields
        ) or (sel.having is not None and _contains_agg(sel.having)) or any(
            _contains_agg(it.expr) for it in sel.order_by
        )

        aggs: List[AggDesc] = []
        agg_uid_of: dict = {}
        windows: List[dict] = []

        def window_collector(name, args, partition, order, spec):
            from ..executor.window import WINDOW_FUNCS, window_ftype

            if name not in WINDOW_FUNCS:
                raise PlanError(f"unknown window function {name!r}")
            ft = window_ftype(name, args)
            uid = next_uid()
            windows.append({
                "uid": uid, "name": name, "args": args,
                "partition": partition, "order": order, "spec": spec,
                "ftype": ft,
            })
            return ColumnExpr(-1, ft, f"{name}(..) over(..)", uid)

        def agg_collector(name, args, distinct):
            key = (name, tuple(str(a) for a in args), distinct)
            if key in agg_uid_of:
                uid, ft = agg_uid_of[key]
                return ColumnExpr(-1, ft, f"{name}(..)", uid)
            desc = AggDesc(name, args, distinct)
            uid = next_uid()
            aggs.append(desc)
            agg_uid_of[key] = (uid, desc.ftype)
            return ColumnExpr(-1, desc.ftype, str(desc), uid)

        sub_handler = self._mk_subquery_handler(p.schema, outer)
        eb = ExprBuilder(p.schema, agg_collector if has_agg else None,
                         sub_handler, outer, self.param_values,
                         window_collector=window_collector)

        field_exprs: List[Expression] = []
        field_names: List[str] = []
        for f in fields:
            e = eb.build(f.expr)
            field_exprs.append(e)
            field_names.append(f.alias or _display_name(f.expr))

        if has_agg:
            # ---- GROUP BY ---------------------------------------------
            group_exprs: List[Expression] = []
            geb = ExprBuilder(from_schema, None, sub_handler, outer,
                              self.param_values)
            for g in sel.group_by:
                if isinstance(g, ast.Literal) and isinstance(g.value, int):
                    idx = g.value - 1
                    if not (0 <= idx < len(field_exprs)):
                        raise PlanError(f"GROUP BY position {g.value}")
                    group_exprs.append(field_exprs[idx])
                elif isinstance(g, ast.ColumnRef) and \
                        from_schema.try_resolve(g.name, g.table) is None:
                    # alias reference
                    if g.name.lower() not in [n.lower() for n in field_names]:
                        raise UnknownColumnError(g.name)
                    i = [n.lower() for n in field_names].index(g.name.lower())
                    group_exprs.append(field_exprs[i])
                else:
                    group_exprs.append(geb.build(g))

            # group outputs keep the uid of bare columns so later refs hit
            group_uids: List[int] = []
            group_schema_cols: List[SchemaCol] = []
            group_key_strs = {}
            for ge in group_exprs:
                if isinstance(ge, ColumnExpr) and ge.unique_id >= 0:
                    uid = ge.unique_id
                    name = ge.name
                else:
                    uid = next_uid()
                    name = str(ge)
                group_uids.append(uid)
                group_key_strs[str(ge)] = (uid, ge.ftype)
                group_schema_cols.append(
                    SchemaCol(uid, name, ge.ftype, "", name)
                )

            def patch(e: Expression) -> Expression:
                # rewrite post-agg exprs onto the agg output schema
                if isinstance(e, ColumnExpr):
                    if any(w["uid"] == e.unique_id for w in windows):
                        return e  # window output, computed above the agg
                    if e.unique_id in group_uids or \
                            e.unique_id in [u for u, _ in agg_uid_of.values()]:
                        return e
                    # bare column outside GROUP BY -> first_row (TiDB
                    # behavior without ONLY_FULL_GROUP_BY)
                    return agg_collector("first_row", [e], False)
                key = str(e)
                if key in group_key_strs:
                    uid, ft = group_key_strs[key]
                    return ColumnExpr(-1, ft, key, uid)
                if isinstance(e, ScalarFunc):
                    return ScalarFunc(e.name, [patch(a) for a in e.args],
                                      e.ftype, e.meta)
                return e

            field_exprs = [patch(e) for e in field_exprs]

            amap = {n.lower(): e for n, e in zip(field_names, field_exprs)}
            having_conds: List[Expression] = []
            if sel.having is not None:
                heb = ExprBuilder(p.schema, agg_collector, sub_handler,
                                  outer, self.param_values,
                                  alias_fields=amap)
                for conj in split_and(sel.having):
                    having_conds.append(patch(heb.build(conj)))

            order_items = self._build_order(sel.order_by, field_names,
                                            field_exprs, p.schema,
                                            ExprBuilder(p.schema, agg_collector,
                                                        sub_handler, outer,
                                                        self.param_values,
                                                        alias_fields=amap,
                                                        window_collector=window_collector))
            order_items = [(patch(e), d) for e, d in order_items]
            for w in windows:
                w["args"] = [patch(a) for a in w["args"]]
                w["partition"] = [patch(x) for x in w["partition"]]
                w["order"] = [(patch(e), d) for e, d in w["order"]]

            agg_schema = Schema(
                group_schema_cols + [
                    SchemaCol(agg_uid_of[k][0], str(a), a.ftype, "", str(a))
                    for k, a in zip(list(agg_uid_of.keys()), aggs)
                ]
            )
            # NOTE: agg_uid_of insertion order == aggs order (both appended
            # together), so the zip above lines up.
            p = LogicalAggregation(p, group_exprs, aggs, agg_schema)
            if having_conds:
                p = LogicalSelection(p, having_conds)
        else:
            amap = {n.lower(): e for n, e in zip(field_names, field_exprs)}
            if sel.having is not None:
                heb = ExprBuilder(p.schema, None, sub_handler, outer,
                                  self.param_values, alias_fields=amap)
                conds = [heb.build(c) for c in split_and(sel.having)]
                p = LogicalSelection(p, conds)
            order_items = self._build_order(
                sel.order_by, field_names, field_exprs, p.schema,
                ExprBuilder(p.schema, None, sub_handler, outer,
                            self.param_values, alias_fields=amap,
                            window_collector=window_collector))

        # ---- window operators (one per distinct spec) -----------------
        if windows:
            p = self._attach_windows(p, windows)

        # ---- ORDER BY placement ---------------------------------------
        if order_items and not sel.distinct:
            if sel.limit is not None:
                p = LogicalTopN(p, order_items, sel.limit, sel.offset)
            else:
                p = LogicalSort(p, order_items)

        # ---- projection -----------------------------------------------
        proj_cols = [
            SchemaCol(next_uid(), name, e.ftype, "", name)
            for name, e in zip(field_names, field_exprs)
        ]
        p = LogicalProjection(p, field_exprs, Schema(proj_cols))

        # ---- DISTINCT --------------------------------------------------
        if sel.distinct:
            group = [c.to_expr() for c in proj_cols]
            p = LogicalAggregation(p, group, [], Schema(proj_cols))
            if order_items:
                # items must reference select outputs; re-resolve by string
                remapped = []
                str_to_col = {str(e): c for e, c in zip(field_exprs, proj_cols)}
                for e, d in order_items:
                    c = str_to_col.get(str(e))
                    if c is None:
                        raise PlanError(
                            "ORDER BY with DISTINCT must use select columns"
                        )
                    remapped.append((c.to_expr(), d))
                if sel.limit is not None:
                    p = LogicalTopN(p, remapped, sel.limit, sel.offset)
                else:
                    p = LogicalSort(p, remapped)
            if sel.limit is not None and not order_items:
                p = LogicalLimit(p, sel.limit, sel.offset)
        elif sel.limit is not None and not order_items:
            p = LogicalLimit(p, sel.limit, sel.offset)

        return p

    def _attach_windows(self, p: LogicalPlan, windows: List[dict]):
        from ..executor.window import Frame, WindowFuncDesc
        from .logical import LogicalWindow

        def frame_of(spec) -> Frame:
            if not spec.unit:
                return Frame()
            s = (spec.start.kind, spec.start.offset) if spec.start else \
                ("unbounded_preceding", 0)
            e = (spec.end.kind, spec.end.offset) if spec.end else \
                ("current", 0)
            return Frame(spec.unit, s, e)

        def expr_key(e):
            # uid-aware structural key: same-named columns from different
            # tables must NOT collide (str() is display-only)
            uids: set = set()
            e.collect_columns(uids)
            return (str(e), tuple(sorted(uids)))

        groups: dict = {}
        for w in windows:
            fr = frame_of(w["spec"])
            key = (
                tuple(expr_key(x) for x in w["partition"]),
                tuple((expr_key(e), d) for e, d in w["order"]),
                (fr.unit, fr.start, fr.end),
            )
            groups.setdefault(key, []).append(w)
        for key, ws in groups.items():
            funcs = [
                (w["uid"], WindowFuncDesc(w["name"], w["args"], w["ftype"]))
                for w in ws
            ]
            fr = frame_of(ws[0]["spec"])
            cols = list(p.schema.cols) + [
                SchemaCol(w["uid"], f'{w["name"]}_over', w["ftype"])
                for w in ws
            ]
            p = LogicalWindow(p, funcs, ws[0]["partition"], ws[0]["order"],
                              fr, Schema(cols))
        return p

    def _build_order(self, order_by, field_names, field_exprs, schema,
                     eb: ExprBuilder):
        items = []
        names = [n.lower() for n in field_names]
        for it in order_by:
            e = it.expr
            if isinstance(e, ast.Literal) and isinstance(e.value, int):
                idx = e.value - 1
                if not (0 <= idx < len(field_exprs)):
                    raise PlanError(f"ORDER BY position {e.value}")
                items.append((field_exprs[idx], it.desc))
                continue
            if isinstance(e, ast.ColumnRef) and not e.table \
                    and schema.try_resolve(e.name) is None \
                    and e.name.lower() in names:
                items.append((field_exprs[names.index(e.name.lower())],
                              it.desc))
                continue
            items.append((eb.build(e), it.desc))
        return items

    def _build_filter(self, p: LogicalPlan, where, outer) -> LogicalPlan:
        holder = [p]
        conds: List[Expression] = []
        for conj in split_and(where):
            neg = False
            node = conj
            if isinstance(node, ast.UnaryOp) and node.op == "not":
                if isinstance(node.operand, (ast.Exists, ast.InSubquery)):
                    neg, node = True, node.operand
            if isinstance(node, ast.InSubquery):
                holder[0] = self._semi_join(holder[0], node.query, node.expr,
                                            node.negated or neg, outer)
                continue
            if isinstance(node, ast.Exists):
                holder[0] = self._exists_join(holder[0], node.query,
                                              node.negated or neg, outer)
                continue
            eb = ExprBuilder(holder[0].schema, None,
                             self._mk_subquery_handler(holder[0].schema,
                                                       outer, holder),
                             outer, self.param_values)
            conds.append(eb.build(conj))
        p = holder[0]
        if conds:
            p = LogicalSelection(p, conds)
        return p

    def _semi_join(self, p: LogicalPlan, query, operand, negated: bool,
                   outer) -> LogicalPlan:
        from .decorrelate import semi_join

        return semi_join(self, p, query, operand, negated, outer)

    def _exists_join(self, p: LogicalPlan, query, negated: bool,
                     outer) -> LogicalPlan:
        from .decorrelate import exists_join

        return exists_join(self, p, query, negated, outer)

    # ------------------------------------------------------------------
    # UNION
    # ------------------------------------------------------------------
    def build_union(self, u: ast.UnionStmt,
                    outer: Optional[List[Schema]] = None) -> LogicalPlan:
        children = [self.build_select(s, outer) for s in u.selects]
        width = len(children[0].schema)
        for c in children[1:]:
            if len(c.schema) != width:
                raise PlanError("UNION columns differ")
        cols = []
        for i in range(width):
            ft = children[0].schema.col(i).ftype
            for c in children[1:]:
                ft = merge_types(ft, c.schema.col(i).ftype)
            first = children[0].schema.col(i)
            cols.append(SchemaCol(next_uid(), first.name, ft, "",
                                  first.display or first.name))
        p: LogicalPlan = LogicalUnion(children, Schema(cols))
        if not u.all:
            group = [c.to_expr() for c in cols]
            p = LogicalAggregation(p, group, [], Schema(cols))
        if u.order_by:
            names = [c.name.lower() for c in cols]
            items = []
            for it in u.order_by:
                e = it.expr
                if isinstance(e, ast.Literal) and isinstance(e.value, int):
                    items.append((cols[e.value - 1].to_expr(), it.desc))
                elif isinstance(e, ast.ColumnRef) and e.name.lower() in names:
                    items.append(
                        (cols[names.index(e.name.lower())].to_expr(), it.desc)
                    )
                else:
                    raise PlanError("UNION ORDER BY must use output columns")
            if u.limit is not None:
                p = LogicalTopN(p, items, u.limit, u.offset)
            else:
                p = LogicalSort(p, items)
        if u.limit is not None and not u.order_by:
            p = LogicalLimit(p, u.limit, u.offset)
        return p

    # ------------------------------------------------------------------
    # DML
    # ------------------------------------------------------------------
    def build_insert(self, st: ast.InsertStmt) -> InsertPlan:
        t = self._table_info(st.table)
        if st.columns:
            offsets = []
            for name in st.columns:
                c = t.find_column(name)
                if c is None:
                    raise UnknownColumnError(name)
                offsets.append(c.offset)
        else:
            offsets = [c.offset for c in t.public_columns()]
        plan = InsertPlan(st.table.db or self.current_db, t, offsets,
                          replace=st.replace, ignore=st.ignore)
        if st.query is not None:
            sub = self.build(st.query)
            if len(sub.schema) != len(offsets):
                raise PlanError("INSERT ... SELECT column count mismatch")
            plan.select_plan = sub
        else:
            eb = ExprBuilder(Schema([]), None, None, [], self.param_values)
            rows = []
            for vals in st.values:
                if len(vals) != len(offsets):
                    raise PlanError("INSERT value count mismatch")
                row = []
                for v, off in zip(vals, offsets):
                    if isinstance(v, ast.Default):
                        row.append(DEFAULT_MARKER)
                        continue
                    e = eb.build(v)
                    row.append(_eval_const(e))
                rows.append(row)
            plan.rows = rows
        if st.on_dup_update:
            # schema: old row cols then VALUES() pseudo-cols (renamed so an
            # unqualified ref never collides with the real column)
            cols = [
                SchemaCol(next_uid(), c.name, c.ftype, "", c.name, c.offset)
                for c in t.columns
            ]
            vcols = [
                SchemaCol(next_uid(), f"__values__{c.name}", c.ftype, "",
                          c.name, len(t.columns) + c.offset)
                for c in t.columns
            ]
            sch = Schema(cols + vcols)
            eb2 = ExprBuilder(sch, None, None, [], self.param_values)
            for name, vexpr in st.on_dup_update:
                c = t.find_column(name)
                if c is None:
                    raise UnknownColumnError(name)
                e = eb2.build(_rewrite_values_fn(vexpr))
                e = e.remap_columns({sc.uid: i for i, sc in enumerate(sch.cols)})
                plan.on_dup_update.append((c.offset, e))
        return plan

    def _full_row_schema(self, t: TableInfo, qualifier: str = "") -> Schema:
        q = qualifier or t.name
        return Schema([
            SchemaCol(next_uid(), c.name, c.ftype, q, c.name, c.offset)
            for c in t.columns
        ])

    def build_update(self, st: ast.UpdateStmt) -> UpdatePlan:
        t = self._table_info(st.table)
        sch = self._full_row_schema(t, st.table.alias)
        pos = {sc.uid: i for i, sc in enumerate(sch.cols)}
        eb = ExprBuilder(sch, None, None, [], self.param_values)
        assigns = []
        for name, vexpr in st.assignments:
            c = t.find_column(name)
            if c is None:
                raise UnknownColumnError(name)
            e = eb.build(vexpr).remap_columns(pos)
            assigns.append((c.offset, e))
        conds = []
        if st.where is not None:
            for conj in split_and(st.where):
                conds.append(eb.build(conj).remap_columns(pos))
        return UpdatePlan(st.table.db or self.current_db, t, assigns, conds)

    def build_delete(self, st: ast.DeleteStmt) -> DeletePlan:
        t = self._table_info(st.table)
        sch = self._full_row_schema(t, st.table.alias)
        pos = {sc.uid: i for i, sc in enumerate(sch.cols)}
        eb = ExprBuilder(sch, None, None, [], self.param_values)
        conds = []
        if st.where is not None:
            for conj in split_and(st.where):
                conds.append(eb.build(conj).remap_columns(pos))
        return DeletePlan(st.table.db or self.current_db, t, conds)


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _aliased(sub: LogicalPlan, alias: str) -> LogicalPlan:
    sub.schema = sub.schema.with_table(alias)
    return sub


def _nullable(c: SchemaCol) -> SchemaCol:
    from dataclasses import replace

    return replace(c, ftype=c.ftype.with_nullable(True))


def _as_eq_key(e: Expression, left_uids, right_uids):
    """cond of shape left_col = right_col (either orientation)."""
    if isinstance(e, ScalarFunc) and e.name == "=" and len(e.args) == 2:
        a, b = e.args
        ua = _root_uids(a)
        ub = _root_uids(b)
        if ua and ub:
            if ua <= left_uids and ub <= right_uids:
                return (a, b)
            if ua <= right_uids and ub <= left_uids:
                return (b, a)
    return None


def _root_uids(e: Expression) -> set:
    out: set = set()
    e.collect_columns(out)
    return out




def _split_corr_eqs(conds, outer_uids: set, inner_uids: set):
    from .decorrelate import split_corr_eqs

    return split_corr_eqs(conds, outer_uids, inner_uids)


def _references_outer(query, schema: Schema,
                      infoschema=None, current_db: str = "") -> bool:
    from .decorrelate import references_outer

    return references_outer(query, schema, infoschema, current_db)


def _walk_exprs(plan: LogicalPlan):
    """All expressions in a logical plan tree."""
    from .logical import LogicalWindow

    stack = [plan]
    while stack:
        node = stack.pop()
        stack.extend(node.children)
        if isinstance(node, LogicalSelection):
            yield from node.conds
        elif isinstance(node, LogicalProjection):
            yield from node.exprs
        elif isinstance(node, LogicalAggregation):
            yield from node.group_by
            for a in node.aggs:
                yield from a.args
        elif isinstance(node, LogicalJoin):
            for l, r in node.eq_conds:
                yield l
                yield r
            yield from node.other_conds
        elif isinstance(node, (LogicalSort, LogicalTopN)):
            for e, _ in node.items:
                yield e
        elif isinstance(node, LogicalDataSource):
            yield from node.pushed_conds
        elif isinstance(node, LogicalWindow):
            for _, f in node.funcs:
                yield from f.args
            yield from node.partition_by
            for e, _ in node.order_by:
                yield e


def _contains_agg(e: ast.Expr) -> bool:
    if isinstance(e, ast.FuncCall):
        if e.over is not None:
            return False  # window function, not an aggregate trigger
        if e.name.lower() in AGG_FUNCS:
            return True
        return any(_contains_agg(a) for a in e.args
                   if isinstance(a, ast.Expr))
    for attr in ("left", "right", "operand", "expr", "low", "high",
                 "else_expr", "value"):
        v = getattr(e, attr, None)
        if isinstance(v, ast.Expr) and _contains_agg(v):
            return True
    if isinstance(e, ast.CaseWhen):
        for w, t in e.branches:
            if _contains_agg(w) or _contains_agg(t):
                return True
    if isinstance(e, ast.InList):
        return any(_contains_agg(x) for x in e.items)
    if isinstance(e, ast.FuncCall):
        return any(_contains_agg(a) for a in e.args)
    return False


def _display_name(e: ast.Expr) -> str:
    if isinstance(e, ast.ColumnRef):
        return e.name
    if isinstance(e, ast.Literal):
        return str(e.value)
    if isinstance(e, ast.FuncCall):
        inner = ", ".join(_display_name(a) for a in e.args)
        return f"{e.name}({inner})"
    if isinstance(e, ast.BinaryOp):
        return f"{_display_name(e.left)} {e.op} {_display_name(e.right)}"
    return type(e).__name__.lower()


def _rewrite_values_fn(e: ast.Expr) -> ast.Expr:
    """VALUES(col) inside ON DUPLICATE KEY UPDATE -> pseudo-col ref."""
    if isinstance(e, ast.FuncCall) and e.name.lower() == "values" \
            and len(e.args) == 1 and isinstance(e.args[0], ast.ColumnRef):
        return ast.ColumnRef(f"__values__{e.args[0].name}")
    if isinstance(e, ast.BinaryOp):
        return ast.BinaryOp(e.op, _rewrite_values_fn(e.left),
                            _rewrite_values_fn(e.right))
    if isinstance(e, ast.FuncCall):
        return ast.FuncCall(e.name, [_rewrite_values_fn(a) for a in e.args],
                            e.distinct)
    return e


def _eval_const(e: Expression):
    e = fold_constant(e)
    if isinstance(e, Constant):
        v = e.value
        from ..types import TypeKind

        if v is not None and e.ftype.kind == TypeKind.DECIMAL:
            from ..types.values import format_decimal

            # exact decimal text (a float here silently drops digits past
            # 2^53 — the wide-decimal path depends on this staying exact)
            return format_decimal(int(v), e.ftype.scale)
        return v
    # non-foldable (now(), rand()): evaluate over a 1-row dual
    dual = Chunk([Column.from_values(ty_int(False), [0])])
    v = e.eval(dual)
    if v.valid is not None and not bool(v.valid[0]):
        return None
    x = v.data[0]
    if isinstance(x, np.generic):
        x = x.item()
    return x
