"""Planner schemas: named, uniquely-identified output columns per plan node.

Reference: expression.Schema / expression.Column with UniqueID (expression/
schema.go, column.go) — unique ids survive through the plan tree so rules can
track a column across projections; positional resolution happens only when
physical executors are built.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field, replace
from typing import List, Optional

from ..errors import AmbiguousColumnError, UnknownColumnError
from ..expr.expression import ColumnExpr
from ..types import FieldType

_uid_counter = itertools.count(1)


def next_uid() -> int:
    return next(_uid_counter)


@dataclass(frozen=True)
class SchemaCol:
    uid: int
    name: str  # column name (lowercase for resolution; display kept separate)
    ftype: FieldType
    table: str = ""  # qualifier (table alias) for resolution
    display: str = ""  # header text
    store_offset: int = -1  # offset in the backing TableStore (DataSource only)

    def to_expr(self) -> ColumnExpr:
        return ColumnExpr(-1, self.ftype, self.display or self.name, self.uid)


class Schema:
    def __init__(self, cols: List[SchemaCol]):
        self.cols = cols

    def __len__(self):
        return len(self.cols)

    def __iter__(self):
        return iter(self.cols)

    def col(self, i: int) -> SchemaCol:
        return self.cols[i]

    def ftypes(self) -> List[FieldType]:
        return [c.ftype for c in self.cols]

    def uids(self) -> List[int]:
        return [c.uid for c in self.cols]

    def headers(self) -> List[str]:
        return [c.display or c.name for c in self.cols]

    def index_of_uid(self, uid: int) -> int:
        for i, c in enumerate(self.cols):
            if c.uid == uid:
                return i
        return -1

    def position_map(self) -> dict:
        """uid -> positional index, for Expression.remap_columns."""
        return {c.uid: i for i, c in enumerate(self.cols)}

    def resolve(self, name: str, table: str = "") -> SchemaCol:
        lname, ltable = name.lower(), table.lower()
        matches = [
            c for c in self.cols
            if c.name.lower() == lname and (not ltable or c.table.lower() == ltable)
        ]
        if not matches:
            raise UnknownColumnError(f"{table + '.' if table else ''}{name}")
        if len(matches) > 1 and len({c.uid for c in matches}) > 1:
            raise AmbiguousColumnError(name)
        return matches[0]

    def try_resolve(self, name: str, table: str = "") -> Optional[SchemaCol]:
        try:
            return self.resolve(name, table)
        except (UnknownColumnError, AmbiguousColumnError):
            return None

    def merge(self, other: "Schema") -> "Schema":
        return Schema(self.cols + other.cols)

    def with_table(self, alias: str) -> "Schema":
        return Schema([replace(c, table=alias) for c in self.cols])
