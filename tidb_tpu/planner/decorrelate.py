"""Subquery decorrelation: EXISTS / NOT EXISTS / IN / NOT IN and simple
correlated scalar subqueries rewrite into semi / anti / left-outer joins
that enter the SAME join graph as the FROM-clause joins (ISSUE 12).

Reference: planner/core/expression_rewriter.go (handleInSubquery /
handleExistSubquery / buildSemiApply) + rule_decorrelate.go.  The rules:

- ``x IN (SELECT e FROM ...)``            -> semi join on x = e
- ``x NOT IN (SELECT e FROM ...)``        -> anti-semi join on x = e
- ``EXISTS (SELECT ... WHERE corr)``      -> semi join on the correlated
  equalities; non-equality correlated conjuncts ride as join other-conds
  (evaluated over the outer++inner pair layout)
- ``NOT EXISTS (...)``                    -> anti-semi join, same shape
- ``expr op (SELECT agg(e) WHERE k = outer.k)`` -> LEFT OUTER join
  against the grouped inner (GROUP BY the correlation keys), with COUNT
  outputs wrapped in IFNULL(.., 0) — the classic COUNT decorrelation
  bug (rule_decorrelate.go wraps the same way)

The semi/anti joins produced here are plain LogicalJoin nodes, so the
join-tree compiler (planner/jointree.py) lowers them onto the device as
semi/anti RUNGS of the same rung ladder as the inner joins — an EXISTS
probe never forces the host path by construction.

This module owns the machinery; planner/build.py delegates to it (the
subquery handler itself stays in the builder, which owns schema scope).
"""

from __future__ import annotations

from typing import List

from ..errors import PlanError
from ..expr.aggregation import AggDesc
from ..expr.expression import ColumnExpr, Constant, Expression, ScalarFunc
from ..parser import ast
from .columns import Schema, SchemaCol, next_uid
from .expr_build import ExprBuilder, expr_uids as _expr_uids, split_and
from .logical import LogicalAggregation, LogicalJoin, LogicalSelection


def split_corr_eqs(conds, outer_uids: set, inner_uids: set):
    """Partition conjuncts into correlated equality pairs
    [(inner_expr, outer_colexpr)] and residual conds."""
    pairs, residual = [], []
    for cond in conds:
        uids = _expr_uids([cond])
        if not (uids & outer_uids):
            residual.append(cond)
            continue
        ok = False
        if isinstance(cond, ScalarFunc) and cond.name == "=" and \
                len(cond.args) == 2:
            a, b = cond.args
            ua, ub = _expr_uids([a]), _expr_uids([b])
            if isinstance(a, ColumnExpr) and a.unique_id in outer_uids \
                    and ub and ub <= inner_uids:
                pairs.append((b, a))
                ok = True
            elif isinstance(b, ColumnExpr) and b.unique_id in outer_uids \
                    and ua and ua <= inner_uids:
                pairs.append((a, b))
                ok = True
        if not ok:
            residual.append(cond)
    return pairs, residual


def references_outer(query, schema: Schema,
                     infoschema=None, current_db: str = "") -> bool:
    """Does the subquery's AST reference a column resolvable ONLY in the
    outer schema?  Walk over ColumnRefs: names the inner FROM cannot
    provide but the outer schema can."""
    outer_names = {(c.table.lower(), c.name.lower()) for c in schema.cols}
    outer_bare = {c.name.lower() for c in schema.cols}
    inner_tables = set()
    inner_cols = set()  # bare column names the inner FROM provides

    def from_names(node):
        if isinstance(node, ast.TableName):
            inner_tables.add((node.alias or node.name).lower())
            if infoschema is not None:
                try:
                    t = infoschema.table(node.db or current_db, node.name)
                    inner_cols.update(c.name.lower()
                                      for c in t.public_columns())
                except Exception:
                    pass
        elif isinstance(node, ast.SubqueryRef):
            inner_tables.add(node.alias.lower())
            for f in getattr(node.query, "fields", []):
                if f.alias:
                    inner_cols.add(f.alias.lower())
                elif isinstance(f.expr, ast.ColumnRef):
                    inner_cols.add(f.expr.name.lower())
        elif isinstance(node, ast.Join):
            from_names(node.left)
            from_names(node.right)

    if isinstance(query, ast.SelectStmt):
        from_names(query.from_clause)

    hit = [False]

    def walk_expr(e):
        if hit[0] or not isinstance(e, ast.Node):
            return
        if isinstance(e, ast.ColumnRef):
            if e.table:
                if e.table.lower() not in inner_tables and \
                        (e.table.lower(), e.name.lower()) in outer_names:
                    hit[0] = True
            else:
                if infoschema is not None and e.name.lower() in outer_bare \
                        and e.name.lower() not in inner_cols:
                    hit[0] = True
            return
        if isinstance(e, (ast.InSubquery, ast.Exists, ast.ScalarSubquery)):
            return  # nested blocks judge their own correlation
        for attr in ("left", "right", "operand", "expr", "low", "high",
                     "else_expr", "value"):
            v = getattr(e, attr, None)
            if isinstance(v, ast.Node):
                walk_expr(v)
        for attr in ("args", "items"):
            v = getattr(e, attr, None)
            if isinstance(v, list):
                for x in v:
                    walk_expr(x)
        if isinstance(e, ast.CaseWhen):
            for w, t in e.branches:
                walk_expr(w)
                walk_expr(t)

    if isinstance(query, ast.SelectStmt):
        for f in query.fields:
            walk_expr(f.expr)
        if query.where is not None:
            walk_expr(query.where)
    return hit[0]


def correlated_source(builder, query, schema: Schema, outer,
                      allow_other: bool = True):
    """FROM+WHERE of a correlated IN/EXISTS block, with the correlated
    equality pairs pulled out (rule_decorrelate.go): returns
    (inner_plan, [(inner_expr, outer_colexpr)], other_corr_conds).
    Non-equality correlated conjuncts become semi-join other-conds when
    allowed (they evaluate over the outer++inner pair layout)."""
    if not isinstance(query, ast.SelectStmt):
        raise PlanError("correlated subquery must be a simple SELECT")
    if query.group_by or query.having:
        raise PlanError(
            "GROUP BY/HAVING in a correlated IN/EXISTS is not supported"
        )
    inner = builder.build_from(query.from_clause, [schema] + outer)
    outer_uids = set(schema.uids())
    conds: List[Expression] = []
    if query.where is not None:
        eb = ExprBuilder(inner.schema, None, None, [schema] + outer,
                         builder.param_values)
        for conj in split_and(query.where):
            conds.append(eb.build(conj))
    pairs, residual = split_corr_eqs(conds, outer_uids,
                                     set(inner.schema.uids()))
    other_corr = [c for c in residual if _expr_uids([c]) & outer_uids]
    residual = [c for c in residual if not (_expr_uids([c]) & outer_uids)]
    if other_corr and not allow_other:
        raise PlanError("correlated predicate must be an equality "
                        "with an outer column")
    if residual:
        inner = LogicalSelection(inner, residual)
    if not pairs and not other_corr:
        raise PlanError("could not decorrelate subquery")
    return inner, pairs, other_corr


def semi_join(builder, p, query, operand, negated: bool, outer):
    """IN / NOT IN conjunct -> semi / anti-semi join on operand = value."""
    kind = "anti_semi" if negated else "semi"
    eb = ExprBuilder(p.schema, None, None, outer, builder.param_values)
    left_key = eb.build(operand)
    if references_outer(query, p.schema, builder.infoschema,
                        builder.current_db):
        inner, pairs, other = correlated_source(builder, query, p.schema,
                                                outer)
        veb = ExprBuilder(inner.schema, None, None,
                          [p.schema] + outer, builder.param_values)
        value = veb.build(query.fields[0].expr)
        eqs = [(left_key, value)] + [(oe, ie) for ie, oe in pairs]
        return LogicalJoin(p, inner, kind, eqs, other, p.schema)
    sub = builder.build_select(query, [p.schema] + outer)
    if len(sub.schema) != 1:
        raise PlanError("IN subquery must return one column")
    right_key = sub.schema.col(0).to_expr()
    return LogicalJoin(p, sub, kind, [(left_key, right_key)], [],
                       p.schema)


def exists_join(builder, p, query, negated: bool, outer):
    """EXISTS / NOT EXISTS conjunct -> semi / anti-semi join."""
    kind = "anti_semi" if negated else "semi"
    if references_outer(query, p.schema, builder.infoschema,
                        builder.current_db):
        inner, pairs, other = correlated_source(builder, query, p.schema,
                                                outer)
        eqs = [(oe, ie) for ie, oe in pairs]
        return LogicalJoin(p, inner, kind, eqs, other, p.schema)
    sub = builder.build_select(query, [p.schema] + outer)
    return LogicalJoin(p, sub, kind, [], [], p.schema)


def is_correlated_agg(builder, query, schema: Schema, outer) -> bool:
    """Cheap AST check: single aggregate select field, no GROUP BY, and
    the WHERE references an enclosing column."""
    if not isinstance(query, ast.SelectStmt) or query.group_by:
        return False
    if len(query.fields) != 1 or not _contains_agg_ast(query.fields[0].expr):
        return False
    return references_outer(query, schema, builder.infoschema,
                            builder.current_db)


def decorrelate_scalar(builder, query, schema: Schema, outer, plan_holder):
    """t1.x > (SELECT agg(e) FROM t2 WHERE t2.k = t1.k AND ...) becomes
    LEFT JOIN (SELECT t2.k, agg(e) FROM t2 WHERE ... GROUP BY t2.k) ON
    t2.k = t1.k, with the expression reading the agg output column."""
    inner = builder.build_from(query.from_clause, [schema] + outer)
    outer_uids = set(schema.uids())
    conds: List[Expression] = []
    if query.where is not None:
        eb = ExprBuilder(inner.schema, None, None, [schema] + outer,
                         builder.param_values)
        # widen resolution: correlated refs resolve via outer schemas
        for conj in split_and(query.where):
            conds.append(eb.build(conj))
    pairs, residual = split_corr_eqs(conds, outer_uids,
                                     set(inner.schema.uids()))
    if any(_expr_uids([c]) & outer_uids for c in residual):
        raise PlanError("correlated predicate must be an equality "
                        "with an outer column")
    if residual:
        inner = LogicalSelection(inner, residual)
    # build the select field: arbitrary expression over collected aggs
    aggs: List[AggDesc] = []
    agg_uids: List[int] = []

    def collector(name, args, distinct):
        d = AggDesc(name, args, distinct)
        aggs.append(d)
        uid = next_uid()
        agg_uids.append(uid)
        col = ColumnExpr(-1, d.ftype.with_nullable(True), str(d), uid)
        if name == "count":
            # the LEFT JOIN below yields NULL for unmatched outer rows,
            # but COUNT over an empty group must read 0 (the classic
            # COUNT decorrelation bug; reference rule_decorrelate.go
            # wraps count outputs the same way)
            from ..expr.builtins import infer_ftype

            zero = Constant(0, d.ftype)
            ft = infer_ftype("ifnull", [col.ftype, zero.ftype], {})
            return ScalarFunc("ifnull", [col, zero], ft, {})
        return col

    feb = ExprBuilder(inner.schema, collector, None, [schema] + outer,
                      builder.param_values)
    field_expr = feb.build(query.fields[0].expr)
    if not aggs:
        raise PlanError("correlated subquery must aggregate")
    used = _expr_uids([field_expr])
    if used - set(agg_uids):
        raise PlanError("correlated subquery field may only combine "
                        "aggregates and constants")
    group_exprs = [ie for ie, _oe in pairs]
    gcols = []
    for ge in group_exprs:
        uid = ge.unique_id if isinstance(ge, ColumnExpr) and \
            ge.unique_id >= 0 else next_uid()
        gcols.append(SchemaCol(uid, str(ge), ge.ftype, "", str(ge)))
    agg_schema = Schema(gcols + [
        SchemaCol(uid, str(a), a.ftype.with_nullable(True), "", str(a))
        for uid, a in zip(agg_uids, aggs)
    ])
    inner_agg = LogicalAggregation(inner, group_exprs, aggs, agg_schema)
    p = plan_holder[0]
    eqs = [(oe, gc.to_expr()) for (_ie, oe), gc in zip(pairs, gcols)]
    joined_schema = Schema(
        list(p.schema.cols)
        + [SchemaCol(c.uid, c.name, c.ftype.with_nullable(True), c.table,
                     c.display, c.store_offset) for c in agg_schema.cols]
    )
    plan_holder[0] = LogicalJoin(p, inner_agg, "left_outer", eqs, [],
                                 joined_schema)
    return field_expr


def _contains_agg_ast(e) -> bool:
    from .build import _contains_agg

    return _contains_agg(e)
