"""AST expression -> resolved, typed Expression trees.

Reference: planner/core/expression_rewriter.go — name resolution against the
child plan's schema, type inference per builtin, constant folding
(expression/constant_fold.go), aggregate extraction, subquery hooks.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import numpy as np

from ..chunk import Chunk, Column
from ..errors import PlanError, UnknownColumnError
from ..expr.aggregation import AGG_FUNCS, AggDesc
from ..expr.builtins import REGISTRY, infer_ftype
from ..expr.expression import ColumnExpr, Constant, Expression, ScalarFunc
from ..parser import ast
from ..types import (
    FieldType,
    TypeKind,
    ty_bool,
    ty_date,
    ty_datetime,
    ty_decimal,
    ty_float,
    ty_int,
    ty_null,
    ty_string,
    ty_uint,
)
from ..types.values import parse_date, parse_datetime
from .columns import Schema

_BINOP_CANON = {
    "<>": "!=", "&&": "and", "||": "or", "<=>": "nulleq",
}

_TEMPORAL_CMP = {"=", "!=", "nulleq", "<", "<=", ">", ">=", "in"}


def _normalize_temporal_consts(name: str,
                               args: List[Expression]) -> List[Expression]:
    """Fold string literals to DATE/DATETIME constants when compared against
    a temporal expression: `l_shipdate <= '1998-09-02'` plans with an int
    day constant, so the predicate is device-compilable (jax_eval rejects
    raw string constants) and the CPU engine skips per-row parsing."""
    if name not in _TEMPORAL_CMP:
        return args
    target = None
    for a in args:
        if a.ftype.kind in (TypeKind.DATE, TypeKind.DATETIME) and not (
            isinstance(a, Constant)
        ):
            target = a.ftype.kind
            break
    if target is None:
        return args
    out: List[Expression] = []
    for a in args:
        if (isinstance(a, Constant) and a.ftype.kind == TypeKind.STRING
                and isinstance(a.value, str)):
            try:
                if target == TypeKind.DATE:
                    a = Constant(parse_date(a.value), ty_date(False))
                else:
                    a = Constant(parse_datetime(a.value), ty_datetime(False))
            except (ValueError, IndexError):
                pass  # not a temporal literal; leave for runtime semantics
        out.append(a)
    return out

_TYPE_NAME_TO_FT = {
    "signed": lambda p, s: ty_int(),
    "unsigned": lambda p, s: ty_uint(),
    "char": lambda p, s: ty_string(),
    "binary": lambda p, s: ty_string(),
    "double": lambda p, s: ty_float(),
    "float": lambda p, s: ty_float(),
    "decimal": lambda p, s: ty_decimal(p or 10, s),
    "date": lambda p, s: ty_date(),
    "datetime": lambda p, s: ty_datetime(),
}


def literal_to_constant(v, type_hint: str = "") -> Constant:
    if v is None:
        return Constant(None, ty_null())
    if type_hint == "date":
        return Constant(parse_date(str(v)), ty_date(False))
    if type_hint in ("datetime", "timestamp"):
        return Constant(parse_datetime(str(v)), ty_datetime(False))
    if type_hint == "decimal":
        text = str(v)
        neg = text.startswith("-")
        digits = text.lstrip("+-")
        intpart, _, frac = digits.partition(".")
        scaled = int((intpart or "0") + frac)
        if neg:
            scaled = -scaled
        prec = max(len(intpart) + len(frac), 1)
        return Constant(scaled, ty_decimal(prec, len(frac), False))
    if isinstance(v, bool):
        return Constant(int(v), ty_int(False))
    if isinstance(v, int):
        if abs(v) >= (1 << 63):
            # past BIGINT range: exact wide-decimal literal (mydecimal's
            # 65-digit domain), host-evaluated
            return Constant(v, ty_decimal(max(len(str(abs(v))), 19), 0,
                                          False))
        return Constant(v, ty_int(False))
    if isinstance(v, float):
        return Constant(v, ty_float(False))
    return Constant(str(v), ty_string(False))


class ExprBuilder:
    """Stateful expression rewriter bound to one input schema.

    agg_collector: called for aggregate FuncCalls; returns the Expression
    that stands for the aggregate's value (a ColumnExpr onto the agg node's
    output).  None -> aggregates are illegal in this context.
    subquery_handler: called for sub-SELECT expressions with
    (query_ast, kind in {'scalar','in','exists'}, extra) -> Expression.
    """

    def __init__(self, schema: Schema,
                 agg_collector: Optional[Callable] = None,
                 subquery_handler: Optional[Callable] = None,
                 outer_schemas: Optional[List[Schema]] = None,
                 param_values: Optional[list] = None,
                 fold_constants: bool = True,
                 alias_fields: Optional[dict] = None,
                 window_collector: Optional[Callable] = None):
        self.schema = schema
        self.agg_collector = agg_collector
        self.subquery_handler = subquery_handler
        self.outer_schemas = outer_schemas or []
        self.param_values = param_values
        self.fold = fold_constants
        # SELECT-alias fallback scope (HAVING/ORDER BY): name -> Expression
        self.alias_fields = alias_fields or {}
        self.window_collector = window_collector

    # ------------------------------------------------------------------
    def build(self, e: ast.Expr) -> Expression:
        out = self._build(e)
        if self.fold:
            out = fold_constant(out)
        return out

    def build_bool(self, e: ast.Expr) -> List[Expression]:
        """WHERE/HAVING/ON: split top-level AND into conjuncts."""
        conds = []
        for sub in split_and(e):
            conds.append(self.build(sub))
        return conds

    # ------------------------------------------------------------------
    def _build(self, e: ast.Expr) -> Expression:
        if isinstance(e, ast.Literal):
            return literal_to_constant(e.value, e.type_hint)
        if isinstance(e, ast.ColumnRef):
            return self._column(e)
        if isinstance(e, ast.BinaryOp):
            return self._binop(e)
        if isinstance(e, ast.UnaryOp):
            return self._unop(e)
        if isinstance(e, ast.FuncCall):
            return self._func(e)
        if isinstance(e, ast.CaseWhen):
            return self._case(e)
        if isinstance(e, ast.Cast):
            return self._cast(e)
        if isinstance(e, ast.InList):
            return self._in_list(e)
        if isinstance(e, ast.InSubquery):
            return self._subquery(e.query, "in", negated=e.negated,
                                  operand=e.expr)
        if isinstance(e, ast.Between):
            return self._between(e)
        if isinstance(e, ast.Exists):
            return self._subquery(e.query, "exists", negated=e.negated)
        if isinstance(e, ast.ScalarSubquery):
            return self._subquery(e.query, "scalar")
        if isinstance(e, ast.Param):
            if self.param_values is None or e.index >= len(self.param_values):
                raise PlanError("missing parameter value")
            return literal_to_constant(self.param_values[e.index])
        if isinstance(e, ast.Variable):
            raise PlanError("variable reference outside SET/session context")
        if isinstance(e, ast.Interval):
            raise PlanError("INTERVAL outside DATE_ADD/DATE_SUB")
        if isinstance(e, ast.Default):
            raise PlanError("DEFAULT outside INSERT/UPDATE")
        raise PlanError(f"unsupported expression {type(e).__name__}")

    # ------------------------------------------------------------------
    def _column(self, e: ast.ColumnRef) -> Expression:
        col = self.schema.try_resolve(e.name, e.table)
        if col is not None:
            return col.to_expr()
        if not e.table and e.name.lower() in self.alias_fields:
            return self.alias_fields[e.name.lower()]
        # correlated reference into an enclosing query block: resolve to the
        # outer column's uid — the subquery planner decorrelates or rejects
        for sc in self.outer_schemas:
            oc = sc.try_resolve(e.name, e.table)
            if oc is not None:
                return oc.to_expr()
        raise UnknownColumnError(
            f"{e.table + '.' if e.table else ''}{e.name}"
        )

    def _make_func(self, name: str, args: List[Expression],
                   meta: Optional[dict] = None) -> ScalarFunc:
        meta = meta or {}
        if name not in REGISTRY:
            raise PlanError(f"unknown function {name!r}")
        args = _normalize_temporal_consts(name, args)
        ft = infer_ftype(name, [a.ftype for a in args], meta)
        return ScalarFunc(name, args, ft, meta)

    def _binop(self, e: ast.BinaryOp) -> Expression:
        op = _BINOP_CANON.get(e.op, e.op)
        if op in ("is", "is not"):
            operand = self._build(e.left)
            if isinstance(e.right, ast.Literal):
                v = e.right.value
                if v is None:
                    return self._make_func(
                        "isnull" if op == "is" else "isnotnull", [operand]
                    )
                if isinstance(v, bool):
                    fn = "istrue" if v else "isfalse"
                    out = self._make_func(fn, [operand])
                    if op == "is not":
                        out = self._make_func("not", [out])
                    return out
            raise PlanError("IS requires NULL/TRUE/FALSE")
        left = self._build(e.left)
        right = self._build(e.right)
        if op == "not like":  # NOT LIKE = not(like(...))
            return self._make_func("not",
                                   [self._make_func("like", [left, right])])
        return self._make_func(op, [left, right])

    def _unop(self, e: ast.UnaryOp) -> Expression:
        operand = self._build(e.operand)
        if e.op == "+":
            return operand
        if e.op == "-":
            return self._make_func("unaryminus", [operand])
        if e.op == "not":
            return self._make_func("not", [operand])
        if e.op == "~":
            return self._make_func("~", [operand])
        raise PlanError(f"unary op {e.op!r}")

    def _func(self, e: ast.FuncCall) -> Expression:
        name = e.name.lower()
        if e.over is not None:
            if self.window_collector is None:
                raise PlanError(
                    f"window function {name}() not allowed in this context"
                )
            args = [self._build(a) for a in e.args
                    if not isinstance(a, ast.Star)]
            partition = [self._build(x) for x in e.over.partition_by]
            order = [(self._build(it.expr), it.desc)
                     for it in e.over.order_by]
            return self.window_collector(name, args, partition, order,
                                         e.over)
        if name in AGG_FUNCS:
            if self.agg_collector is None:
                raise PlanError(f"aggregate {name}() not allowed here")
            args = []
            for a in e.args:
                if isinstance(a, ast.Star):
                    args = []
                    break
                args.append(self._build(a))
            return self.agg_collector(name, args, e.distinct)
        # date_add/date_sub: second arg is Interval
        if name in ("date_add", "date_sub", "adddate", "subdate"):
            canon = "date_add" if name in ("date_add", "adddate") else "date_sub"
            base = self._build(e.args[0])
            iv = e.args[1]
            if isinstance(iv, ast.Interval):
                amount = self._build(iv.value)
                unit = iv.unit
            else:
                amount = self._build(iv)
                unit = "day"
            return self._make_func(canon, [base, amount], {"unit": unit})
        if name == "extract":
            iv = e.args[0]
            unit = iv.unit if isinstance(iv, ast.Interval) else "day"
            return self._make_func(
                "extract", [self._build(e.args[1])], {"unit": unit}
            )
        if name in ("timestampadd", "timestampdiff"):
            # first arg is a bare unit keyword (SECOND, DAY, MONTH, ...) —
            # depending on the word it parses as a column ref or a
            # zero-arg function call (MONTH, DATE are also functions);
            # MySQL also accepts the ODBC SQL_TSI_* spellings
            unit = _bare_word(e.args[0], "day")
            if unit.startswith("sql_tsi_"):
                unit = unit[len("sql_tsi_"):]
            if unit not in ("microsecond", "second", "minute", "hour",
                            "day", "week", "month", "quarter", "year"):
                raise PlanError(f"invalid {name.upper()} unit {unit!r}")
            rest = [self._build(a) for a in e.args[1:]]
            return self._make_func(name, rest, {"unit": unit})
        if name == "get_format":
            # GET_FORMAT(DATE|DATETIME|TIME, 'locale'): the first arg is a
            # bare keyword, not an expression
            kindc = Constant(_bare_word(e.args[0], "date"), ty_string(False))
            return self._make_func(name, [kindc, self._build(e.args[1])])
        args = [self._build(a) for a in e.args]
        return self._make_func(name, args)

    def _case(self, e: ast.CaseWhen) -> Expression:
        args: List[Expression] = []
        if e.operand is not None:
            op = self._build(e.operand)
            for w, t in e.branches:
                args.append(self._make_func("=", [op, self._build(w)]))
                args.append(self._build(t))
        else:
            for w, t in e.branches:
                args.append(self._build(w))
                args.append(self._build(t))
        if e.else_expr is not None:
            args.append(self._build(e.else_expr))
        return self._make_func("case", args)

    def _cast(self, e: ast.Cast) -> Expression:
        mk = _TYPE_NAME_TO_FT.get(e.type_name.lower())
        if mk is None:
            raise PlanError(f"CAST target {e.type_name!r}")
        target = mk(e.precision, e.scale)
        arg = self._build(e.expr)
        return self._make_func("cast", [arg],
                               {"target": target.with_nullable(arg.ftype.nullable)})

    def _in_list(self, e: ast.InList) -> Expression:
        args = [self._build(e.expr)] + [self._build(x) for x in e.items]
        out = self._make_func("in", args)
        if e.negated:
            out = self._make_func("not", [out])
        return out

    def _between(self, e: ast.Between) -> Expression:
        x = self._build(e.expr)
        lo = self._build(e.low)
        hi = self._build(e.high)
        ge = self._make_func(">=", [x, lo])
        le = self._make_func("<=", [x, hi])
        out = self._make_func("and", [ge, le])
        if e.negated:
            out = self._make_func("not", [out])
        return out

    def _subquery(self, query, kind: str, negated: bool = False,
                  operand=None) -> Expression:
        if self.subquery_handler is None:
            raise PlanError("subquery not allowed in this context")
        return self.subquery_handler(query, kind, negated, operand)


class CorrelatedColumn(Exception):
    """Raised when a name resolves only in an enclosing block; the caller
    (subquery planner) catches it to build an Apply."""

    def __init__(self, col):
        self.col = col
        super().__init__(str(col))


def split_and(e: ast.Expr) -> List[ast.Expr]:
    if isinstance(e, ast.BinaryOp) and e.op in ("and", "&&"):
        return split_and(e.left) + split_and(e.right)
    return [e]


def expr_uids(exprs) -> set:
    """Every column uid referenced by `exprs` (the shared walk used by
    the plan builder, the decorrelator, and the join-tree compiler)."""
    out: set = set()
    for e in exprs:
        e.collect_columns(out)
    return out


def fold_constant(e: Expression) -> Expression:
    """Bottom-up constant folding (expression/constant_fold.go)."""
    if isinstance(e, ScalarFunc):
        e = ScalarFunc(e.name, [fold_constant(a) for a in e.args],
                       e.ftype, e.meta)
        if e.name in ("rand", "sleep", "now", "curdate", "version",
                      "connection_id", "database", "found_rows", "row"):
            return e
        if all(isinstance(a, Constant) for a in e.args):
            dual = Chunk([Column.from_values(ty_int(False), [0])])
            try:
                v = e.eval(dual)
            except Exception:
                return e
            if v.valid is not None and not bool(v.valid[0]):
                return Constant(None, e.ftype)
            x = v.data[0]
            if isinstance(x, np.generic):
                x = x.item()
            # NOTE: DECIMAL constants store the scaled-int representation,
            # matching Column.constant / the cop IR wire format.
            return Constant(x, e.ftype)
    return e


def _bare_word(node, default: str) -> str:
    """The identifier a bare keyword argument parsed into (column ref or
    zero-arg function call), lowercased."""
    import tidb_tpu.parser.ast as _ast

    if isinstance(node, _ast.ColumnRef):
        return node.name.lower()
    if isinstance(node, _ast.FuncCall):
        return node.name.lower()
    v = getattr(node, "value", None)
    return str(v).lower() if v is not None else default
