"""Join-tree compiler: n-way equi-join graphs -> device rung ladders.

ISSUE 12's tentpole, the planning half.  TPC-H is join TREES — Q2/Q5/
Q7/Q8/Q9 join 4-8 tables — but the two-table MPP lane
(physical._try_mpp_join) only fires when BOTH children are scans, so
multi-way joins fell back to host rungs.  This module:

1. collects a maximal inner-join GROUP whose members are all
   MPP-eligible scan fragments, plus the semi / anti-semi / left-outer
   joins stacked above it (decorrelated EXISTS/IN subqueries —
   planner/decorrelate.py — arrive exactly in that shape);
2. chooses a join ORDER from NDV/row-count statistics: exact dynamic
   programming over connected left-deep orders up to ``DP_MAX_RELS``
   relations (Selinger on subsets), the greedy smallest-intermediate
   heuristic beyond;
3. emits a ``PhysMPPJoinTree`` whose executor (mpp/jointree.py) runs
   one exchange/local-join program per rung with the intermediate
   result staying DEVICE-RESIDENT between rungs, and (when the parent
   aggregation is pushable) finishes with the on-device partial
   aggregation so only O(G) rows ever leave the mesh.

EXPLAIN shows the chosen order with est_rows per rung; every structural
decline returns None and the generic lanes (index join, two-table MPP,
host hash join) take over unchanged.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..expr.expression import ColumnExpr, Expression
from ..expr.pushdown import (can_push_agg, can_push_expr,
                             can_remap_group_key)
from ..types import TypeKind
from .columns import Schema
from .expr_build import expr_uids as _expr_uids
from .logical import (
    LogicalAggregation,
    LogicalDataSource,
    LogicalJoin,
    LogicalPlan,
    LogicalProjection,
)

#: exact DP join ordering up to this many relations; greedy beyond
DP_MAX_RELS = 8


# ---------------------------------------------------------------------------
# collection: flatten the join tree into group members + filter rungs
# ---------------------------------------------------------------------------


class _Collected:
    """Flattened join tree: inner-group members, their eq edges/other
    conds, and the semi/anti/left-outer joins stacked above the group
    (bottom-up order)."""

    def __init__(self):
        self.members: List[LogicalDataSource] = []
        self.eqs: List[Tuple[Expression, Expression]] = []
        self.others: List[Expression] = []
        # (kind, inner datasource, [(outer_e, inner_e)], other_conds)
        self.filters: List[tuple] = []


def _subst_cols(e: Expression, sub: dict) -> Expression:
    """Replace mapped column uids, leave everything else alone (the
    outer side of a semi-join condition must survive untouched)."""
    if isinstance(e, ColumnExpr):
        return sub.get(e.unique_id, e)
    from ..expr.expression import ScalarFunc

    if isinstance(e, ScalarFunc):
        return ScalarFunc(e.name, [_subst_cols(a, sub) for a in e.args],
                          e.ftype, e.meta)
    return e


def _peel_projection(p: LogicalPlan):
    """A plain-column Projection over a scan (the shape an uncorrelated
    IN subquery's select list leaves behind) is transparent to the join
    graph: return (datasource, {proj uid -> source ColumnExpr})."""
    if not isinstance(p, LogicalProjection) \
            or not isinstance(p.children[0], LogicalDataSource):
        return None
    sub = {}
    for c, e in zip(p.schema.cols, p.exprs):
        if not isinstance(e, ColumnExpr) or e.unique_id < 0:
            return None
        sub[c.uid] = e
    return p.children[0], sub


def _collect(plan: LogicalPlan) -> Optional[_Collected]:
    out = _Collected()
    # peel the filter-join chain (semi/anti/louter applied above FROM)
    filters_top_down = []
    node = plan
    while isinstance(node, LogicalJoin) and node.kind in (
            "semi", "anti_semi", "left_outer"):
        right = node.children[1]
        eqs, others = list(node.eq_conds), list(node.other_conds)
        if not isinstance(right, LogicalDataSource):
            peeled = _peel_projection(right)
            if peeled is None:
                return None
            right, sub = peeled
            eqs = [(le, _subst_cols(re_, sub)) for le, re_ in eqs]
            others = [_subst_cols(c, sub) for c in others]
        filters_top_down.append((node.kind, right, eqs, others))
        node = node.children[0]
    out.filters = list(reversed(filters_top_down))  # bottom-up

    def collect(p):
        if isinstance(p, LogicalJoin) and p.kind == "inner":
            out.eqs.extend(p.eq_conds)
            out.others.extend(p.other_conds)
            for c in p.children:
                collect(c)
        else:
            out.members.append(p)

    collect(node)
    if not all(isinstance(m, LogicalDataSource) for m in out.members):
        return None
    return out


# ---------------------------------------------------------------------------
# per-side eligibility (mirrors physical._mpp_join_parts' gates)
# ---------------------------------------------------------------------------


class _Side:
    """One eligible scan side: the cop task plus uid bookkeeping."""

    def __init__(self, ds: LogicalDataSource, task):
        self.ds = ds
        self.task = task
        self.uid_pos = {c.uid: i for i, c in enumerate(ds.schema.cols)}

    @property
    def table(self):
        return self.ds.table


def _eligible_side(ds: LogicalDataSource, pctx) -> Optional[_Side]:
    from ..copr.ir import SelectionIR
    from .physical import _MPP_OUT_KINDS, _start_cop

    if ds.table.is_partitioned:
        return None  # per-partition stores; the copart lane owns these
    if any(c.ftype.kind not in _MPP_OUT_KINDS
           or (c.ftype.kind == TypeKind.DECIMAL
               and c.ftype.is_wide_decimal)
           for c in ds.schema.cols):
        return None
    task, residual = _start_cop(ds, pctx)
    if task is None or residual or task.ranges == []:
        return None
    if any(not isinstance(x, SelectionIR) for x in task.dag_execs):
        return None
    return _Side(ds, task)


def _side_ndv(side: _Side, uid: int, pctx) -> Optional[float]:
    sc = next((c for c in side.ds.schema.cols if c.uid == uid), None)
    if sc is None or pctx.stats is None:
        return None
    st = pctx.stats.get(side.table.id)
    cs = st.columns.get(sc.store_offset) if st else None
    if cs is None or cs.ndv <= 0:
        return None
    return float(cs.ndv)


def _side_rows(side: _Side, pctx) -> float:
    from .physical import PhysTableReader, _est_rows

    return max(_est_rows(
        PhysTableReader(Schema(side.task.scan_cols), side.task, False,
                        side.ds.ranges), pctx), 1.0)


# ---------------------------------------------------------------------------
# join ordering: DP on connected left-deep orders, greedy beyond
# ---------------------------------------------------------------------------


def _edge_list(members, eqs) -> Optional[List[tuple]]:
    uid_of = {}
    for i, m in enumerate(members):
        for u in m.schema.uids():
            uid_of[u] = i

    def side_of(e):
        us = _expr_uids([e])
        idxs = {uid_of.get(u) for u in us}
        if None in idxs or len(idxs) != 1:
            return None
        return idxs.pop()

    edges = []
    for le, re_ in eqs:
        i, j = side_of(le), side_of(re_)
        if i is None or j is None or i == j:
            return None
        edges.append((i, j, le, re_))
    return edges


def _join_est(rows_built: float, built_idx: set, rows_new: float,
              new_idx: int, edges, ndv_of) -> float:
    """Containment estimate |built ⋈ new|, one division per connecting
    eq edge (capped NDVs: filters cannot raise distinct counts)."""
    est = rows_built * rows_new
    connected = False
    for i, j, le, re_ in edges:
        if (i in built_idx and j == new_idx):
            pair = (le, re_)
        elif (j in built_idx and i == new_idx):
            pair = (re_, le)
        else:
            continue
        connected = True
        bl, nw = pair
        nl = min(ndv_of(bl) or 100.0, rows_built)
        nr = min(ndv_of(nw) or 100.0, rows_new)
        est /= max(nl, nr, 1.0)
    if not connected:
        return -1.0  # cross join: not a candidate
    return max(est, 1.0)


def _order_members(sides: List[_Side], edges, pctx
                   ) -> Optional[Tuple[List[int], List[float]]]:
    """Left-deep join order minimizing the summed intermediate sizes:
    exact DP over connected subsets up to DP_MAX_RELS, greedy beyond.

    Returns (order, per-step estimates): ests[k] is the estimated
    intermediate after joining order[k+1] — the SAME numbers the DP
    costed with, so rung assembly (EXPLAIN est_rows, grouped-agg
    budgets) never re-derives them from a second copy of the
    containment formula (ISSUE 13 / jointree follow-up (f))."""
    n = len(sides)
    if n == 1:
        return [0], []
    rows = [_side_rows(s, pctx) for s in sides]

    def ndv_of(e):
        if not isinstance(e, ColumnExpr) or e.unique_id < 0:
            return None
        for s in sides:
            if e.unique_id in s.uid_pos:
                return _side_ndv(s, e.unique_id, pctx)
        return None

    if n <= DP_MAX_RELS:
        # best[frozenset] = (cost, rows, order, ests): Selinger over
        # left-deep connected extensions
        best = {frozenset([i]): (0.0, rows[i], (i,), ()) for i in range(n)}
        for _size in range(1, n):
            nxt = {}
            for subset, (cost, r, order, ests) in best.items():
                if len(subset) != _size:
                    continue
                for j in range(n):
                    if j in subset:
                        continue
                    est = _join_est(r, subset, rows[j], j, edges, ndv_of)
                    if est < 0:
                        continue
                    key = subset | {j}
                    cand = (cost + est, est, order + (j,), ests + (est,))
                    cur = nxt.get(key)
                    if cur is None or cand[0] < cur[0]:
                        nxt[key] = cand
            best.update(nxt)
        full = best.get(frozenset(range(n)))
        if full is None:
            return None  # disconnected graph: cross joins stay host
        return list(full[2]), list(full[3])

    # greedy: start from the smallest member, repeatedly add the
    # connected member minimizing the estimated intermediate
    order = [min(range(n), key=lambda i: rows[i])]
    joined = set(order)
    cur_rows = rows[order[0]]
    step_ests: List[float] = []
    while len(order) < n:
        cands = []
        for j in range(n):
            if j in joined:
                continue
            est = _join_est(cur_rows, joined, rows[j], j, edges, ndv_of)
            if est >= 0:
                cands.append((est, j))
        if not cands:
            return None
        est, j = min(cands)
        joined.add(j)
        order.append(j)
        step_ests.append(est)
        cur_rows = est
    return order, step_ests


# ---------------------------------------------------------------------------
# rung assembly
# ---------------------------------------------------------------------------


_TREE_KEY_KINDS = (TypeKind.INT, TypeKind.UINT, TypeKind.DECIMAL,
                   TypeKind.DATE)


def _key_ok(le: Expression, re_: Expression) -> bool:
    if not isinstance(le, ColumnExpr) or not isinstance(re_, ColumnExpr):
        return False
    if le.ftype.kind not in _TREE_KEY_KINDS \
            or re_.ftype.kind != le.ftype.kind:
        return False
    if le.ftype.kind == TypeKind.DECIMAL \
            and le.ftype.scale != re_.ftype.scale:
        return False
    return True


class _TreePlan:
    """The assembled ladder, pre-physical: sides in join order, rung
    dicts, slot bookkeeping."""

    def __init__(self):
        self.sides: List[_Side] = []
        self.rungs: List[dict] = []
        self.slot_of: dict = {}       # uid -> slot
        self.slot_src: List[Tuple[int, int]] = []
        self.slot_ftypes: list = []
        self.dict_uids: set = set()


def _assemble(col: _Collected, pctx) -> Optional[_TreePlan]:
    from .physical import _dict_uids

    member_sides = []
    for m in col.members:
        s = _eligible_side(m, pctx)
        if s is None:
            return None
        member_sides.append(s)
    filter_sides = []
    for kind, ds, eqs, others in col.filters:
        if kind == "left_outer" and len(eqs) > 1:
            # multi-key louter candidates come from the collision-prone
            # mix-hash; dropping a collision pair would still emit a
            # spurious NULL-extended row (keep=out_valid), so this
            # shape stays host — the same gate the two-table lane
            # applies when exact key packing doesn't cover the space
            return None
        if kind == "left_outer" and others:
            # push build-side-only ON conds into the inner scan (sound
            # for LEFT JOIN: they only restrict which inner rows match);
            # anything referencing the outer side keeps the host lane
            ruids = set(ds.schema.uids())
            duids = _dict_uids(ds, pctx)
            for c in others:
                if not (_expr_uids([c]) <= ruids) or not can_push_expr(
                        c, pctx.pushdown_blacklist, duids):
                    return None
            # identity-dedupe: _assemble may run more than once over the
            # SAME logical nodes (agg lane declines after assembly, the
            # rows lane retries) — never stack the same cond twice
            ds.pushed_conds.extend(
                c for c in others
                if not any(c is p for p in ds.pushed_conds))
            others = []
        s = _eligible_side(ds, pctx)
        if s is None:
            return None
        filter_sides.append((kind, s, eqs, others))

    edges = _edge_list(col.members, col.eqs)
    if edges is None:
        return None
    for _i, _j, le, re_ in edges:
        if not _key_ok(le, re_):
            return None
    for kind, s, eqs, _o in filter_sides:
        if not eqs and kind in ("semi", "anti_semi"):
            return None  # uncorrelated EXISTS: host lane
        for oe, ie in eqs:
            if not _key_ok(oe, ie):
                return None

    ordered = _order_members(member_sides, edges, pctx)
    if ordered is None:
        return None
    # one formula drives ordering AND EXPLAIN/budget estimates: the DP's
    # per-step numbers ARE the rung est_rows (jointree follow-up (f))
    order, step_ests = ordered

    tp = _TreePlan()
    dict_all: set = set()
    for m in col.members:
        dict_all |= _dict_uids(m, pctx)
    for _k, s, _e, _o in filter_sides:
        dict_all |= _dict_uids(s.ds, pctx)
    tp.dict_uids = dict_all

    def add_slots(side: _Side, ordinal: int):
        for pos, c in enumerate(side.ds.schema.cols):
            tp.slot_of[c.uid] = len(tp.slot_src)
            tp.slot_src.append((ordinal, pos))
            tp.slot_ftypes.append(c.ftype)

    rows = [_side_rows(s, pctx) for s in member_sides]

    base = member_sides[order[0]]
    tp.sides.append(base)
    add_slots(base, 0)
    placed_eq = [False] * len(edges)
    placed_other = [False] * len(col.others)
    built_idx = {order[0]}
    built_uids = set(base.ds.schema.uids())
    cur_rows = rows[order[0]]
    for step, mi in enumerate(order[1:]):
        side = member_sides[mi]
        ordinal = len(tp.sides)
        keys = []
        for k, (i, j, le, re_) in enumerate(edges):
            if placed_eq[k]:
                continue
            if i in built_idx and j == mi:
                keys.append((le, re_))
                placed_eq[k] = True
            elif j in built_idx and i == mi:
                keys.append((re_, le))
                placed_eq[k] = True
        if not keys:
            return None  # cross-join rung: host lane
        est = step_ests[step]
        muids = set(side.ds.schema.uids())
        avail = built_uids | muids
        oth = []
        for k, c in enumerate(col.others):
            if placed_other[k]:
                continue
            if _expr_uids([c]) <= avail:
                if not can_push_expr(c, pctx.pushdown_blacklist,
                                     dict_all):
                    return None
                oth.append(c)
                placed_other[k] = True
        rung = {
            "side": ordinal,
            "kind": "inner",
            "left_uids": [le.unique_id for le, _ in keys],
            "build_pos": [side.uid_pos[re_.unique_id]
                          for _, re_ in keys],
            "others": oth,
            "build_width": len(side.ds.schema.cols),
            "est": est,
        }
        tp.sides.append(side)
        tp.rungs.append(rung)
        add_slots(side, ordinal)
        built_idx.add(mi)
        built_uids = avail
        cur_rows = est
    if not all(placed_eq) or not all(placed_other):
        return None

    # filter rungs (bottom-up order preserved)
    for kind, s, eqs, others in filter_sides:
        ordinal = len(tp.sides)
        muids = set(s.ds.schema.uids())
        for oe, _ie in eqs:
            if oe.unique_id not in built_uids:
                return None
        for c in others:
            refs = _expr_uids([c])
            if not refs <= (built_uids | muids):
                return None
            if not can_push_expr(c, pctx.pushdown_blacklist, dict_all):
                return None
        est = cur_rows if kind == "left_outer" else max(cur_rows * 0.5,
                                                        1.0)
        rung = {
            "side": ordinal,
            "kind": kind,
            "left_uids": [oe.unique_id for oe, _ in eqs],
            "build_pos": [s.uid_pos[ie.unique_id] for _, ie in eqs],
            "others": list(others),
            "build_width": len(s.ds.schema.cols),
            "est": est,
        }
        tp.sides.append(s)
        tp.rungs.append(rung)
        if kind == "left_outer":
            add_slots(s, ordinal)
        built_uids = built_uids | (muids if kind == "left_outer"
                                   else set())
        cur_rows = est
    return tp


def _remap_pair(e: Expression, tp: _TreePlan, rung: dict,
                side: _Side) -> Expression:
    """uid expr -> pair-layout positions: built slots, build side cols
    at n_slots+pos (the rung program's evaluation layout)."""
    n_slots = _n_slots_before(tp, rung)
    mapping = dict(tp.slot_of)
    for uid, pos in side.uid_pos.items():
        mapping[uid] = n_slots + pos
    return e.remap_columns(mapping)


def _n_slots_before(tp: _TreePlan, rung: dict) -> int:
    n = len(tp.sides[0].ds.schema.cols)
    for r in tp.rungs:
        if r is rung:
            break
        if r["kind"] in ("inner", "left_outer"):
            n += r["build_width"]
    return n


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def _tree_gate(col: Optional[_Collected], pctx) -> bool:
    if col is None:
        return False
    if not pctx.allow_mpp or not pctx.enable_pushdown \
            or pctx.prefer_merge_join:
        return False
    if len(col.members) >= 3:
        return True
    # smaller ladders only when a decorrelated filter rung makes the
    # device the only lane that keeps the subquery off the host
    return bool(col.filters)


def try_jointree(plan: LogicalJoin, pctx):
    """Rows-mode ladder: Join tree -> PhysMPPJoinTree emitting joined
    rows.  None when ineligible (generic lanes take over)."""
    col = _collect(plan)
    if not _tree_gate(col, pctx):
        return None
    tp = _assemble(col, pctx)
    if tp is None:
        return None
    out_slots, out_ftypes = [], []
    for c in plan.schema.cols:
        slot = tp.slot_of.get(c.uid)
        if slot is None:
            return None
        out_slots.append(slot)
        out_ftypes.append(c.ftype)
    return _phys_tree(tp, pctx, plan.schema, out_slots, out_ftypes)


def try_jointree_agg(plan: LogicalAggregation, join: LogicalPlan, pctx):
    """Aggregation over a join tree -> the partial aggregation runs in
    the ladder's final on-device phase; a FINAL HashAgg merges."""
    group_by, aggs = list(plan.group_by), list(plan.aggs)
    if isinstance(join, LogicalProjection):
        sub = {c.uid: e for c, e in zip(join.schema.cols, join.exprs)}
        from .rules import _substitute

        child = join.children[0]
        if not isinstance(child, LogicalJoin):
            return None
        g2, a2 = [], []
        for g in group_by:
            s = _substitute(g, sub)
            if s is None:
                return None
            g2.append(s)
        for a in aggs:
            from ..expr.aggregation import AggDesc

            args = []
            for x in a.args:
                s = _substitute(x, sub)
                if s is None:
                    return None
                args.append(s)
            a2.append(AggDesc(a.name, args, a.distinct, a.ftype))
        group_by, aggs, join = g2, a2, child
    if not isinstance(join, LogicalJoin) or not aggs:
        return None
    col = _collect(join)
    if not _tree_gate(col, pctx):
        return None
    tp = _assemble(col, pctx)
    if tp is None:
        return None

    from .physical import (MPP_GROUP_BUDGET_MAX, MPP_GROUP_BUDGET_MIN,
                           _is_plain_col, _mpp_grouped_enabled,
                           _partial_schema)

    grouped = bool(group_by)
    if grouped and not _mpp_grouped_enabled():
        return None
    all_uids = set(tp.slot_of)
    for g in group_by:
        if not (_expr_uids([g]) <= all_uids):
            return None
        if not (can_push_expr(g, pctx.pushdown_blacklist, tp.dict_uids)
                or _is_plain_col(g)
                or can_remap_group_key(g, tp.dict_uids)):
            return None
        if (g.ftype.kind == TypeKind.STRING
                and not isinstance(g, ColumnExpr)
                and not can_remap_group_key(g, tp.dict_uids)):
            return None
    for a in aggs:
        if a.name not in ("count", "sum", "avg", "min", "max") \
                or a.distinct:
            return None
        if not can_push_agg(a, pctx.pushdown_blacklist, tp.dict_uids):
            return None
        if not (_expr_uids(a.args) <= all_uids):
            return None
        if any(x.ftype.kind == TypeKind.STRING for x in a.args):
            return None  # dict codes don't aggregate
    budget = 0
    if grouped:
        est_rows = tp.rungs[-1]["est"] if tp.rungs else 1.0
        est_g = 1.0
        for g in group_by:
            got = None
            if isinstance(g, ColumnExpr) and g.unique_id >= 0:
                for s in tp.sides:
                    if g.unique_id in s.uid_pos:
                        got = _side_ndv(s, g.unique_id, pctx)
                        break
            est_g *= got if got is not None else 100.0
        # correlated keys (Q3's l_orderkey, o_orderdate) make the NDV
        # product wildly pessimistic: groups cannot exceed joined rows
        est_g = min(est_g, 2.0 * max(est_rows, 1.0))
        if est_g > MPP_GROUP_BUDGET_MAX:
            return None
        budget = int(min(max(2.0 * est_g, MPP_GROUP_BUDGET_MIN),
                         MPP_GROUP_BUDGET_MAX))

    # agg exprs remap onto the slot layout
    slot_map = dict(tp.slot_of)
    gb = [g.remap_columns(slot_map) for g in group_by]
    from ..expr.aggregation import AggDesc

    ag = [AggDesc(a.name, [x.remap_columns(slot_map) for x in a.args],
                  a.distinct, a.ftype) for a in aggs]
    partial = _partial_schema(plan)
    phys = _phys_tree(tp, pctx, partial,
                      list(range(len(tp.slot_src))),
                      list(tp.slot_ftypes),
                      aggs=ag, group_by=gb or None, group_budget=budget)
    if phys is None:
        return None
    from .physical import PhysHashAgg

    fin_gb = [ColumnExpr(i, g.ftype, str(g), -1)
              for i, g in enumerate(plan.group_by)]
    return PhysHashAgg(phys, fin_gb, plan.aggs, True, plan.schema)


def _phys_tree(tp: _TreePlan, pctx, schema, out_slots, out_ftypes,
               aggs=None, group_by=None, group_budget=0):
    from .physical import PhysExchangeSender, PhysMPPJoinTree

    senders = []
    key_pos_of = {0: []}
    for r in tp.rungs:
        key_pos_of[r["side"]] = r["build_pos"]
    for ordinal, s in enumerate(tp.sides):
        senders.append(PhysExchangeSender(
            Schema(s.task.scan_cols), s.task,
            key_pos_of.get(ordinal, []), ranges=s.ds.ranges))
    rungs = []
    for r in tp.rungs:
        side = tp.sides[r["side"]]
        others = [_remap_pair(c, tp, r, side) for c in r["others"]]
        rungs.append({
            "side": r["side"],
            "kind": r["kind"],
            "left_slots": [tp.slot_of[u] for u in r["left_uids"]],
            "build_pos": r["build_pos"],
            "others": others,
            "est": r["est"],
        })
    return PhysMPPJoinTree(
        senders, rungs, list(tp.slot_src), out_slots, out_ftypes,
        schema, aggs=aggs, group_by=group_by, group_budget=group_budget)
