"""Logical plan nodes.

Reference: planner/core/logical_plans.go (LogicalSelection, LogicalJoin,
LogicalAggregation, DataSource, ...).  Thin dataclasses: rules rewrite the
tree in place or rebuild nodes; every node exposes `schema` (output columns
with stable uids) and `children`.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from ..catalog import TableInfo
from ..expr.aggregation import AggDesc
from ..expr.expression import Expression
from .columns import Schema, SchemaCol


class LogicalPlan:
    schema: Schema
    children: List["LogicalPlan"]

    def __init__(self, schema: Schema, children: List["LogicalPlan"]):
        self.schema = schema
        self.children = children

    @property
    def name(self) -> str:
        return type(self).__name__.replace("Logical", "")


class LogicalDataSource(LogicalPlan):
    def __init__(self, db: str, table: TableInfo, alias: str, schema: Schema):
        super().__init__(schema, [])
        self.db = db
        self.table = table
        self.alias = alias
        # conjuncts pushed into the scan by predicate pushdown (become the
        # cop SelectionIR or residual root filters at physical time)
        self.pushed_conds: List[Expression] = []
        # handle ranges from ranger (full range when empty)
        self.ranges = None


class LogicalSelection(LogicalPlan):
    def __init__(self, child: LogicalPlan, conds: List[Expression]):
        super().__init__(child.schema, [child])
        self.conds = conds


class LogicalProjection(LogicalPlan):
    def __init__(self, child: LogicalPlan, exprs: List[Expression],
                 schema: Schema):
        super().__init__(schema, [child])
        self.exprs = exprs


class LogicalAggregation(LogicalPlan):
    def __init__(self, child: LogicalPlan, group_by: List[Expression],
                 aggs: List[AggDesc], schema: Schema):
        super().__init__(schema, [child])
        self.group_by = group_by
        self.aggs = aggs


class LogicalJoin(LogicalPlan):
    """kind: inner | left_outer | semi | anti_semi | left_outer_semi.
    eq_conds: [(left_expr, right_expr)] equality keys; other_conds evaluated
    over the joined row (left schema ++ right schema)."""

    def __init__(self, left: LogicalPlan, right: LogicalPlan, kind: str,
                 eq_conds: List[Tuple[Expression, Expression]],
                 other_conds: List[Expression], schema: Schema):
        super().__init__(schema, [left, right])
        self.kind = kind
        self.eq_conds = eq_conds
        self.other_conds = other_conds


class LogicalSort(LogicalPlan):
    def __init__(self, child: LogicalPlan,
                 items: List[Tuple[Expression, bool]]):
        super().__init__(child.schema, [child])
        self.items = items


class LogicalTopN(LogicalPlan):
    def __init__(self, child: LogicalPlan,
                 items: List[Tuple[Expression, bool]], limit: int,
                 offset: int = 0):
        super().__init__(child.schema, [child])
        self.items = items
        self.limit = limit
        self.offset = offset


class LogicalLimit(LogicalPlan):
    def __init__(self, child: LogicalPlan, limit: int, offset: int = 0):
        super().__init__(child.schema, [child])
        self.limit = limit
        self.offset = offset


class LogicalUnion(LogicalPlan):
    def __init__(self, children: List[LogicalPlan], schema: Schema):
        super().__init__(schema, children)


class LogicalDual(LogicalPlan):
    """No-table source: 1 row (SELECT 1) or 0 rows (provably-false WHERE)."""

    def __init__(self, schema: Schema, row_count: int = 1):
        super().__init__(schema, [])
        self.row_count = row_count


class LogicalMaxOneRow(LogicalPlan):
    def __init__(self, child: LogicalPlan):
        super().__init__(child.schema, [child])


class LogicalWindow(LogicalPlan):
    """One window spec; funcs = [(uid, WindowFuncDesc)].  Output schema is
    the child's columns followed by one column per window function."""

    def __init__(self, child: LogicalPlan, funcs, partition_by,
                 order_by, frame, schema: Schema):
        super().__init__(schema, [child])
        self.funcs = funcs
        self.partition_by = partition_by
        self.order_by = order_by
        self.frame = frame


class LogicalMemTable(LogicalPlan):
    """Virtual table backed by a provider function (INFORMATION_SCHEMA)."""

    def __init__(self, provider_name: str, schema: Schema):
        super().__init__(schema, [])
        self.provider_name = provider_name
        self.pushed_conds: List[Expression] = []


def walk(plan: LogicalPlan):
    yield plan
    for c in plan.children:
        yield from walk(c)
