"""Planner entry point.

Reference: planner.Optimize (planner/optimize.go:42) — build logical plan,
apply the logical rule pipeline, search/split into physical root+cop tasks.
"""

from __future__ import annotations

from typing import Optional

from ..catalog import InfoSchema
from ..parser import ast
from .build import (
    DeletePlan,
    InsertPlan,
    LoadDataPlan,
    PlanBuilder,
    UpdatePlan,
)
from .logical import LogicalPlan
from .physical import (
    PhysicalContext,
    PhysicalPlan,
    annotate_estimates,
    physical_for_stmt,
)
from .rules import optimize_logical


def plan_statement(stmt: ast.Stmt, infoschema: InfoSchema, current_db: str,
                   pctx: PhysicalContext, exec_subplan=None,
                   param_values=None) -> PhysicalPlan:
    builder = PlanBuilder(infoschema, current_db, exec_subplan, param_values)
    logical = builder.build(stmt)
    return finish_plan(logical, pctx)


def finish_plan(logical, pctx: PhysicalContext) -> PhysicalPlan:
    if isinstance(logical, InsertPlan):
        if logical.select_plan is not None:
            logical.select_plan = optimize_logical(logical.select_plan, pctx)
        return _verified(physical_for_stmt(logical, pctx), pctx)
    if isinstance(logical, (UpdatePlan, DeletePlan, LoadDataPlan)):
        return _verified(physical_for_stmt(logical, pctx), pctx)
    assert isinstance(logical, LogicalPlan)
    logical = optimize_logical(logical, pctx)
    phys = physical_for_stmt(logical, pctx)
    annotate_estimates(phys, pctx)
    return _verified(phys, pctx)


def _verified(phys: PhysicalPlan, pctx: PhysicalContext) -> PhysicalPlan:
    """Schema/dtype-verify the finished plan (lint.plancheck) when the
    session asks for it — the vet-for-plans pass over the OUTPUT of every
    planner rewrite, gated on `tidb_check_plan`."""
    if pctx.check_plan:
        from ..lint.plancheck import assert_plan

        assert_plan(phys)
    return phys
