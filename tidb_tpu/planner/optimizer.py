"""Planner entry point.

Reference: planner.Optimize (planner/optimize.go:42) — build logical plan,
apply the logical rule pipeline, search/split into physical root+cop tasks.
"""

from __future__ import annotations

from typing import Optional

from ..catalog import InfoSchema
from ..parser import ast
from .build import (
    DeletePlan,
    InsertPlan,
    LoadDataPlan,
    PlanBuilder,
    UpdatePlan,
)
from .logical import LogicalPlan
from .physical import (
    PhysicalContext,
    PhysicalPlan,
    annotate_estimates,
    physical_for_stmt,
)
from .rules import optimize_logical


def plan_statement(stmt: ast.Stmt, infoschema: InfoSchema, current_db: str,
                   pctx: PhysicalContext, exec_subplan=None,
                   param_values=None) -> PhysicalPlan:
    builder = PlanBuilder(infoschema, current_db, exec_subplan, param_values)
    logical = builder.build(stmt)
    return finish_plan(logical, pctx)


def finish_plan(logical, pctx: PhysicalContext) -> PhysicalPlan:
    if isinstance(logical, InsertPlan):
        if logical.select_plan is not None:
            logical.select_plan = optimize_logical(logical.select_plan, pctx)
        return physical_for_stmt(logical, pctx)
    if isinstance(logical, (UpdatePlan, DeletePlan, LoadDataPlan)):
        return physical_for_stmt(logical, pctx)
    assert isinstance(logical, LogicalPlan)
    logical = optimize_logical(logical, pctx)
    phys = physical_for_stmt(logical, pctx)
    annotate_estimates(phys, pctx)
    return phys
