"""Partition pruning: drop partitions a scan's predicates cannot touch.

Reference: planner/core/rule_partition_processor.go:1-249 (the partition
processor rewrites a partitioned DataSource into a union of per-partition
accesses, pruning by the partition expression's range).  Here the pruned
partition list becomes extra KeyRanges on one PhysTableReader — every
surviving partition's regions fan out over the same device mesh, so
"partition = shard group" (SURVEY.md §2.6) costs no extra plan nodes.

Only single-column RANGE / HASH partitioning exists (catalog/schema.py),
which is exactly the statically-prunable subset.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..catalog.schema import PartitionDef, PartitionInfo, TableInfo
from ..expr.expression import ColumnExpr, Constant, Expression, ScalarFunc

_FLIP = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "="}


def _cid(col: ColumnExpr, by_offset: bool) -> int:
    return col.index if by_offset else col.unique_id


def _col_op_const(cond: Expression, by_offset: bool = False):
    """(col id, op, value) for `col op const` / `const op col`, else None."""
    if not isinstance(cond, ScalarFunc) or cond.name not in _FLIP:
        return None
    if len(cond.args) != 2:
        return None
    a, b = cond.args
    if isinstance(a, ColumnExpr) and isinstance(b, Constant):
        return _cid(a, by_offset), cond.name, b.value
    if isinstance(b, ColumnExpr) and isinstance(a, Constant):
        return _cid(b, by_offset), _FLIP[cond.name], a.value
    return None


def _in_list(cond: Expression, by_offset: bool = False):
    """(col id, values) for `col IN (consts...)`, else None."""
    if not isinstance(cond, ScalarFunc) or cond.name != "in":
        return None
    if not cond.args or not isinstance(cond.args[0], ColumnExpr):
        return None
    vals = []
    for a in cond.args[1:]:
        if not isinstance(a, Constant):
            return None
        vals.append(a.value)
    return _cid(cond.args[0], by_offset), vals


def prune_partitions(table: TableInfo, conds: List[Expression],
                     part_uid: int,
                     by_offset: bool = False) -> List[PartitionDef]:
    """Partitions that can hold rows satisfying the conjunction `conds`.

    Bounds semantics: interval [lo, hi] with open flags, NULL handled by
    the write-route rule (NULL lives in the first partition and no
    col-op-const cond matches NULL, so eq/range conds never keep it)."""
    pi = table.partition_info
    assert pi is not None
    lo = hi = None
    lo_open = hi_open = False
    in_vals: Optional[List[object]] = None
    for c in conds:
        cc = _col_op_const(c, by_offset)
        if cc is not None and cc[0] == part_uid:
            _, op, v = cc
            if v is None:
                return []  # col op NULL matches nothing
            try:
                v = int(v)
            except (TypeError, ValueError):
                continue
            if op == "=":
                if (lo is not None and (v < lo or (v == lo and lo_open))) or \
                   (hi is not None and (v > hi or (v == hi and hi_open))):
                    return []
                lo = hi = v
                lo_open = hi_open = False
            elif op in (">", ">="):
                o = op == ">"
                if lo is None or v > lo or (v == lo and o and not lo_open):
                    lo, lo_open = v, o
            elif op in ("<", "<="):
                o = op == "<"
                if hi is None or v < hi or (v == hi and o and not hi_open):
                    hi, hi_open = v, o
            continue
        il = _in_list(c, by_offset)
        if il is not None and il[0] == part_uid:
            vals = []
            for v in il[1]:
                if v is None:
                    continue
                try:
                    vals.append(int(v))
                except (TypeError, ValueError):
                    vals = None
                    break
            if vals is not None:
                in_vals = vals if in_vals is None else \
                    [v for v in in_vals if v in set(vals)]
    if lo is not None and hi is not None and \
            (lo > hi or (lo == hi and (lo_open or hi_open))):
        return []  # contradictory conjunction: empty interval
    if in_vals is not None:
        # apply the interval to the IN list, then prune per value
        keep = []
        for v in in_vals:
            if lo is not None and (v < lo or (v == lo and lo_open)):
                continue
            if hi is not None and (v > hi or (v == hi and hi_open)):
                continue
            keep.append(v)
        if not keep:
            return []
        ids = set()
        out = []
        for v in keep:
            try:
                pd = pi.partition_for_value(v)
            except Exception:
                continue  # out-of-range value matches no partition
            if pd.id not in ids:
                ids.add(pd.id)
                out.append(pd)
        return sorted(out, key=lambda p: pi.defs.index(p))
    if pi.kind == "hash":
        if lo is not None and lo == hi and not lo_open and not hi_open:
            return [pi.defs[abs(lo) % len(pi.defs)]]  # Go truncated-rem abs
        return list(pi.defs)
    # RANGE: keep defs whose [prev_bound, less_than) intersects [lo, hi]
    out = []
    prev = None  # inclusive lower bound of this partition's range
    for pd in pi.defs:
        p_lo, p_hi = prev, pd.less_than  # [p_lo, p_hi)
        prev = pd.less_than
        if lo is not None and p_hi is not None and \
                (lo > p_hi - 1 or (lo == p_hi - 1 and lo_open)):
            continue
        if hi is not None and p_lo is not None and \
                (hi < p_lo or (hi == p_lo and hi_open)):
            continue
        out.append(pd)
    return out


def partition_uid(table: TableInfo, schema) -> Optional[int]:
    """uid of the partition column in this DataSource's schema."""
    pi = table.partition_info
    if pi is None:
        return None
    col = table.find_column(pi.column)
    if col is None:
        return None
    for c in schema.cols:
        if c.store_offset == col.offset:
            return c.uid
    return None
