"""Physical plans: cop/root task split + executor construction + EXPLAIN.

Reference: planner/core/physical_plans.go + task.go (copTask vs rootTask, the
cost boundary where operators either sink into the coprocessor DAG or stay in
root executors) + plan_to_pb.go (DAG serialization) + executor/builder.go (the
physical-plan -> executor type switch).

The pushdown decision (the TPU routing) happens in `attach_*` below: a
DataSource starts a cop task (TableScanIR [+ SelectionIR]); Aggregation/TopN/
Limit directly above a cop task sink into the DAG when their expressions pass
`can_push_*` (expr/pushdown.py) and the table has no dirty txn writes;
everything else finalizes the cop task into a PhysTableReader and continues
root-side.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import List, Optional, Tuple

from ..catalog import TableInfo
from ..copr.ir import (
    DAG,
    AggregationIR,
    LimitIR,
    ProjectionIR,
    SelectionIR,
    TableScanIR,
    TopNIR,
)
from ..errors import KVError, PlanError
from ..expr.aggregation import AggDesc
from ..expr.expression import ColumnExpr, Constant, Expression, ScalarFunc
from ..expr.pushdown import (can_push_agg, can_push_expr,
                             can_remap_group_key)
from ..store.kv import KeyRange
from ..store.regions import INF
from ..types import FieldType, TypeKind, common_compare_type
from .build import DeletePlan, InsertPlan, LoadDataPlan, UpdatePlan
from .columns import Schema, SchemaCol
from .logical import (
    LogicalAggregation,
    LogicalDataSource,
    LogicalDual,
    LogicalJoin,
    LogicalLimit,
    LogicalMaxOneRow,
    LogicalPlan,
    LogicalProjection,
    LogicalSelection,
    LogicalSort,
    LogicalTopN,
    LogicalUnion,
)

_plan_id_counter = [0]


def _next_plan_id() -> int:
    _plan_id_counter[0] += 1
    return _plan_id_counter[0]


class PhysicalPlan:
    """Base physical node: knows its output schema (for positional remap),
    builds its executor, explains itself."""

    def __init__(self, schema: Schema, children: List["PhysicalPlan"]):
        self.schema = schema
        self.children = children
        self.id = _next_plan_id()
        self.est_rows: Optional[float] = None

    @property
    def name(self) -> str:
        return type(self).__name__.replace("Phys", "")

    def task(self) -> str:
        return "root"

    def info(self) -> str:
        return ""

    def build(self, ctx):
        raise NotImplementedError

    def _est_str(self) -> str:
        return f"{self.est_rows:.2f}" if self.est_rows is not None else ""

    def explain_tree(self, indent: int = 0, lines=None) -> List[str]:
        lines = lines if lines is not None else []
        pad = ("  " * indent + "└─") if indent else ""
        lines.append((f"{pad}{self.name}_{self.id}", self._est_str(),
                      self.task(), self.info()))
        for c in self.children:
            c.explain_tree(indent + 1, lines)
        return lines


# ---------------------------------------------------------------------------
# cop task: a DAG under construction (task.go copTask analog)
# ---------------------------------------------------------------------------


@dataclass
class CopTask:
    table: TableInfo
    scan_cols: List[SchemaCol]  # schema cols with store offsets
    dag_execs: List = dc_field(default_factory=list)  # IR nodes after scan
    out_schema: Schema = None  # current output schema of the DAG
    partial_agg: Optional[Tuple[List[Expression], List[AggDesc]]] = None
    # partitioned tables: pruned per-partition key ranges + names (EXPLAIN)
    ranges: Optional[List[KeyRange]] = None
    partitions: Optional[List[str]] = None

    def scan_pos_map(self) -> dict:
        return {c.uid: i for i, c in enumerate(self.scan_cols)}


class PhysTableReader(PhysicalPlan):
    """Root-side reader driving the cop DAG over all regions."""

    def __init__(self, schema: Schema, task: CopTask, keep_order: bool,
                 ranges: Optional[List[KeyRange]] = None):
        super().__init__(schema, [])
        self.cop = task
        self.keep_order = keep_order
        if ranges is None:
            ranges = task.ranges  # pruned partition ranges ([] = all pruned)
        self.ranges = (ranges if ranges is not None
                       else [KeyRange(task.table.id, 0, INF)])
        scan = TableScanIR(
            task.table.id,
            [c.store_offset for c in task.scan_cols],
            [c.ftype for c in task.scan_cols],
        )
        self.dag = DAG([scan] + task.dag_execs)

    def task(self) -> str:
        return "root"

    def info(self) -> str:
        parts = [f"table:{self.cop.table.name}"]
        if self.cop.partitions is not None:
            parts.append("partition:" + ",".join(self.cop.partitions))
        if self.keep_order:
            parts.append("keep-order")
        return ", ".join(parts)

    def build(self, ctx):
        from ..executor import TableReaderExec

        return TableReaderExec(ctx, self.dag, self.ranges,
                               self.dag.output_ftypes(),
                               self.keep_order, self.id)

    def explain_tree(self, indent: int = 0, lines=None):
        lines = lines if lines is not None else []
        pad = ("  " * indent + "└─") if indent else ""
        lines.append((f"{pad}{self.name}_{self.id}", self._est_str(),
                      self.task(), self.info()))
        for i, ex in enumerate(self.dag.executors):
            pad2 = "  " * (indent + 1 + i) + "└─"
            nm = type(ex).__name__.replace("IR", "")
            info = ""
            if isinstance(ex, TableScanIR):
                info = f"table:{self.cop.table.name}, cols:{ex.columns}"
            elif isinstance(ex, SelectionIR):
                info = ", ".join(str(c) for c in ex.conditions)
            elif isinstance(ex, AggregationIR):
                info = (f"group:[{', '.join(map(str, ex.group_by))}] "
                        f"aggs:[{', '.join(map(str, ex.aggs))}] {ex.mode}")
            elif isinstance(ex, TopNIR):
                info = f"limit:{ex.limit}"
            elif isinstance(ex, LimitIR):
                info = f"limit:{ex.limit}"
            else:
                from ..copr.ir import JoinLookupIR, JoinProbeIR

                if isinstance(ex, JoinProbeIR):
                    info = f"runtime filter: {ex.key} in build keys"
                elif isinstance(ex, JoinLookupIR):
                    info = (f"inner join on {ex.key}, "
                            f"{len(ex.payload_ftypes)} payload cols "
                            "(broadcast build)")
            lines.append((f"{pad2}{nm}", "", "cop[tpu]", info))
        return lines


class PhysDeviceJoinReader(PhysicalPlan):
    """Broadcast lookup join pushed into the cop task: the build subplan
    runs root-side first, its sorted unique keys + payload columns ship to
    every mesh shard, and the probe table's device DAG completes
    scan -> filter -> JOIN -> partial aggregation on chip
    (copr/ir.py JoinLookupIR; the reference's executor/join.go HashJoin
    role, relocated into the coprocessor)."""

    def __init__(self, schema: Schema, reader: PhysTableReader,
                 build: PhysicalPlan, build_key_pos: int,
                 payload_pos: List[int], filter_id: int = 0):
        super().__init__(schema, [build])
        self.reader = reader
        self.build_plan = build
        self.build_key_pos = build_key_pos
        self.payload_pos = payload_pos
        self.filter_id = filter_id

    def task(self) -> str:
        return "root"

    def info(self) -> str:
        return (f"build key @{self.build_key_pos}, "
                f"payload cols {self.payload_pos} -> cop join")

    def build(self, ctx):
        from ..executor.readers import DeviceJoinReaderExec

        return DeviceJoinReaderExec(
            ctx, self.reader.build(ctx), self.build_plan.build(ctx),
            self.build_key_pos, self.payload_pos, self.filter_id, self.id)

    def explain_tree(self, indent: int = 0, lines=None):
        lines = lines if lines is not None else []
        pad = ("  " * indent + "└─") if indent else ""
        lines.append((f"{pad}{self.name}_{self.id}", self._est_str(), "root",
                      self.info()))
        self.reader.explain_tree(indent + 1, lines)
        self.build_plan.explain_tree(indent + 1, lines)
        return lines


class PhysExchangeSender(PhysTableReader):
    """MPP fragment boundary: this scan's shards hash-partition their
    rows by the join key and exchange them across the mesh
    (tipb.ExchangeSender with ExchangeType Hash; TiFlash's
    mpp.ExchangeSenderBlockInputStream role, realized as a
    `jax.lax.all_to_all` inside the shard_map program)."""

    def __init__(self, schema: Schema, task: CopTask, key_pos: List[int],
                 ranges: Optional[List[KeyRange]] = None,
                 elided: bool = False):
        super().__init__(schema, task, keep_order=False, ranges=ranges)
        self.key_pos = list(key_pos)  # scan positions of the join key(s)
        # co-partitioned elision: this fragment IS already partitioned on
        # the join key (hash-partitioned table), so no exchange runs —
        # the node renders as a plain MPP scan
        self.elided = elided

    @property
    def name(self) -> str:
        return "MPPScan" if self.elided else "ExchangeSender"

    def task(self) -> str:
        return "mpp[tpu]"

    def info(self) -> str:
        key = ", ".join(self.cop.scan_cols[k].name for k in self.key_pos)
        if self.elided:
            return (f"co-partitioned on {key} "
                    f"(hash, {len(self.cop.table.partition_info.defs)} "
                    f"partitions), table:{self.cop.table.name}")
        return (f"ExchangeType: HashPartition, key:{key}, "
                f"table:{self.cop.table.name}")


class PhysExchangeReceiver(PhysicalPlan):
    """Receiving end of the exchange: reassembles one hash partition per
    mesh shard (tipb.ExchangeReceiver).  Pure plan-shape marker — the
    sender/receiver pair compiles into the all_to_all collective."""

    def __init__(self, sender: PhysExchangeSender):
        super().__init__(sender.schema, [sender])

    def task(self) -> str:
        return "mpp[tpu]"

    def info(self) -> str:
        return "stream: hash-partitioned"


class PhysMPPJoin(PhysicalPlan):
    """Device-resident partitioned shuffle join over the mesh: children
    = [left receiver, right receiver] in schema order; both sides stay
    on device, partitions exchange via all_to_all, and the
    co-partitioned local join (+ optional scalar partial aggregation)
    completes inside the same compiled program.  Strategy ladder at
    runtime: shuffle -> broadcast -> host hash join (mpp/engine.py)."""

    def __init__(self, left_recv, right_recv, kind: str,
                 probe_is_left: bool, schema: Schema,
                 left_keys: List[Expression], right_keys: List[Expression],
                 aggs=None, group_by=None, group_budget: int = 0,
                 reason: str = "", elided: bool = False):
        super().__init__(schema, [left_recv, right_recv])
        self.kind = kind
        self.probe_is_left = probe_is_left
        self.left_keys = left_keys
        self.right_keys = right_keys
        self.aggs = aggs  # partial-agg pushdown (joined layout)
        # grouped partial-agg pushdown: GROUP BY exprs (joined layout) +
        # the cost-model group budget the device checks at runtime
        self.group_by = group_by
        self.group_budget = group_budget
        self.reason = reason  # cost-choice note surfaced in EXPLAIN
        # co-partitioned elision: children are bare MPPScan fragments
        # (no sender/receiver pair); the join runs per partition pair
        self.elided = elided

    def _sender(self, child) -> "PhysExchangeSender":
        return child if isinstance(child, PhysExchangeSender) \
            else child.children[0]

    @property
    def probe_sender(self) -> "PhysExchangeSender":
        return self._sender(self.children[0 if self.probe_is_left else 1])

    @property
    def build_sender(self) -> "PhysExchangeSender":
        return self._sender(self.children[1 if self.probe_is_left else 0])

    def info(self) -> str:
        keys = ", ".join(
            f"{l}=={r}" for l, r in zip(self.left_keys, self.right_keys))
        s = f"{self.kind} [{keys}] "
        s += "exchange elided (co-partitioned)" if self.elided else "shuffle"
        s += ", build:" + ("right" if self.probe_is_left else "left")
        if self.aggs is not None:
            s += f", partial aggs:[{', '.join(map(str, self.aggs))}]"
        if self.group_by:
            s += (f", group by:[{', '.join(map(str, self.group_by))}]"
                  f" budget:{self.group_budget}")
        if self.reason:
            s += f" ({self.reason})"
        return s

    def build(self, ctx):
        from ..mpp import MPPJoinSide, MPPJoinSpec, MPPReaderExec

        def side(sender: PhysExchangeSender) -> MPPJoinSide:
            return MPPJoinSide(
                table_id=sender.cop.table.id,
                dag=sender.dag.to_dict(),
                ranges=list(sender.ranges),
                key_pos=list(sender.key_pos),
                out_ftypes=sender.dag.output_ftypes(),
            )

        spec = MPPJoinSpec(
            probe=side(self.probe_sender), build=side(self.build_sender),
            kind=self.kind, probe_is_left=self.probe_is_left,
            aggs=self.aggs, group_by=self.group_by,
            group_budget=self.group_budget)
        if self.elided:
            # partition pairs aligned by ordinal: partition i of the
            # probe table joins ONLY partition i of the build table
            ppi = self.probe_sender.cop.table.partition_info
            bpi = self.build_sender.cop.table.partition_info
            spec.copartitions = list(zip(
                (d.id for d in ppi.defs), (d.id for d in bpi.defs)))
        return MPPReaderExec(ctx, spec, self.schema.ftypes(), self.id)


class PhysMPPJoinTree(PhysicalPlan):
    """Multi-way device-resident join ladder (ISSUE 12): children are
    one ExchangeSender scan fragment per side in JOIN ORDER; each rung
    joins the device-resident intermediate against the next side inside
    one exchange program, and the final phase emits joined rows or the
    on-device partial aggregation.  EXPLAIN shows the chosen join order
    with est_rows per rung; the executor (MPPTreeReaderExec) falls back
    to a chained host hash join when the mesh declines."""

    def __init__(self, senders, rungs, slot_src, out_slots, out_ftypes,
                 schema: Schema, aggs=None, group_by=None,
                 group_budget: int = 0):
        super().__init__(schema, list(senders))
        self.rungs = rungs          # [{side, kind, left_slots, build_pos,
        #                              others, est}]
        self.slot_src = slot_src
        self.out_slots = out_slots
        self.out_ftypes = out_ftypes
        self.aggs = aggs
        self.group_by = group_by
        self.group_budget = group_budget

    @property
    def name(self) -> str:
        return "MPPJoinTree"

    def task(self) -> str:
        return "mpp[tpu]"

    def info(self) -> str:
        order = " -> ".join(c.cop.table.name for c in self.children)
        s = f"order: {order}"
        if self.aggs is not None:
            s += f", partial aggs:[{', '.join(map(str, self.aggs))}]"
        if self.group_by:
            s += (f", group by:[{', '.join(map(str, self.group_by))}]"
                  f" budget:{self.group_budget}")
        return s

    def explain_tree(self, indent: int = 0, lines=None):
        lines = lines if lines is not None else []
        pad = ("  " * indent + "└─") if indent else ""
        lines.append((f"{pad}{self.name}_{self.id}", self._est_str(),
                      self.task(), self.info()))
        for i, r in enumerate(self.rungs):
            pad2 = "  " * (indent + 1) + "└─"
            build = self.children[r["side"]].cop.table.name
            info = (f"{r['kind']} build:{build}, "
                    f"keys:{r['left_slots']}=={r['build_pos']}")
            if r["others"]:
                info += " other:[" + ", ".join(
                    map(str, r["others"])) + "]"
            lines.append((f"{pad2}Rung_{i}", f"{r['est']:.2f}",
                          "mpp[tpu]", info))
        for c in self.children:
            c.explain_tree(indent + 1, lines)
        return lines

    def build(self, ctx):
        from ..mpp import MPPJoinSide
        from ..mpp.jointree import MPPJoinTreeSpec, TreeRung
        from ..mpp.reader import MPPTreeReaderExec

        sides = []
        for sender in self.children:
            sides.append(MPPJoinSide(
                table_id=sender.cop.table.id,
                dag=sender.dag.to_dict(),
                ranges=list(sender.ranges),
                key_pos=list(sender.key_pos),
                out_ftypes=sender.dag.output_ftypes(),
            ))
        rungs = [TreeRung(side=r["side"], kind=r["kind"],
                          left_slots=list(r["left_slots"]),
                          build_key_pos=list(r["build_pos"]),
                          other_conds=list(r["others"]),
                          est_rows=float(r["est"]))
                 for r in self.rungs]
        spec = MPPJoinTreeSpec(
            sides=sides, rungs=rungs, slot_src=list(self.slot_src),
            out_slots=list(self.out_slots),
            out_ftypes=list(self.out_ftypes),
            aggs=self.aggs, group_by=self.group_by,
            group_budget=self.group_budget)
        return MPPTreeReaderExec(ctx, spec, self.schema.ftypes(), self.id)


class PhysIndexLookUp(PhysicalPlan):
    """Index-range read: binary search the sorted index for handles, sparse
    block gather for rows (root task, host path — the OLTP lane)."""

    def __init__(self, schema: Schema, table: TableInfo, index_name: str,
                 index_offsets, rng, all_conds, residual_conds,
                 point_get: bool = False):
        super().__init__(schema, [])
        self.table = table
        self.index_name = index_name
        self.index_offsets = index_offsets
        self.rng = rng
        self.all_conds = all_conds
        self.residual_conds = residual_conds
        self.point_get = point_get

    @property
    def name(self) -> str:
        return "PointGet" if self.point_get else "IndexLookUp"

    def info(self) -> str:
        r = self.rng
        parts = [f"table:{self.table.name}", f"index:{self.index_name}"]
        if r.eq_prefix:
            parts.append(f"eq:{r.eq_prefix}")
        if r.low is not None or r.high is not None:
            lo = "(" if r.low_open else "["
            hi = ")" if r.high_open else "]"
            parts.append(f"range:{lo}{r.low}, {r.high}{hi}")
        return ", ".join(parts)

    def build(self, ctx):
        from ..executor.index_reader import IndexLookUpExec

        offsets = [c.store_offset for c in self.schema.cols]
        return IndexLookUpExec(
            ctx, self.table, list(self.index_offsets), self.rng,
            offsets, list(range(len(offsets))), self.all_conds,
            self.residual_conds, plan_id=self.id,
        )


class PhysIndexReader(PhysicalPlan):
    """Covering index-only scan (executor/distsql.go:317 IndexReader): the
    schema is served straight from the sorted index's key columns — the
    table is never touched."""

    def __init__(self, schema: Schema, table: TableInfo, index_name: str,
                 index_offsets: List[int], rng, out_pos: List[int],
                 all_conds, residual_conds):
        super().__init__(schema, [])
        self.table = table
        self.index_name = index_name
        self.index_offsets = index_offsets  # FULL index column offsets
        self.rng = rng
        self.out_pos = out_pos
        self.all_conds = all_conds
        self.residual_conds = residual_conds

    @property
    def name(self) -> str:
        return "IndexReader"

    def info(self) -> str:
        r = self.rng
        parts = [f"table:{self.table.name}", f"index:{self.index_name}",
                 "covering"]
        if r.eq_prefix:
            parts.append(f"eq:{r.eq_prefix}")
        if r.low is not None or r.high is not None:
            lo = "(" if r.low_open else "["
            hi = ")" if r.high_open else "]"
            parts.append(f"range:{lo}{r.low}, {r.high}{hi}")
        return ", ".join(parts)

    def build(self, ctx):
        from ..executor.index_reader import IndexReaderExec

        return IndexReaderExec(ctx, self.table, list(self.index_offsets),
                               self.rng, list(self.out_pos),
                               self.residual_conds, self.all_conds,
                               plan_id=self.id)


class PhysBatchPointGet(PhysicalPlan):
    """Multi-key point read over a unique index
    (executor/batch_point_get.go:1-176)."""

    def __init__(self, schema: Schema, table: TableInfo, index_name: str,
                 index_offsets: List[int], keys: List[tuple],
                 all_conds, residual_conds):
        super().__init__(schema, [])
        self.table = table
        self.index_name = index_name
        self.index_offsets = index_offsets
        self.keys = keys
        self.all_conds = all_conds
        self.residual_conds = residual_conds

    @property
    def name(self) -> str:
        return "Batch_Point_Get"

    def info(self) -> str:
        return (f"table:{self.table.name}, index:{self.index_name}, "
                f"keys:{len(self.keys)}")

    def build(self, ctx):
        from ..executor.index_reader import BatchPointGetExec

        offsets = [c.store_offset for c in self.schema.cols]
        return BatchPointGetExec(
            ctx, self.table, list(self.index_offsets), list(self.keys),
            offsets, list(range(len(offsets))), self.all_conds,
            self.residual_conds, plan_id=self.id)


class PhysUnionScan(PhysicalPlan):
    """Dirty-table scan merging the txn buffer (no pushdown)."""

    def __init__(self, schema: Schema, table: TableInfo,
                 conds: List[Expression]):
        super().__init__(schema, [])
        self.table = table
        self.conds = conds

    def info(self) -> str:
        return f"table:{self.table.name}, dirty"

    def build(self, ctx):
        from ..executor import UnionScanExec

        offsets = [c.store_offset for c in self.schema.cols]
        pos = {c.uid: i for i, c in enumerate(self.schema.cols)}
        conds = [c.remap_columns(pos) for c in self.conds]
        return UnionScanExec(ctx, self.table, offsets, conds,
                             with_handle=False, plan_id=self.id)


class PhysSelection(PhysicalPlan):
    def __init__(self, child: PhysicalPlan, conds: List[Expression]):
        super().__init__(child.schema, [child])
        self.conds = conds

    def info(self) -> str:
        return ", ".join(str(c) for c in self.conds)

    def build(self, ctx):
        from ..executor import SelectionExec

        return SelectionExec(ctx, self.children[0].build(ctx), self.conds,
                             self.id)


class PhysProjection(PhysicalPlan):
    def __init__(self, child: PhysicalPlan, exprs: List[Expression],
                 schema: Schema):
        super().__init__(schema, [child])
        self.exprs = exprs

    def info(self) -> str:
        return ", ".join(str(e) for e in self.exprs)

    def build(self, ctx):
        from ..executor import ProjectionExec

        return ProjectionExec(ctx, self.children[0].build(ctx), self.exprs,
                              self.id)


class PhysHashAgg(PhysicalPlan):
    def __init__(self, child: PhysicalPlan, group_by: List[Expression],
                 aggs: List[AggDesc], partial_input: bool, schema: Schema):
        super().__init__(schema, [child])
        self.group_by = group_by
        self.aggs = aggs
        self.partial_input = partial_input

    def info(self) -> str:
        mode = "final" if self.partial_input else "complete"
        return (f"group:[{', '.join(map(str, self.group_by))}] "
                f"funcs:[{', '.join(map(str, self.aggs))}] mode:{mode}")

    def build(self, ctx):
        from ..executor import HashAggExec

        return HashAggExec(ctx, self.children[0].build(ctx), self.group_by,
                           self.aggs, self.partial_input, self.id)


class PhysStreamAgg(PhysicalPlan):
    def __init__(self, child: PhysicalPlan, group_by, aggs, partial_input,
                 schema: Schema):
        super().__init__(schema, [child])
        self.group_by = group_by
        self.aggs = aggs
        self.partial_input = partial_input

    def info(self) -> str:
        return (f"group:[{', '.join(map(str, self.group_by))}] "
                f"funcs:[{', '.join(map(str, self.aggs))}]")

    def build(self, ctx):
        from ..executor import StreamAggExec

        return StreamAggExec(ctx, self.children[0].build(ctx), self.group_by,
                             self.aggs, self.partial_input, self.id)


class PhysHashJoin(PhysicalPlan):
    """children = [left, right] in schema order; build_right selects which
    child is materialized into the hash table."""

    def __init__(self, left: PhysicalPlan, right: PhysicalPlan, kind: str,
                 left_keys: List[Expression], right_keys: List[Expression],
                 other_conds: List[Expression], build_right: bool,
                 schema: Schema, rf_build_key: Optional[int] = None,
                 rf_filter_id: int = 0):
        super().__init__(schema, [left, right])
        self.kind = kind
        self.left_keys = left_keys
        self.right_keys = right_keys
        self.other_conds = other_conds
        self.build_right = build_right
        # index of the eq-key pair whose build-side distinct values are
        # shipped to the probe reader's device DAG as a runtime semi-join
        # filter (JoinProbeIR); None = no runtime filter
        self.rf_build_key = rf_build_key
        self.rf_filter_id = rf_filter_id

    def info(self) -> str:
        keys = ", ".join(
            f"{l}=={r}" for l, r in zip(self.left_keys, self.right_keys)
        )
        side = "build:right" if self.build_right else "build:left"
        s = f"{self.kind} [{keys}] {side}"
        if self.rf_build_key is not None:
            s += " runtime-filter"
        if self.other_conds:
            s += " other:[" + ", ".join(map(str, self.other_conds)) + "]"
        return s

    def build(self, ctx):
        from ..executor import HashJoinExec

        left = self.children[0].build(ctx)
        right = self.children[1].build(ctx)
        if self.build_right:
            build_exec, probe_exec, probe_is_left = right, left, True
            bkeys, pkeys = self.right_keys, self.left_keys
        else:
            build_exec, probe_exec, probe_is_left = left, right, False
            bkeys, pkeys = self.left_keys, self.right_keys
        rf_reader = probe_exec if self.rf_build_key is not None else None
        return HashJoinExec(ctx, build_exec, probe_exec, self.kind,
                            bkeys, pkeys, self.other_conds,
                            probe_is_left=probe_is_left, plan_id=self.id,
                            rf_reader=rf_reader,
                            rf_key_idx=self.rf_build_key or 0,
                            rf_filter_id=self.rf_filter_id)


class PhysIndexJoin(PhysicalPlan):
    """Index lookup join family (index_lookup_join.go:1-687,
    index_lookup_hash_join.go, index_lookup_merge_join.go): children =
    [outer]; the inner side is a (table, index) probe per outer batch."""

    VARIANT_NAMES = {"lookup": "IndexLookUpJoin",
                     "hash": "IndexLookUpHashJoin",
                     "merge": "IndexLookUpMergeJoin"}

    def __init__(self, outer: PhysicalPlan, kind: str, table: TableInfo,
                 index_name: str, index_offsets: List[int],
                 outer_keys: List[Expression], fetch_offsets: List[int],
                 out_pick: List[int], inner_conds: List[Expression],
                 other_conds: List[Expression], outer_is_left: bool,
                 variant: str, schema: Schema):
        super().__init__(schema, [outer])
        self.kind = kind
        self.table = table
        self.index_name = index_name
        self.index_offsets = index_offsets
        self.outer_keys = outer_keys
        self.fetch_offsets = fetch_offsets
        self.out_pick = out_pick
        self.inner_conds = inner_conds
        self.other_conds = other_conds
        self.outer_is_left = outer_is_left
        self.variant = variant

    @property
    def name(self) -> str:
        return self.VARIANT_NAMES.get(self.variant, "IndexLookUpJoin")

    def info(self) -> str:
        keys = ", ".join(str(k) for k in self.outer_keys)
        s = (f"{self.kind} inner:{self.table.name}, "
             f"index:{self.index_name}, outer key:[{keys}]")
        if self.inner_conds:
            s += " inner-cond:[" + ", ".join(map(str, self.inner_conds)) + "]"
        if self.other_conds:
            s += " other:[" + ", ".join(map(str, self.other_conds)) + "]"
        return s

    def explain_tree(self, indent: int = 0, lines=None):
        lines = lines if lines is not None else []
        pad = ("  " * indent + "└─") if indent else ""
        lines.append((f"{pad}{self.name}_{self.id}", self._est_str(),
                      self.task(), self.info()))
        pad2 = "  " * (indent + 1) + "└─"
        lines.append((f"{pad2}IndexRangeScan(Probe)", "", "root",
                      f"table:{self.table.name}, index:{self.index_name}"))
        for c in self.children:
            c.explain_tree(indent + 1, lines)
        return lines

    def build(self, ctx):
        from ..executor.index_join import IndexLookUpJoinExec

        return IndexLookUpJoinExec(
            ctx, self.children[0].build(ctx), self.table,
            list(self.index_offsets), self.outer_keys,
            list(self.fetch_offsets), list(self.out_pick),
            self.inner_conds, self.other_conds, self.kind,
            self.outer_is_left, self.variant, self.id)


class PhysMergeJoin(PhysicalPlan):
    """Sort-merge join over key-sorted children (merge_join.go)."""

    def __init__(self, left, right, kind, left_keys, right_keys,
                 other_conds, schema):
        super().__init__(schema, [left, right])
        self.kind = kind
        self.left_keys = left_keys
        self.right_keys = right_keys
        self.other_conds = other_conds

    def info(self) -> str:
        keys = ", ".join(f"{l}=={r}" for l, r in
                         zip(self.left_keys, self.right_keys))
        return f"{self.kind} [{keys}]"

    def build(self, ctx):
        from ..executor import MergeJoinExec

        return MergeJoinExec(ctx, self.children[0].build(ctx),
                             self.children[1].build(ctx), self.kind,
                             self.left_keys, self.right_keys,
                             self.other_conds, self.id)


class PhysSort(PhysicalPlan):
    def __init__(self, child: PhysicalPlan, items):
        super().__init__(child.schema, [child])
        self.items = items

    def info(self) -> str:
        return ", ".join(f"{e}{' desc' if d else ''}" for e, d in self.items)

    def build(self, ctx):
        from ..executor import SortExec

        return SortExec(ctx, self.children[0].build(ctx), self.items, self.id)


class PhysTopN(PhysicalPlan):
    def __init__(self, child: PhysicalPlan, items, limit: int, offset: int):
        super().__init__(child.schema, [child])
        self.items = items
        self.limit = limit
        self.offset = offset

    def info(self) -> str:
        keys = ", ".join(f"{e}{' desc' if d else ''}" for e, d in self.items)
        return f"[{keys}] limit:{self.limit} offset:{self.offset}"

    def build(self, ctx):
        from ..executor import TopNExec

        return TopNExec(ctx, self.children[0].build(ctx), self.items,
                        self.limit, self.offset, self.id)


class PhysLimit(PhysicalPlan):
    def __init__(self, child: PhysicalPlan, limit: int, offset: int):
        super().__init__(child.schema, [child])
        self.limit = limit
        self.offset = offset

    def info(self) -> str:
        return f"limit:{self.limit} offset:{self.offset}"

    def build(self, ctx):
        from ..executor import LimitExec

        return LimitExec(ctx, self.children[0].build(ctx), self.limit,
                         self.offset, self.id)


class PhysUnion(PhysicalPlan):
    def build(self, ctx):
        from ..executor import UnionExec

        return UnionExec(ctx, [c.build(ctx) for c in self.children],
                         self.schema.ftypes(), self.id)


class PhysDual(PhysicalPlan):
    def __init__(self, schema: Schema, row_count: int):
        super().__init__(schema, [])
        self.row_count = row_count

    def info(self) -> str:
        return f"rows:{self.row_count}"

    def build(self, ctx):
        from ..executor import TableDualExec

        return TableDualExec(ctx, self.schema.ftypes(), self.row_count,
                             self.id)


class PhysMaxOneRow(PhysicalPlan):
    def build(self, ctx):
        from ..executor import MaxOneRowExec

        return MaxOneRowExec(ctx, self.children[0].build(ctx), self.id)


class PhysMemTable(PhysicalPlan):
    def __init__(self, schema: Schema, provider_name: str, conds):
        super().__init__(schema, [])
        self.provider_name = provider_name
        self.conds = conds

    def info(self) -> str:
        return f"table:information_schema.{self.provider_name}"

    def build(self, ctx):
        from ..executor.memtable import MemTableExec

        pos = {c.uid: i for i, c in enumerate(self.schema.cols)}
        conds = [c.remap_columns(pos) for c in self.conds]
        return MemTableExec(ctx, self.provider_name,
                            [c.store_offset for c in self.schema.cols],
                            self.schema.ftypes(), conds, self.id)


class PhysWindow(PhysicalPlan):
    def __init__(self, child: PhysicalPlan, funcs, partition_by, order_by,
                 frame, schema: Schema):
        super().__init__(schema, [child])
        self.funcs = funcs  # [(uid, WindowFuncDesc)] remapped
        self.partition_by = partition_by
        self.order_by = order_by
        self.frame = frame

    def info(self) -> str:
        fns = ", ".join(f.name for _, f in self.funcs)
        parts = ", ".join(str(p) for p in self.partition_by)
        return f"funcs:[{fns}] partition:[{parts}]"

    def build(self, ctx):
        from ..executor.window import WindowExec

        return WindowExec(ctx, self.children[0].build(ctx),
                          [f for _, f in self.funcs], self.partition_by,
                          self.order_by, self.frame, self.id)


# ---------------------------------------------------------------------------
# DML physical wrappers
# ---------------------------------------------------------------------------


class PhysInsert(PhysicalPlan):
    def __init__(self, plan: InsertPlan,
                 select_phys: Optional[PhysicalPlan]):
        super().__init__(Schema([]), [select_phys] if select_phys else [])
        self.plan = plan

    def info(self) -> str:
        return f"table:{self.plan.table.name}"

    def build(self, ctx):
        from ..executor import InsertExec

        child = self.children[0].build(ctx) if self.children else None
        p = self.plan
        rows = None
        if p.rows is not None:
            from .build import DEFAULT_MARKER

            rows = []
            for r in p.rows:
                rows.append([
                    (p.table.columns[off].default
                     if v is DEFAULT_MARKER else v)
                    for v, off in zip(r, p.col_offsets)
                ])
        return InsertExec(ctx, p.table, p.col_offsets, rows, child,
                          p.replace, p.ignore, p.on_dup_update,
                          plan_id=self.id)


class PhysUpdate(PhysicalPlan):
    def __init__(self, plan: UpdatePlan):
        super().__init__(Schema([]), [])
        self.plan = plan

    def info(self) -> str:
        return f"table:{self.plan.table.name}"

    def build(self, ctx):
        from ..executor import UpdateExec

        t = self.plan.table
        readers = _dml_readers(ctx, t, self.plan.conditions, self.id)
        return UpdateExec(ctx, t, readers, self.plan.assignments, self.id)


class PhysDelete(PhysicalPlan):
    def __init__(self, plan: DeletePlan):
        super().__init__(Schema([]), [])
        self.plan = plan

    def info(self) -> str:
        return f"table:{self.plan.table.name}"

    def build(self, ctx):
        from ..executor import DeleteExec

        t = self.plan.table
        readers = _dml_readers(ctx, t, self.plan.conditions, self.id)
        return DeleteExec(ctx, t, readers, self.id)


def _dml_readers(ctx, t: TableInfo, conditions, plan_id: int):
    """(physical id, handle-scan) pairs feeding UPDATE/DELETE: one per
    pruned partition (conditions are full-row-offset exprs, so pruning
    matches by store offset)."""
    from ..executor import UnionScanExec

    offsets = [c.offset for c in t.columns]
    if not t.is_partitioned:
        return [(t.id, UnionScanExec(ctx, t, offsets, conditions,
                                     with_handle=True, plan_id=plan_id))]
    from .partition import prune_partitions

    part_off = t.find_column(t.partition_info.column).offset
    parts = prune_partitions(t, conditions, part_off, by_offset=True)
    return [
        (pd.id, UnionScanExec(ctx, t.partition_table(pd), offsets,
                              conditions, with_handle=True, plan_id=plan_id))
        for pd in parts
    ]


class PhysLoadData(PhysicalPlan):
    def __init__(self, plan: LoadDataPlan):
        super().__init__(Schema([]), [])
        self.plan = plan

    def build(self, ctx):
        from ..executor import LoadDataExec

        p = self.plan
        return LoadDataExec(ctx, p.table, p.path, p.fields_terminated,
                            p.ignore_lines, self.id)


# ---------------------------------------------------------------------------
# logical -> physical conversion (find_best_task analog, rule-based)
# ---------------------------------------------------------------------------


@dataclass
class PhysicalContext:
    storage: object
    dirty_tables: frozenset = frozenset()
    pushdown_blacklist: frozenset = frozenset()
    enable_pushdown: bool = True
    stats: object = None  # StatsHandle
    prefer_merge_join: bool = False  # tidb_opt_prefer_merge_join
    enable_index_join: bool = True  # tidb_opt_enable_index_join
    index_join_variant: str = "lookup"  # tidb_index_join_variant
    # tidb_check_plan: run the lint.plancheck schema/dtype verifier over
    # every finished physical plan (vet-for-plans; cheap host-side walk)
    check_plan: bool = False
    # MPP shuffle-join routing (tidb_allow_mpp / tidb_enforce_mpp /
    # tidb_broadcast_join_threshold_count): build sides at or below the
    # threshold stay on the broadcast/host lanes; bigger ones shuffle
    allow_mpp: bool = True
    enforce_mpp: bool = False
    mpp_threshold: int = 10240


def to_physical(plan: LogicalPlan, pctx: PhysicalContext) -> PhysicalPlan:
    from .logical import LogicalMemTable

    if isinstance(plan, LogicalDataSource):
        return _finish_datasource(plan, pctx)

    if isinstance(plan, LogicalMemTable):
        return PhysMemTable(plan.schema, plan.provider_name,
                            plan.pushed_conds)

    if isinstance(plan, LogicalSelection):
        child_l = plan.children[0]
        if isinstance(child_l, LogicalMemTable):
            child_l.pushed_conds.extend(plan.conds)
            return PhysMemTable(child_l.schema, child_l.provider_name,
                                child_l.pushed_conds)
        if isinstance(child_l, LogicalDataSource):
            child_l.pushed_conds.extend(plan.conds)
            return _finish_datasource(child_l, pctx)
        child = to_physical(child_l, pctx)
        conds = _remap(plan.conds, child.schema)
        return PhysSelection(child, conds)

    if isinstance(plan, LogicalProjection):
        child = to_physical(plan.children[0], pctx)
        exprs = _remap(plan.exprs, child.schema)
        return PhysProjection(child, exprs, plan.schema)

    if isinstance(plan, LogicalAggregation):
        return _physical_agg(plan, pctx)

    if isinstance(plan, LogicalTopN):
        return _physical_topn(plan, pctx)

    if isinstance(plan, LogicalSort):
        child = to_physical(plan.children[0], pctx)
        items = [(e, d) for e, d in
                 zip(_remap([e for e, _ in plan.items], child.schema),
                     [d for _, d in plan.items])]
        return PhysSort(child, items)

    if isinstance(plan, LogicalLimit):
        child, pushed = _try_push_limit(plan, pctx)
        if pushed is not None:
            return pushed
        return PhysLimit(child, plan.limit, plan.offset)

    if isinstance(plan, LogicalJoin):
        return _physical_join(plan, pctx)

    if isinstance(plan, LogicalUnion):
        children = [to_physical(c, pctx) for c in plan.children]
        return PhysUnion(plan.schema, children)

    if isinstance(plan, LogicalDual):
        return PhysDual(plan.schema, plan.row_count)

    if isinstance(plan, LogicalMaxOneRow):
        child = to_physical(plan.children[0], pctx)
        return PhysMaxOneRow(child.schema, [child])

    from ..executor.window import WindowFuncDesc
    from .logical import LogicalWindow

    if isinstance(plan, LogicalWindow):
        child = to_physical(plan.children[0], pctx)
        pos = child.schema.position_map()
        funcs = [
            (uid, WindowFuncDesc(
                f.name, _remap(f.args, child.schema), f.ftype))
            for uid, f in plan.funcs
        ]
        partition = _remap(plan.partition_by, child.schema)
        order = [(e, d) for e, d in zip(
            _remap([e for e, _ in plan.order_by], child.schema),
            [d for _, d in plan.order_by])]
        win_cols = {uid for uid, _ in plan.funcs}
        out_schema = Schema(
            list(child.schema.cols)
            + [c for c in plan.schema.cols if c.uid in win_cols]
        )
        return PhysWindow(child, funcs, partition, order, plan.frame,
                          out_schema)

    raise PlanError(f"no physical impl for {type(plan).__name__}")


def physical_for_stmt(plan, pctx: PhysicalContext) -> PhysicalPlan:
    """Entry covering DML containers too."""
    if isinstance(plan, InsertPlan):
        sub = to_physical(plan.select_plan, pctx) if plan.select_plan else None
        return PhysInsert(plan, sub)
    if isinstance(plan, UpdatePlan):
        return PhysUpdate(plan)
    if isinstance(plan, DeletePlan):
        return PhysDelete(plan)
    if isinstance(plan, LoadDataPlan):
        return PhysLoadData(plan)
    return to_physical(plan, pctx)


# ---- datasource / cop-task assembly ---------------------------------------


def _dict_uids(ds: LogicalDataSource, pctx: PhysicalContext) -> set:
    dict_cols = set()
    for pid in ds.table.physical_ids():
        dict_cols |= pctx.storage.table(pid).dict_encoded_cols()
    return {c.uid for c in ds.schema.cols if c.store_offset in dict_cols}


def _split_pushable(conds, blacklist, dict_uids):
    push, residual = [], []
    for c in conds:
        (push if can_push_expr(c, blacklist, dict_uids) else residual).append(c)
    return push, residual


def _start_cop(ds: LogicalDataSource, pctx: PhysicalContext):
    """Build the cop task skeleton: scan + pushable selection; return
    (CopTask, residual_conds).  For a partitioned table the task carries the
    pruned per-partition ranges (rule_partition_processor.go analog)."""
    task = CopTask(ds.table, list(ds.schema.cols))
    dirty = any(pid in pctx.dirty_tables for pid in ds.table.physical_ids())
    if dirty or not pctx.enable_pushdown:
        return None, list(ds.pushed_conds)
    if ds.table.is_partitioned:
        parts = _pruned_partitions(ds)
        task.ranges = [KeyRange(pd.id, 0, INF) for pd in parts]
        task.partitions = [pd.name for pd in parts]
    dict_uids = _dict_uids(ds, pctx)
    push, residual = _split_pushable(
        ds.pushed_conds, pctx.pushdown_blacklist, dict_uids
    )
    if push:
        pos = task.scan_pos_map()
        task.dag_execs.append(
            SelectionIR([c.remap_columns(pos) for c in push])
        )
    task.out_schema = Schema(task.scan_cols)
    return task, residual


def _pruned_partitions(ds: LogicalDataSource):
    from .partition import partition_uid, prune_partitions

    puid = partition_uid(ds.table, ds.schema)
    if puid is None:
        return list(ds.table.partition_info.defs)
    return prune_partitions(ds.table, ds.pushed_conds, puid)


def _finish_datasource(ds: LogicalDataSource,
                       pctx: PhysicalContext) -> PhysicalPlan:
    ix = _try_index_path(ds, pctx)
    if ix is not None:
        return ix
    task, residual = _start_cop(ds, pctx)
    if task is not None and task.ranges == []:
        return PhysDual(ds.schema, 0)  # every partition pruned
    if task is None:
        if ds.table.is_partitioned:
            # dirty/no-pushdown partitioned scan: one UnionScan per pruned
            # partition, concatenated (each partition is its own physical
            # table to the txn buffer and store)
            parts = _pruned_partitions(ds)
            if not parts:
                return PhysDual(ds.schema, 0)
            kids = [PhysUnionScan(ds.schema, ds.table.partition_table(pd),
                                  list(ds.pushed_conds)) for pd in parts]
            if len(kids) == 1:
                return kids[0]
            return PhysUnion(ds.schema, kids)
        return PhysUnionScan(ds.schema, ds.table, list(ds.pushed_conds))
    reader = PhysTableReader(Schema(task.scan_cols), task, keep_order=False,
                             ranges=ds.ranges)
    out: PhysicalPlan = reader
    if residual:
        out = PhysSelection(reader, _remap(residual, reader.schema))
    return out


def _try_index_path(ds: LogicalDataSource,
                    pctx: PhysicalContext) -> Optional[PhysicalPlan]:
    """Pick an index read over the device scan when the predicate pins a
    unique key or stats say the range is very selective (find_best_task's
    index-path choice, rule-based)."""
    if not ds.pushed_conds or not ds.table.indexes:
        return None
    if ds.table.is_partitioned:
        # sorted indexes are per-partition stores; the index read path
        # addresses a single store — partitioned tables take the pruned
        # mesh-scan path instead
        return None
    from .ranger import build_access_path

    store = pctx.storage.table(ds.table.id)
    by_name = {c.name.lower(): c for c in ds.schema.cols}
    uid_to_off = {c.uid: c.store_offset for c in ds.schema.cols}
    bpg = _try_batch_point_get(ds, store, by_name)
    if bpg is not None:
        return bpg
    best = None  # (score, index, path)
    from ..catalog.schema import STATE_PUBLIC as _PUB

    for ix in ds.table.indexes:
        if ix.state != _PUB:
            continue  # online DDL: only public indexes serve reads
        uids = []
        for cname in ix.columns:
            sc = by_name.get(cname.lower())
            if sc is None:
                break  # column pruned away -> no conds reference it
            uids.append(sc.uid)
        if not uids:
            continue
        path = build_access_path(ds.pushed_conds, uids, uid_to_off, store)
        if path is None:
            continue
        unique_full_eq = (
            (ix.unique or ix.primary)
            and path.rng.full_eq_depth == len(ix.columns)
            and path.rng.low is None and path.rng.high is None
        )
        score = (2 if unique_full_eq else 0) + path.rng.full_eq_depth \
            + (0.5 if path.rng.low is not None or path.rng.high is not None
               else 0)
        if best is None or score > best[0]:
            best = (score, ix, path, unique_full_eq)
    if best is None:
        return None
    _, ix, path, unique_full_eq = best
    if not unique_full_eq:
        # non-unique: only beat the device brute-force scan when stats say
        # the range is tiny
        if pctx.stats is None:
            return None
        offmap = {c.uid: c.store_offset for c in ds.schema.cols}
        remapped = [c.remap_columns(offmap) for c in path.access_conds]
        sel = pctx.stats.estimate_selectivity(ds.table.id, remapped)
        total = store.base_rows + len(store.delta)
        if pctx.stats.get(ds.table.id) is None or \
                sel * total > max(1000.0, 0.05 * total):
            return None
    index_offsets = [store.col_index(c) for c in ix.columns[:max(
        path.rng.full_eq_depth + (1 if path.rng.low is not None
                                  or path.rng.high is not None else 0), 1)]]
    pos = {c.uid: i for i, c in enumerate(ds.schema.cols)}
    all_conds = [c.remap_columns(pos) for c in ds.pushed_conds]
    residual = [c.remap_columns(pos) for c in path.residual_conds]
    if not unique_full_eq:
        cov = _try_covering_reader(ds, store, ix, path, all_conds, residual)
        if cov is not None:
            return cov
    return PhysIndexLookUp(ds.schema, ds.table, ix.name, index_offsets,
                           path.rng, all_conds, residual,
                           point_get=unique_full_eq)


def _try_covering_reader(ds: LogicalDataSource, store, ix, path,
                         all_conds, residual) -> Optional[PhysicalPlan]:
    """Upgrade an index path to a covering IndexReader when the output is
    served entirely by the index key columns (executor/distsql.go:317):
    skips the table-side sparse gather altogether."""
    name_to_ixpos = {n.lower(): i for i, n in enumerate(ix.columns)}
    out_pos = []
    for c in ds.schema.cols:
        p = name_to_ixpos.get(c.name.lower())
        if p is None:
            return None  # not covering
        out_pos.append(p)
    # NULL safety: the sorted index EXCLUDES rows with NULL in any key
    # column (store/index.py SortedIndex).  A covering read is sound only
    # when every nullable key column is pinned by a null-rejecting access
    # cond — i.e. sits inside the constrained prefix of the range walk.
    constrained = path.rng.full_eq_depth + (
        1 if path.rng.low is not None or path.rng.high is not None else 0)
    for depth, cname in enumerate(ix.columns):
        off = store.col_index(cname)
        if ds.table.columns[off].ftype.nullable and depth >= constrained:
            return None
    full_offsets = [store.col_index(c) for c in ix.columns]
    return PhysIndexReader(ds.schema, ds.table, ix.name, full_offsets,
                           path.rng, out_pos, all_conds, residual)


def _try_batch_point_get(ds: LogicalDataSource, store,
                         by_name) -> Optional[PhysicalPlan]:
    """`key IN (c1..ck)` over a single-column unique index becomes one
    multi-key point read (executor/batch_point_get.go:1-176)."""
    from ..catalog.schema import STATE_PUBLIC as _PUB
    from .ranger import _const_key

    for ix in ds.table.indexes:
        if ix.state != _PUB or not (ix.unique or ix.primary):
            continue
        if len(ix.columns) != 1:
            continue
        sc = by_name.get(ix.columns[0].lower())
        if sc is None:
            continue
        for cond in ds.pushed_conds:
            if not (isinstance(cond, ScalarFunc) and cond.name == "in"
                    and len(cond.args) >= 2
                    and isinstance(cond.args[0], ColumnExpr)
                    and all(isinstance(a, Constant) for a in cond.args[1:])):
                continue
            col = cond.args[0]
            uid = col.unique_id if col.unique_id >= 0 else col.index
            if uid != sc.uid:
                continue
            off = sc.store_offset
            keys, seen = [], set()
            for a in cond.args[1:]:
                ke = _const_key(col, a, store, off, "=")
                if ke is None or ke[1] != "=":
                    continue  # NULL / unrepresentable -> matches nothing
                if ke[0] not in seen:
                    seen.add(ke[0])
                    keys.append((ke[0],))
            pos = {c.uid: i for i, c in enumerate(ds.schema.cols)}
            all_conds = [c.remap_columns(pos) for c in ds.pushed_conds]
            residual = [c.remap_columns(pos) for c in ds.pushed_conds
                        if c is not cond]
            return PhysBatchPointGet(ds.schema, ds.table, ix.name, [off],
                                     keys, all_conds, residual)
    return None


def _physical_agg(plan: LogicalAggregation,
                  pctx: PhysicalContext) -> PhysicalPlan:
    child_l = plan.children[0]
    # a pin-point index read beats the device scan for OLTP-shaped aggs
    if isinstance(child_l, LogicalDataSource):
        ix = _try_index_path(child_l, pctx)
        if ix is not None:
            gb = _remap(plan.group_by, ix.schema)
            aggs = [a.remap_columns(ix.schema.position_map())
                    for a in plan.aggs]
            return PhysHashAgg(ix, gb, aggs, False, plan.schema)
    # direct cop-task child (DataSource or Selection(DataSource) already
    # collapsed by rules into ds.pushed_conds)
    if isinstance(child_l, LogicalDataSource) and pctx.enable_pushdown:
        task, residual = _start_cop(child_l, pctx)
        if task is not None and task.ranges == []:
            task = None  # every partition pruned: plan over an empty Dual
        if task is not None and not residual and plan.aggs:
            dict_uids = _dict_uids(child_l, pctx)
            ok = all(
                can_push_expr(g, pctx.pushdown_blacklist, dict_uids)
                or _is_plain_col(g)
                # computed STRING keys over dict columns lower to device
                # dict-code re-mapping (ISSUE 11) — push the agg
                or can_remap_group_key(g, dict_uids)
                for g in plan.group_by
            ) and all(
                can_push_agg(a, pctx.pushdown_blacklist, dict_uids)
                for a in plan.aggs
            )
            if ok:
                pos = task.scan_pos_map()
                gb = [g.remap_columns(pos) for g in plan.group_by]
                aggs = [a.remap_columns(pos) for a in plan.aggs]
                task.dag_execs.append(AggregationIR(gb, aggs, mode="partial"))
                # first_row partials are position-sensitive: region chunks
                # must merge in handle order or the "first" value depends on
                # task completion order (the mesh path is deterministic —
                # global min row index — so the fan-out path must match)
                has_first = any(a.name == "first_row" for a in aggs)
                reader = PhysTableReader(
                    _partial_schema(plan), task, keep_order=has_first,
                    ranges=child_l.ranges,
                )
                # final merge positions: [keys..., states...] by position
                n = len(plan.group_by)
                fin_gb = [
                    ColumnExpr(i, g.ftype, str(g), -1)
                    for i, g in enumerate(plan.group_by)
                ]
                return PhysHashAgg(reader, fin_gb, plan.aggs, True,
                                   plan.schema)
    # agg over an eligible inner join: push scan+filter+JOIN+partial agg
    # into one device program (the Q3/SSB star-aggregate shape); when the
    # build side is too big to broadcast, the MPP shuffle join carries
    # the same partial-agg pushdown (scalar aggs)
    if isinstance(child_l, LogicalJoin) and pctx.enable_pushdown:
        dj = _try_device_join_agg(plan, child_l, pctx)
        if dj is not None:
            return dj
        mj = _try_mpp_join_agg(plan, child_l, pctx)
        if mj is not None:
            return mj
    # agg over a multi-way join TREE (optionally through a projection,
    # the derived-table shape of Q7/Q8/Q9): the join-tree compiler
    # lowers the whole ladder + partial agg onto the device (ISSUE 12)
    if isinstance(child_l, (LogicalJoin, LogicalProjection)) \
            and pctx.enable_pushdown:
        from .jointree import try_jointree_agg

        tj = try_jointree_agg(plan, child_l, pctx)
        if tj is not None:
            return tj
    child = to_physical(child_l, pctx)
    gb = _remap(plan.group_by, child.schema)
    aggs = [a.remap_columns(child.schema.position_map()) for a in plan.aggs]
    return PhysHashAgg(child, gb, aggs, False, plan.schema)


# device-join gates: the build side is broadcast to every shard, so it must
# be decisively the small side; the key must be int-domain and plan-time
# unique (lookup join semantics: <= 1 match per probe row)
DEVICE_JOIN_BUILD_MAX = 2_000_000
_DJ_KEY_KINDS = (TypeKind.INT, TypeKind.UINT, TypeKind.DECIMAL,
                 TypeKind.DATE)
_DJ_PAYLOAD_KINDS = _DJ_KEY_KINDS + (TypeKind.FLOAT, TypeKind.BOOL)


def _build_key_unique(plan, uid: int) -> bool:
    """Conservative plan-time uniqueness: does each output row of `plan`
    carry a distinct value of column `uid`?  (util/ranger + schema key
    inference role — TiDB's schema.Keys/maxOneRow propagation.)"""
    from .logical import (LogicalAggregation, LogicalDataSource, LogicalJoin,
                          LogicalProjection, LogicalSelection)

    if isinstance(plan, LogicalDataSource):
        sc = next((c for c in plan.schema.cols if c.uid == uid), None)
        if sc is None:
            return False
        t = plan.table
        if 0 <= t.pk_is_handle < len(t.columns) \
                and t.columns[t.pk_is_handle].name == sc.name:
            return True
        return any((ix.unique or ix.primary) and len(ix.columns) == 1
                   and ix.columns[0] == sc.name for ix in t.indexes)
    if isinstance(plan, LogicalSelection):
        return _build_key_unique(plan.children[0], uid)
    if isinstance(plan, LogicalProjection):
        if not any(c.uid == uid for c in plan.schema.cols):
            return False
        return _build_key_unique(plan.children[0], uid)
    if isinstance(plan, LogicalAggregation):
        # the SOLE group-by key is unique per output row by construction;
        # with multiple keys the same value of one key can repeat
        return (len(plan.group_by) == 1
                and isinstance(plan.group_by[0], ColumnExpr)
                and plan.group_by[0].unique_id == uid)
    if isinstance(plan, LogicalJoin):
        left, right = plan.children
        in_left = any(c.uid == uid for c in left.schema.cols)
        side, other = (left, right) if in_left else (right, left)
        if not _build_key_unique(side, uid):
            return False
        if plan.kind in ("semi", "anti_semi") and in_left:
            return True  # semi joins only filter left rows
        if plan.kind == "inner" and len(plan.eq_conds) == 1:
            # each side row matches <= 1 other row iff the other side's
            # eq key is unique there
            le, re_ = plan.eq_conds[0]
            oe = re_ if in_left else le
            if isinstance(oe, ColumnExpr) and oe.unique_id >= 0:
                return _build_key_unique(other, oe.unique_id)
        return False
    return False


def _try_device_join_agg(plan: LogicalAggregation, join: LogicalJoin,
                         pctx: PhysicalContext):
    """Agg(InnerJoin(probe datasource, small unique-key build)) ->
    final agg over a DeviceJoinReader whose cop DAG is
    scan -> selection -> JoinLookupIR -> partial AggregationIR.
    Returns None whenever any gate fails (the generic paths take over)."""
    from ..copr.ir import JoinLookupIR

    if join.kind != "inner" or len(join.eq_conds) != 1 or join.other_conds:
        return None
    if not plan.aggs:
        return None
    if pctx.prefer_merge_join:
        return None  # MERGE_JOIN hint/binding pins the root algorithm
    if pctx.enforce_mpp:
        return None  # tidb_enforce_mpp pins the exchange engine
    left, right = join.children
    le, re_ = join.eq_conds[0]
    for probe_l, build_l, pk_e, bk_e in (
            (left, right, le, re_), (right, left, re_, le)):
        if not isinstance(probe_l, LogicalDataSource):
            continue
        if not isinstance(bk_e, ColumnExpr) or bk_e.unique_id < 0:
            continue
        if pk_e.ftype.kind not in _DJ_KEY_KINDS:
            continue
        # both key sides must share the scaled-int comparison domain
        if bk_e.ftype.kind != pk_e.ftype.kind:
            continue
        if pk_e.ftype.kind == TypeKind.DECIMAL \
                and bk_e.ftype.scale != pk_e.ftype.scale:
            continue
        if not _build_key_unique(build_l, bk_e.unique_id):
            continue
        task, residual = _start_cop(probe_l, pctx)
        if task is None or residual:
            continue
        if task.ranges == []:
            continue  # fully pruned: the Dual path handles it
        if any(not isinstance(ex, SelectionIR) for ex in task.dag_execs):
            continue
        dict_uids = _dict_uids(probe_l, pctx)
        from ..expr.pushdown import can_push_agg, can_push_expr

        if not can_push_expr(pk_e, pctx.pushdown_blacklist, dict_uids):
            continue
        probe_uids = {c.uid for c in probe_l.schema.cols}
        build_pos = {c.uid: i for i, c in enumerate(build_l.schema.cols)}
        # split agg expr refs between probe scan cols and build payload
        refs: set = set()
        for g in plan.group_by:
            g.collect_columns(refs)
        for a in plan.aggs:
            for x in a.args:
                x.collect_columns(refs)
        payload_uids = sorted(u for u in refs if u not in probe_uids)
        if any(u not in build_pos for u in payload_uids):
            continue  # references something outside the join
        payload_cols = [build_l.schema.cols[build_pos[u]]
                        for u in payload_uids]
        if any(c.ftype.kind not in _DJ_PAYLOAD_KINDS for c in payload_cols):
            continue
        if any(a.name == "first_row" and any(
                u not in probe_uids
                for u in _collect(a)) for a in plan.aggs):
            continue  # first_row partials gather from the table
        # size gate (after the cheap structural gates)
        build_phys = to_physical(build_l, pctx)
        build_est = _est_rows(build_phys, pctx)
        probe_est = _est_rows(
            PhysTableReader(Schema(task.scan_cols), task, False,
                            probe_l.ranges), pctx)
        if build_est > DEVICE_JOIN_BUILD_MAX \
                or build_est > 0.5 * max(probe_est, 1):
            continue
        # remap: probe uids -> scan positions; build uids -> payload slots
        scan_w = len(task.scan_cols)
        mapping = dict(task.scan_pos_map())
        for j, u in enumerate(payload_uids):
            mapping[u] = scan_w + j
        gb = [g.remap_columns(mapping) for g in plan.group_by]
        aggs = [a.remap_columns(mapping) for a in plan.aggs]
        if not all(can_push_expr(g, pctx.pushdown_blacklist, dict_uids)
                   or _is_plain_col(g) for g in gb):
            continue
        if not all(can_push_agg(a, pctx.pushdown_blacklist, dict_uids)
                   for a in aggs):
            continue
        pk_pos = pk_e.remap_columns(task.scan_pos_map())
        task.dag_execs.append(JoinLookupIR(
            pk_pos, 0, [c.ftype for c in payload_cols]))
        task.dag_execs.append(AggregationIR(gb, aggs, mode="partial"))
        # first_row partials are position-sensitive: region chunks must
        # merge in handle order (same invariant as the direct agg
        # pushdown path) or "first" depends on task completion order
        has_first = any(a.name == "first_row" for a in aggs)
        reader = PhysTableReader(_partial_schema(plan), task,
                                 keep_order=has_first,
                                 ranges=probe_l.ranges)
        djr = PhysDeviceJoinReader(
            reader.schema, reader, build_phys,
            build_pos[bk_e.unique_id],
            [build_pos[u] for u in payload_uids])
        fin_gb = [ColumnExpr(i, g.ftype, str(g), -1)
                  for i, g in enumerate(plan.group_by)]
        return PhysHashAgg(djr, fin_gb, plan.aggs, True, plan.schema)
    return None


def _collect(a) -> set:
    refs: set = set()
    for x in a.args:
        x.collect_columns(refs)
    return refs


def _partial_schema(plan: LogicalAggregation) -> Schema:
    cols = []
    from .columns import next_uid

    for g in plan.group_by:
        cols.append(SchemaCol(next_uid(), str(g), g.ftype))
    for a in plan.aggs:
        for j, pt in enumerate(a.partial_types()):
            cols.append(SchemaCol(next_uid(), f"{a}#{j}", pt))
    return Schema(cols)


def _physical_topn(plan: LogicalTopN, pctx: PhysicalContext) -> PhysicalPlan:
    child_l = plan.children[0]
    k = plan.limit + plan.offset
    if isinstance(child_l, LogicalDataSource) and pctx.enable_pushdown:
        task, residual = _start_cop(child_l, pctx)
        if task is not None and task.ranges == []:
            task = None
        if task is not None and not residual:
            dict_uids = _dict_uids(child_l, pctx)
            if all(can_push_expr(e, pctx.pushdown_blacklist, dict_uids)
                   or _is_plain_col(e) for e, _ in plan.items):
                pos = task.scan_pos_map()
                items = [(e.remap_columns(pos), d) for e, d in plan.items]
                task.dag_execs.append(TopNIR(items, k))
                reader = PhysTableReader(Schema(task.scan_cols), task,
                                         keep_order=False,
                                         ranges=child_l.ranges)
                ritems = [(e.remap_columns(reader.schema.position_map()), d)
                          for e, d in plan.items]
                return PhysTopN(reader, ritems, plan.limit, plan.offset)
    child = to_physical(child_l, pctx)
    items = [(e, d) for e, d in
             zip(_remap([e for e, _ in plan.items], child.schema),
                 [d for _, d in plan.items])]
    return PhysTopN(child, items, plan.limit, plan.offset)


def _try_push_limit(plan: LogicalLimit, pctx: PhysicalContext):
    child_l = plan.children[0]
    if isinstance(child_l, LogicalDataSource) and pctx.enable_pushdown:
        task, residual = _start_cop(child_l, pctx)
        if task is not None and task.ranges == []:
            task = None
        if task is not None and not residual:
            task.dag_execs.append(LimitIR(plan.limit + plan.offset))
            reader = PhysTableReader(Schema(task.scan_cols), task,
                                     keep_order=False, ranges=child_l.ranges)
            return None, PhysLimit(reader, plan.limit, plan.offset)
    return to_physical(child_l, pctx), None


def _try_index_join(plan: LogicalJoin,
                    pctx: PhysicalContext) -> Optional[PhysicalPlan]:
    """Choose an index lookup join when the inner side is a datasource with
    a usable index on the join keys and the outer side is small (the
    reference's index-join path in planner/core/exhaust_physical_plans.go;
    executors match index_lookup_join.go / _hash_ / _merge_)."""
    if not pctx.enable_index_join or not plan.eq_conds:
        return None
    if plan.kind not in ("inner", "left_outer", "semi", "anti_semi"):
        return None
    from ..catalog.schema import STATE_PUBLIC as _PUB
    from .rules import _bool_ft, _est_member

    sides = [1] + ([0] if plan.kind == "inner" else [])
    for inner_pos in sides:
        inner_l = plan.children[inner_pos]
        outer_l = plan.children[1 - inner_pos]
        if not isinstance(inner_l, LogicalDataSource):
            continue
        if inner_l.table.is_partitioned:
            continue  # index lookups address one partition store
        inner_cols = {c.uid: c for c in inner_l.schema.cols}
        eqmap = {}  # inner col uid -> (outer_expr, compare type, pair)
        for le, re in plan.eq_conds:
            ie, oe = (re, le) if inner_pos == 1 else (le, re)
            ct = common_compare_type(le.ftype, re.ftype)
            if (isinstance(ie, ColumnExpr)
                    and ie.unique_id in inner_cols
                    and ie.unique_id not in eqmap
                    and _ij_type_ok(ct, inner_cols[ie.unique_id].ftype)):
                eqmap[ie.unique_id] = (oe, ct, (le, re))
        if not eqmap:
            continue
        store = pctx.storage.table(inner_l.table.id)
        by_name = {c.name.lower(): c for c in inner_l.schema.cols}
        best = None  # ((prefix_len, unique_full), ix, prefix schema cols)
        for ix in inner_l.table.indexes:
            if ix.state != _PUB:
                continue
            prefix = []
            for cname in ix.columns:
                sc = by_name.get(cname.lower())
                if sc is None or sc.uid not in eqmap:
                    break
                prefix.append(sc)
            if not prefix:
                continue
            score = (len(prefix),
                     1 if ix.unique and len(prefix) == len(ix.columns) else 0)
            if best is None or score > best[0]:
                best = (score, ix, prefix)
        if best is None:
            continue
        _, ix, prefix = best
        # cost gate: the lookup path wins only when the outer side is small
        # relative to the inner table (otherwise the device scan + hash
        # join lane is faster); mirrors the small-outer heuristic of the
        # reference's index-join cost
        outer_est = _est_member(outer_l, pctx)
        inner_rows = store.base_rows + len(store.delta)
        if outer_est > 4096 or outer_est * 16 > max(inner_rows, 1):
            continue
        outer_phys = to_physical(outer_l, pctx)
        omap = outer_phys.schema.position_map()
        outer_keys, index_offsets, chosen = [], [], []
        for sc in prefix:
            oe, ct, pair = eqmap[sc.uid]
            outer_keys.append(_maybe_cast(oe.remap_columns(omap), ct))
            index_offsets.append(sc.store_offset)
            chosen.append(pair)
        outer_is_left = inner_pos == 1
        if outer_is_left:
            pair_cols = list(outer_phys.schema.cols) + list(inner_l.schema.cols)
        else:
            pair_cols = list(inner_l.schema.cols) + list(outer_phys.schema.cols)
        pair_map = {c.uid: i for i, c in enumerate(pair_cols)}
        others = [c.remap_columns(pair_map) for c in plan.other_conds]
        for le, re in plan.eq_conds:
            if any(p[0] is le and p[1] is re for p in chosen):
                continue
            others.append(ScalarFunc(
                "=", [le.remap_columns(pair_map), re.remap_columns(pair_map)],
                _bool_ft(), {}))
        fetch_offsets = [c.store_offset for c in inner_l.schema.cols]
        fmap = {c.uid: i for i, c in enumerate(inner_l.schema.cols)}
        inner_conds = [c.remap_columns(fmap) for c in inner_l.pushed_conds]
        return PhysIndexJoin(
            outer_phys, plan.kind, inner_l.table, ix.name, index_offsets,
            outer_keys, fetch_offsets, list(range(len(fetch_offsets))),
            inner_conds, others, outer_is_left,
            pctx.index_join_variant, plan.schema)
    return None


def _ij_type_ok(ct: FieldType, inner_ft: FieldType) -> bool:
    """The probe compares outer keys (cast to `ct`) against the inner
    index's NATIVE key arrays — only exact-domain matches are safe."""
    intk = (TypeKind.INT, TypeKind.UINT, TypeKind.BOOL,
            TypeKind.DATE, TypeKind.DATETIME)
    if ct.kind != inner_ft.kind and not (
            ct.kind in intk and inner_ft.kind in intk):
        return False
    if inner_ft.kind == TypeKind.DECIMAL and ct.scale != inner_ft.scale:
        return False
    return True


# MPP shuffle joins exchange full column payloads between shards, so the
# output columns must be device-representable (int-domain, float, or
# dict-coded strings the host decodes after readback)
_MPP_OUT_KINDS = _DJ_PAYLOAD_KINDS + (TypeKind.STRING,)


def _mpp_join_parts(join: LogicalJoin, pctx: PhysicalContext):
    """Structural + cost gates for the MPP shuffle join; returns
    (probe_l, build_l, p_task, b_task, pk_pos, bk_pos, probe_is_left,
    build_est, copart) with pk_pos/bk_pos as scan-position LISTS, or
    None.  Mirrors TiFlash's MPP eligibility: int-domain equi-keys
    (multi-column inner joins exchange a mix-hash and re-verify true
    equality on device; build keys may be NON-unique — the local join
    is a two-pass count+emit expansion), plain scan[+selection]
    fragments on both sides."""
    if join.kind not in ("inner", "left_outer") or not join.eq_conds \
            or join.other_conds:
        return None
    # multi-column LEFT-OUTER keys are planner-eligible since ISSUE 11:
    # the engine composes them EXACTLY (stride packing over both sides'
    # column stats — mpp/exchange.pack_keys_exact), so no probe row can
    # lose its NULL-extension slot to a hash collision; key spaces too
    # wide to pack raise MPPIneligible at run time and take the host rung
    if not pctx.allow_mpp or not pctx.enable_pushdown \
            or pctx.prefer_merge_join:
        return None
    if any(not isinstance(le, ColumnExpr) or not isinstance(re_, ColumnExpr)
           for le, re_ in join.eq_conds):
        return None
    left, right = join.children
    les = [le for le, _ in join.eq_conds]
    res = [re_ for _, re_ in join.eq_conds]
    orders = [(left, right, les, res, True)]
    if join.kind == "inner":
        orders.append((right, left, res, les, False))
    for probe_l, build_l, pks, bks, probe_is_left in orders:
        if not isinstance(probe_l, LogicalDataSource) \
                or not isinstance(build_l, LogicalDataSource):
            continue
        copart = False
        if probe_l.table.is_partitioned or build_l.table.is_partitioned:
            # co-partitioned elision (TiFlash's same-zone optimization):
            # both sides HASH-partitioned on the join key with equal
            # partition counts means partition i of one side can only
            # match partition i of the other — the join runs per
            # partition pair with NO exchange operators.  Inner joins
            # with a single key only: a pruned build partition then
            # simply contributes nothing.  Anything else stays
            # per-partition-store sharded and takes the host lanes.
            copart = (join.kind == "inner" and len(pks) == 1
                      and _co_partitioned(probe_l, pks[0], build_l,
                                          bks[0]))
            if not copart:
                continue
        if any(pk.ftype.kind not in _DJ_KEY_KINDS
               or bk.ftype.kind != pk.ftype.kind
               for pk, bk in zip(pks, bks)):
            continue
        if any(pk.ftype.kind == TypeKind.DECIMAL
               and bk.ftype.scale != pk.ftype.scale
               for pk, bk in zip(pks, bks)):
            continue
        if any(c.ftype.kind not in _MPP_OUT_KINDS
               or (c.ftype.kind == TypeKind.DECIMAL
                   and c.ftype.is_wide_decimal)
               for c in list(probe_l.schema.cols) + list(build_l.schema.cols)):
            continue
        p_task, p_resid = _start_cop(probe_l, pctx)
        if p_task is None or p_resid or p_task.ranges == []:
            continue
        b_task, b_resid = _start_cop(build_l, pctx)
        if b_task is None or b_resid or b_task.ranges == []:
            continue
        if any(not isinstance(x, SelectionIR)
               for x in p_task.dag_execs + b_task.dag_execs):
            continue
        pk_pos = [p_task.scan_pos_map().get(pk.unique_id) for pk in pks]
        bk_pos = [b_task.scan_pos_map().get(bk.unique_id) for bk in bks]
        if any(p is None for p in pk_pos) or any(b is None
                                                 for b in bk_pos):
            continue
        # cost gate: small build sides are served better by the
        # broadcast lookup / host lanes (no exchange); the shuffle wins
        # once the build side is too big to broadcast or hash cheaply
        build_est = _est_rows(
            PhysTableReader(Schema(b_task.scan_cols), b_task, False,
                            build_l.ranges), pctx)
        if not probe_is_left:
            # the reversed order exists so the SMALLER side builds; now
            # that non-unique build keys are legal, never reverse just
            # to get a bigger build side past the broadcast threshold
            probe_est = _est_rows(
                PhysTableReader(Schema(p_task.scan_cols), p_task, False,
                                probe_l.ranges), pctx)
            if build_est > probe_est:
                continue
        if not pctx.enforce_mpp and build_est <= pctx.mpp_threshold:
            continue
        return (probe_l, build_l, p_task, b_task, pk_pos, bk_pos,
                probe_is_left, build_est, copart)
    return None


def _co_partitioned(probe_l, pk, build_l, bk) -> bool:
    """True when both sides are HASH-partitioned ON THE JOIN KEY with
    equal partition counts: rows with equal keys land in same-ordinal
    partitions (the same abs(v) %% N routing on both sides), so the
    exchange pair is provably unnecessary."""
    pi = probe_l.table.partition_info
    bi = build_l.table.partition_info
    if pi is None or bi is None:
        return False
    if pi.kind != "hash" or bi.kind != "hash" or len(pi.defs) != len(bi.defs):
        return False

    def key_is_part_col(ds, key, info):
        col = next((c for c in ds.schema.cols
                    if c.uid == key.unique_id), None)
        return (col is not None
                and col.name.lower() == info.column.lower())

    return (key_is_part_col(probe_l, pk, pi)
            and key_is_part_col(build_l, bk, bi))


def _mpp_reason(pctx: PhysicalContext, build_est: float) -> str:
    if pctx.enforce_mpp and build_est <= pctx.mpp_threshold:
        return "enforced"
    return f"build est {build_est:.0f} > broadcast threshold"


def _mpp_exchange_pair(probe_l, build_l, p_task, b_task, pk_pos, bk_pos,
                       probe_is_left, elided: bool = False):
    """(left, right) fragment plans in schema order: sender/receiver
    pairs normally, bare co-partitioned scans when the exchange is
    elided (no exchange operators in the plan at all)."""
    p_sender = PhysExchangeSender(Schema(p_task.scan_cols), p_task, pk_pos,
                                  ranges=probe_l.ranges, elided=elided)
    b_sender = PhysExchangeSender(Schema(b_task.scan_cols), b_task, bk_pos,
                                  ranges=build_l.ranges, elided=elided)
    if elided:
        left, right = ((p_sender, b_sender) if probe_is_left
                       else (b_sender, p_sender))
        return left, right
    p_recv = PhysExchangeReceiver(p_sender)
    b_recv = PhysExchangeReceiver(b_sender)
    if probe_is_left:
        return p_recv, b_recv
    return b_recv, p_recv


def _try_mpp_join(plan: LogicalJoin,
                  pctx: PhysicalContext) -> Optional[PhysicalPlan]:
    """Join(big scan, big unique-key scan) -> device-resident shuffle
    join: ExchangeSender/Receiver pair per side under one PhysMPPJoin."""
    parts = _mpp_join_parts(plan, pctx)
    if parts is None:
        return None
    (probe_l, build_l, p_task, b_task, pk_pos, bk_pos, probe_is_left,
     build_est, copart) = parts
    left_l, right_l = plan.children
    want = [c.uid for c in list(left_l.schema.cols)
            + list(right_l.schema.cols)]
    if [c.uid for c in plan.schema.cols] != want:
        return None  # schema is not the plain left++right concatenation
    left_recv, right_recv = _mpp_exchange_pair(
        probe_l, build_l, p_task, b_task, pk_pos, bk_pos, probe_is_left,
        elided=copart)
    lmap = {c.uid: i for i, c in enumerate(left_l.schema.cols)}
    rmap = {c.uid: i for i, c in enumerate(right_l.schema.cols)}
    return PhysMPPJoin(
        left_recv, right_recv, plan.kind, probe_is_left, plan.schema,
        [le.remap_columns(lmap) for le, _ in plan.eq_conds],
        [re_.remap_columns(rmap) for _, re_ in plan.eq_conds],
        reason=_mpp_reason(pctx, build_est), elided=copart)


#: grouped-pushdown budget ceiling: above this estimated group count the
#: compacted (key, state) all_gather stops paying for itself and the
#: generic plan (device join + host agg over joined rows) serves better
MPP_GROUP_BUDGET_MAX = 1 << 15
MPP_GROUP_BUDGET_MIN = 1 << 10


def _mpp_grouped_enabled() -> bool:
    from ..mpp.engine import grouped_pushdown_enabled

    return grouped_pushdown_enabled()


def _mpp_group_ndv(p_task, b_task, group_by, pctx) -> float:
    """Estimated distinct-group count of a GROUP BY over the join:
    product of per-key ANALYZEd NDVs (plain columns resolve against the
    owning side's stats; computed keys guess 100, the _group_ndv
    default)."""
    ndv = 1.0
    for g in group_by:
        got = None
        if isinstance(g, ColumnExpr) and g.unique_id >= 0 \
                and pctx.stats is not None:
            for task in (p_task, b_task):
                sc = next((c for c in task.scan_cols
                           if c.uid == g.unique_id), None)
                if sc is None:
                    continue
                st = pctx.stats.get(task.table.id)
                cs = st.columns.get(sc.store_offset) if st else None
                if cs is not None and cs.ndv > 0:
                    got = float(cs.ndv)
                break
        ndv *= got if got is not None else 100.0
    return ndv


def _try_mpp_join_agg(plan: LogicalAggregation, join: LogicalJoin,
                      pctx: PhysicalContext) -> Optional[PhysicalPlan]:
    """Aggregation over an MPP-eligible inner join -> the partial
    aggregation runs inside the exchange program and a FINAL HashAgg
    merges.  Scalar aggs psum-merge on device (G=1 partials leave);
    GROUP BY sort-groups per shard inside a planner-budgeted group
    capacity and merges partials ACROSS shards on device, so only O(G)
    group rows leave — the "partial partial aggregates" regime.  The
    group-cardinality gate keeps exploding GROUP BYs on the generic
    plan; runtime overflow falls back through the agg-peel rung."""
    if not plan.aggs or join.kind != "inner":
        return None
    grouped = bool(plan.group_by)
    if grouped and not _mpp_grouped_enabled():
        return None
    parts = _mpp_join_parts(join, pctx)
    if parts is None:
        return None
    (probe_l, build_l, p_task, b_task, pk_pos, bk_pos, probe_is_left,
     build_est, copart) = parts
    if not probe_is_left:
        return None  # host-rung partial layout assumes probe==left
    budget = 0
    if grouped:
        est_g = _mpp_group_ndv(p_task, b_task, plan.group_by, pctx)
        if est_g > MPP_GROUP_BUDGET_MAX:
            return None  # group cardinality too large to pay for itself
        budget = int(min(max(2.0 * est_g, MPP_GROUP_BUDGET_MIN),
                         MPP_GROUP_BUDGET_MAX))
    if grouped and copart:
        # per-pair grouped partials merge at the final HashAgg anyway,
        # but each pair would budget G independently; keep the elided
        # path on the scalar/row shapes it is tested for and let the
        # grouped plan ride the generic per-pair host merge
        return None
    from ..expr.pushdown import can_push_agg, can_push_expr

    dict_uids = _dict_uids(probe_l, pctx) | _dict_uids(build_l, pctx)
    probe_uids = {c.uid for c in probe_l.schema.cols}
    build_pos = {c.uid: i for i, c in enumerate(build_l.schema.cols)}
    wp = len(p_task.scan_cols)
    mapping = dict(p_task.scan_pos_map())
    for u, i in build_pos.items():
        mapping[u] = wp + i
    group_by = []
    for g in plan.group_by:
        refs: set = set()
        g.collect_columns(refs)
        if any(u not in probe_uids and u not in build_pos for u in refs):
            return None
        remappable = can_remap_group_key(g, dict_uids)
        if not (can_push_expr(g, pctx.pushdown_blacklist, dict_uids)
                or _is_plain_col(g) or remappable):
            return None
        if (g.ftype.kind == TypeKind.STRING
                and not isinstance(g, ColumnExpr) and not remappable):
            # computed STRING keys lower via dict-code re-mapping
            # (ISSUE 11 / MPP follow-up (d)); anything else still needs
            # a store column for the dict decode
            return None
        group_by.append(g.remap_columns(mapping))
    aggs = []
    for a in plan.aggs:
        if a.name not in ("count", "sum", "avg", "min", "max") \
                or a.distinct:
            return None
        if not can_push_agg(a, pctx.pushdown_blacklist, dict_uids):
            return None
        refs = set()
        for x in a.args:
            x.collect_columns(refs)
        if any(u not in probe_uids and u not in build_pos for u in refs):
            return None
        if any(x.ftype.kind == TypeKind.STRING for x in a.args):
            return None  # dict codes don't aggregate
        aggs.append(a.remap_columns(mapping))
    left_recv, right_recv = _mpp_exchange_pair(
        probe_l, build_l, p_task, b_task, pk_pos, bk_pos, probe_is_left,
        elided=copart)
    lmap = {c.uid: i for i, c in enumerate(probe_l.schema.cols)}
    rmap = {c.uid: i for i, c in enumerate(build_l.schema.cols)}
    mpp = PhysMPPJoin(
        left_recv, right_recv, "inner", True, _partial_schema(plan),
        [le.remap_columns(lmap) for le, _ in join.eq_conds],
        [re_.remap_columns(rmap) for _, re_ in join.eq_conds],
        aggs=aggs, group_by=group_by or None, group_budget=budget,
        reason=_mpp_reason(pctx, build_est), elided=copart)
    fin_gb = [ColumnExpr(i, g.ftype, str(g), -1)
              for i, g in enumerate(plan.group_by)]
    return PhysHashAgg(mpp, fin_gb, plan.aggs, True, plan.schema)


def _physical_join(plan: LogicalJoin, pctx: PhysicalContext) -> PhysicalPlan:
    if not pctx.prefer_merge_join:
        # tidb_enforce_mpp pins the exchange engine whenever structurally
        # eligible — it outranks the index-join cost choice too
        if pctx.enforce_mpp:
            mpp = _try_mpp_join(plan, pctx)
            if mpp is not None:
                return mpp
        ij = _try_index_join(plan, pctx)
        if ij is not None:
            return ij
        if not pctx.enforce_mpp:
            mpp = _try_mpp_join(plan, pctx)
            if mpp is not None:
                return mpp
        # multi-way join trees / decorrelated semi-anti filter rungs:
        # the join-tree compiler keeps the whole ladder device-resident
        from .jointree import try_jointree

        jt = try_jointree(plan, pctx)
        if jt is not None:
            return jt
    left = to_physical(plan.children[0], pctx)
    right = to_physical(plan.children[1], pctx)
    lmap = left.schema.position_map()
    rmap = right.schema.position_map()
    lkeys, rkeys = [], []
    for le, re in plan.eq_conds:
        ct = common_compare_type(le.ftype, re.ftype)
        le2 = _maybe_cast(le.remap_columns(lmap), ct)
        re2 = _maybe_cast(re.remap_columns(rmap), ct)
        lkeys.append(le2)
        rkeys.append(re2)
    # other conds evaluate over left++right layout
    pair_map = dict(lmap)
    off = len(left.schema)
    for uid, i in rmap.items():
        pair_map[uid] = off + i
    others = [c.remap_columns(pair_map) for c in plan.other_conds]
    if plan.kind == "inner":
        build_right = _est_rows(right, pctx) <= _est_rows(left, pctx)
    else:
        build_right = True  # outer/semi: probe must be the left side
    if not plan.eq_conds and not plan.other_conds and \
            plan.kind in ("semi", "anti_semi"):
        # EXISTS with no correlation: keys empty -> every probe row matches
        # iff build side non-empty; HashJoinExec handles empty key lists.
        pass
    if (pctx.prefer_merge_join and plan.eq_conds
            and plan.kind in ("inner", "left_outer", "semi", "anti_semi")):
        # sort-merge join: inject explicit sorts on the join keys (the
        # merge exec requires ascending key order); preserves left order
        # through the join (merge_join.go's keep-order property)
        left_s = PhysSort(left, [(k, False) for k in lkeys])
        right_s = PhysSort(right, [(k, False) for k in rkeys])
        return PhysMergeJoin(left_s, right_s, plan.kind, lkeys, rkeys,
                             others, plan.schema)
    rf = _attach_runtime_filter(
        plan.kind, left, right, lkeys, rkeys, build_right, pctx
    )
    rf_key, rf_id = rf if rf is not None else (None, 0)
    return PhysHashJoin(left, right, plan.kind, lkeys, rkeys, others,
                        build_right, plan.schema, rf_build_key=rf_key,
                        rf_filter_id=rf_id)


def _attach_runtime_filter(kind, left, right, lkeys, rkeys, build_right,
                           pctx) -> Optional[Tuple[int, int]]:
    """Device semi-join probe (runtime filter): when the probe side is a
    plain cop scan and a join key is device-eligible, append a JoinProbeIR
    to the probe DAG — the hash join ships its build-side distinct keys to
    the device so non-matching fact rows die before reaching the host.

    The device analog of index_lookup_join.go building inner requests from
    outer rows; only row-reducing join kinds qualify (inner/semi — outer
    and anti joins need the non-matching probe rows too)."""
    if kind not in ("inner", "semi"):
        return None
    if not pctx.enable_pushdown:
        return None
    probe = left if build_right else right
    build = right if build_right else left
    pkeys = lkeys if build_right else rkeys
    if not isinstance(probe, PhysTableReader) or not pkeys:
        return None
    # size gate: shipping + deduping a huge build key set costs more than it
    # filters; only worth it when the build side is clearly the small side
    build_est = _est_rows(build, pctx)
    probe_est = _est_rows(probe, pctx)
    if build_est > 2_000_000 or build_est > 0.5 * max(probe_est, 1):
        return None
    # DAG must end at scan [+ selections]: a probe after agg/topn/proj is
    # not row-aligned with the scan
    from ..copr.ir import JoinProbeIR

    if any(not isinstance(ex, (SelectionIR, JoinProbeIR))
           for ex in probe.dag.executors[1:]):
        return None
    from ..expr.pushdown import can_push_expr

    # dict encoding lives on PHYSICAL stores: a partitioned probe's scan
    # carries the logical id, which has no storage — resolve through the
    # first range's physical id (encoding is uniform per column family)
    try:
        store_tid = probe.ranges[0].table_id if probe.ranges \
            else probe.dag.scan.table_id
        dict_cols = {
            i for i, ci in enumerate(probe.dag.scan.columns)
            if ci in pctx.storage.table(store_tid).dict_encoded_cols()
        }
    except KVError:
        return None  # no physical store reachable: skip the filter
    from ..copr.ir import deserialize_expr, serialize_expr

    for i, pk in enumerate(pkeys):
        if pk.ftype.kind == TypeKind.STRING:
            continue  # dict codes are store-local; skip string keys
        # strip planner uids: IR exprs address scan-output POSITIONS
        pk_pos = deserialize_expr(serialize_expr(pk))
        cols: set = set()
        pk_pos.collect_columns(cols)
        if any(c >= len(probe.dag.scan.columns) for c in cols):
            continue
        if not can_push_expr(pk_pos, pctx.pushdown_blacklist, dict_cols):
            continue
        # unique per reader: a second join filtering the same scan gets its
        # own aux slot instead of colliding on probe_keys_0
        fid = sum(1 for ex in probe.dag.executors
                  if isinstance(ex, JoinProbeIR))
        probe.dag.executors.append(JoinProbeIR(pk_pos, filter_id=fid))
        return i, fid
    return None


def _key_ndv(child: PhysicalPlan, key, child_rows: float,
             pctx: PhysicalContext):
    """ANALYZEd NDV of a plain-column join key, capped by the child's
    estimated output rows (filters cannot increase distinct count); None
    when no stats reach the key."""
    if not isinstance(key, ColumnExpr) or key.unique_id < 0:
        return None
    node = child
    while isinstance(node, (PhysSelection, PhysSort, PhysExchangeReceiver)):
        node = node.children[0]
    if not isinstance(node, PhysTableReader) or pctx.stats is None:
        return None
    sc = next((c for c in node.cop.scan_cols if c.uid == key.unique_id),
              None)
    st = pctx.stats.get(node.cop.table.id)
    if sc is None or st is None:
        return None
    cs = st.columns.get(sc.store_offset)
    if cs is None or cs.ndv <= 0:
        return None
    return max(min(float(cs.ndv), child_rows), 1.0)


def _cop_selectivity(p: "PhysTableReader", conds, pctx) -> float:
    """Histogram-backed selectivity for pushed conds; conds' ColumnExprs are
    remapped (by uid) onto STORE column offsets for the stats lookup."""
    if pctx.stats is None:
        return 0.25 ** min(len(conds), 2)
    offmap = {c.uid: c.store_offset for c in p.cop.scan_cols}
    remapped = [c.remap_columns(offmap) for c in conds]
    return pctx.stats.estimate_selectivity(p.cop.table.id, remapped)


def _est_rows(p: PhysicalPlan, pctx: PhysicalContext) -> float:
    if isinstance(p, PhysTableReader):
        st = pctx.stats.get(p.cop.table.id) if pctx.stats else None
        if st is not None:
            rows = float(st.row_count)
        else:
            rows = 0.0
            for pid in {kr.table_id for kr in p.ranges}:
                store = pctx.storage.table(pid)
                rows += store.base_rows + len(store.delta)
        for ex in p.dag.executors[1:]:
            if isinstance(ex, SelectionIR):
                rows *= _cop_selectivity(p, ex.conditions, pctx)
            elif isinstance(ex, (TopNIR, LimitIR)):
                rows = min(rows, ex.limit)
            elif isinstance(ex, AggregationIR):
                ndv = _group_ndv(p, ex, pctx)
                rows = max(min(rows, ndv), 1)
        return rows
    if isinstance(p, (PhysSelection,)):
        return _est_rows(p.children[0], pctx) * 0.25
    if isinstance(p, (PhysLimit, PhysTopN)):
        return min(_est_rows(p.children[0], pctx), p.limit)
    if isinstance(p, PhysHashAgg):
        if p.partial_input:
            # child already emits one row per (shard, group); the final
            # merge keeps roughly the group count
            return max(_est_rows(p.children[0], pctx), 1)
        return max(_est_rows(p.children[0], pctx) * 0.1, 1)
    if isinstance(p, PhysMPPJoinTree):
        if p.aggs is not None:
            if p.group_by:
                return float(max(p.group_budget, 1))
            return 1.0
        return max(float(p.rungs[-1]["est"]) if p.rungs else 1.0, 1.0)
    if isinstance(p, PhysMPPJoin):
        if p.aggs is not None:
            if p.group_by:
                # grouped partials: at most the planner's group budget
                return float(max(p.group_budget, 1))
            return 1.0  # scalar partial: one G=1 partial row
        l = _est_rows(p.children[0], pctx)
        r = _est_rows(p.children[1], pctx)
        if p.left_keys and p.right_keys:
            nl = _key_ndv(p.children[0], p.left_keys[0], l, pctx)
            nr = _key_ndv(p.children[1], p.right_keys[0], r, pctx)
            if nl is not None and nr is not None:
                est = l * r / max(nl, nr, 1.0)
                if p.kind == "left_outer":
                    est = max(est, l)
                return max(est, 1.0)
        return max(l, r)
    if isinstance(p, PhysHashJoin):
        l = _est_rows(p.children[0], pctx)
        r = _est_rows(p.children[1], pctx)
        if p.kind in ("semi", "anti_semi", "left_outer_semi"):
            return l
        # equi-join output from key NDVs: |L ⋈ R| = |L|·|R| / max(ndv_l,
        # ndv_r) (the classic System-R containment assumption, the
        # reference's statistics join estimation) — fixed-fraction
        # heuristics only when no ANALYZEd NDV reaches the key
        if p.left_keys and p.right_keys:
            nl = _key_ndv(p.children[0], p.left_keys[0], l, pctx)
            nr = _key_ndv(p.children[1], p.right_keys[0], r, pctx)
            if nl is not None and nr is not None:
                est = l * r / max(nl, nr, 1.0)
                if p.kind == "left_outer":
                    est = max(est, l)
                return max(est, 1.0)
        return max(l, r)  # FK-join heuristic (no usable key stats)
    if isinstance(p, PhysIndexJoin):
        o = _est_rows(p.children[0], pctx)
        if p.kind in ("semi", "anti_semi"):
            return o
        return max(o, 1.0)  # FK lookup: ~one inner row per outer row
    if isinstance(p, PhysBatchPointGet):
        return float(max(len(p.keys), 1))
    if isinstance(p, (PhysIndexLookUp, PhysIndexReader)):
        if isinstance(p, PhysIndexLookUp) and p.point_get:
            return 1.0
        store = pctx.storage.table(p.table.id)
        total = float(store.base_rows + len(store.delta))
        if pctx.stats is not None:
            offmap = {c.uid: c.store_offset for c in p.schema.cols}
            remapped = [c.remap_columns(offmap) for c in p.all_conds]
            return max(
                pctx.stats.estimate_selectivity(p.table.id, remapped) * total,
                1.0,
            )
        return max(total * 0.01, 1.0)
    if isinstance(p, PhysUnionScan):
        total = 0.0
        for pid in p.table.physical_ids():
            store = pctx.storage.table(pid)
            total += store.base_rows + len(store.delta)
        return total
    if isinstance(p, PhysUnion):
        return sum(_est_rows(c, pctx) for c in p.children)
    if p.children:
        return _est_rows(p.children[0], pctx)
    return 1.0


def _group_ndv(p: "PhysTableReader", agg_ir: AggregationIR, pctx) -> float:
    if pctx.stats is None:
        return 100.0
    st = pctx.stats.get(p.cop.table.id)
    if st is None:
        return 100.0
    ndv = 1.0
    offmap = {i: c.store_offset for i, c in enumerate(p.cop.scan_cols)}
    for g in agg_ir.group_by:
        if isinstance(g, ColumnExpr) and g.index in offmap:
            cs = st.columns.get(offmap[g.index])
            ndv *= cs.ndv if cs else 100.0
        else:
            ndv *= 100.0
    return ndv


def annotate_estimates(p: PhysicalPlan, pctx: PhysicalContext):
    """Fill est_rows on every node for EXPLAIN (stats.go row counts)."""
    try:
        p.est_rows = _est_rows(p, pctx)
    except Exception:
        p.est_rows = None
    for c in p.children:
        annotate_estimates(c, pctx)


def _is_plain_col(e: Expression) -> bool:
    return isinstance(e, ColumnExpr)


def _maybe_cast(e: Expression, target: FieldType) -> Expression:
    if e.ftype.kind == target.kind and e.ftype.scale == target.scale:
        return e
    return ScalarFunc("cast", [e], target.with_nullable(e.ftype.nullable),
                      {"target": target.with_nullable(e.ftype.nullable)})


def _remap(exprs: List[Expression], schema: Schema) -> List[Expression]:
    pos = schema.position_map()
    for e in exprs:
        used: set = set()
        e.collect_columns(used)
        missing = used - pos.keys()
        if missing:
            raise PlanError(
                f"column uid(s) {sorted(missing)} not in child schema for "
                f"expr {e}"
            )
    return [e.remap_columns(pos) for e in exprs]


def explain_text(p: PhysicalPlan) -> str:
    lines = p.explain_tree()
    w1 = max(len(l[0]) for l in lines) + 2
    w2 = max(len(l[1]) for l in lines) + 2
    w3 = max(len(l[2]) for l in lines) + 2
    return "\n".join(
        f"{a:<{w1}}{b:<{w2}}{c:<{w3}}{d}" for a, b, c, d in lines
    )
