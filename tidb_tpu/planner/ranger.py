"""Range derivation: access conditions -> index key ranges.

Reference: util/ranger (BuildTableRange ranger.go:282, points2Ranges :54)
— splits a conjunction into access conditions (consumed by the index range)
and residual filter conditions.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from ..expr.expression import ColumnExpr, Constant, Expression, ScalarFunc
from ..types import TypeKind


@dataclass
class IndexRange:
    """Bounds over a prefix of the index columns: eq_prefix values for the
    leading columns, then an optional range on the next column."""

    eq_prefix: List[object] = field(default_factory=list)
    low: Optional[object] = None
    high: Optional[object] = None
    low_open: bool = False
    high_open: bool = False

    def low_tuple(self) -> Optional[tuple]:
        if self.low is not None:
            return tuple(self.eq_prefix) + (self.low,)
        return tuple(self.eq_prefix) if self.eq_prefix else None

    def high_tuple(self) -> Optional[tuple]:
        if self.high is not None:
            return tuple(self.eq_prefix) + (self.high,)
        return tuple(self.eq_prefix) if self.eq_prefix else None

    @property
    def full_eq_depth(self) -> int:
        return len(self.eq_prefix)


@dataclass
class AccessPath:
    index_uids: List[int]  # uids of the index columns, in index order
    rng: IndexRange
    access_conds: List[Expression]
    residual_conds: List[Expression]


def _col_const(cond):
    """(col, const, op) for col-op-const or const-op-col (op flipped)."""
    flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "="}
    if not isinstance(cond, ScalarFunc) or len(cond.args) != 2:
        return None
    a, b = cond.args
    if cond.name not in flip:
        return None
    if isinstance(a, ColumnExpr) and isinstance(b, Constant):
        return a, b, cond.name
    if isinstance(b, ColumnExpr) and isinstance(a, Constant):
        return b, a, flip[cond.name]
    return None


def _const_key(col: ColumnExpr, const: Constant, store, store_offset: int,
               op: str):
    """Constant -> (index key repr, effective op) for the column, or None
    when the constant cannot be represented exactly (cond stays residual).
    The effective op can differ from `op` when the bound is adjusted, e.g.
    int_col > 10.5 becomes int_col >= 11 (closed bound!)."""
    v = const.value
    if v is None:
        return None
    kind = col.ftype.kind
    if kind == TypeKind.STRING:
        if not isinstance(v, str):
            return None
        meta = store.cols[store_offset]
        if meta.dictionary is None:
            return None
        if op == "=":
            code = store.encode_dict_const(store_offset, v)
            return (code if code >= 0 else -1, "=")
        side = "left" if op in (">=", "<") else "right"
        # >=/<: first code with value >= v; >/<=: first code > v — the
        # bound code is then used with CLOSED-low/OPEN-high semantics
        code = store.dict_bound(store_offset, v, side)
        eff = ">=" if op in (">", ">=") else "<"
        return (code, eff)
    if kind in (TypeKind.INT, TypeKind.UINT, TypeKind.BOOL, TypeKind.DATE,
                TypeKind.DATETIME):
        scaled = _exact_scaled(v, const.ftype, 0)
        if scaled is None:
            return None
        return _closed_bound(*scaled, op)
    if kind == TypeKind.DECIMAL:
        scaled = _exact_scaled(v, const.ftype, col.ftype.scale)
        if scaled is None:
            return None
        return _closed_bound(*scaled, op)
    if kind == TypeKind.FLOAT:
        if const.ftype.kind == TypeKind.DECIMAL and isinstance(v, int):
            return (v / 10 ** const.ftype.scale, op)
        return (float(v), op) if isinstance(v, (int, float)) else None
    return None


def _exact_scaled(v, const_ft, target_scale: int):
    """(quotient, has_fraction) of the constant shifted to the column's
    scale, computed EXACTLY (no IEEE noise: 0.07*100 != 7.0 in floats)."""
    from fractions import Fraction

    if isinstance(v, bool):
        v = int(v)
    if isinstance(v, int) and const_ft.kind == TypeKind.DECIMAL:
        f = Fraction(v, 10 ** const_ft.scale)
    elif isinstance(v, int):
        f = Fraction(v)
    elif isinstance(v, float):
        # repr() is the shortest decimal that round-trips: the value the
        # user wrote, free of binary representation noise
        f = Fraction(repr(v))
    else:
        return None
    f *= 10 ** target_scale
    q, r = divmod(f.numerator, f.denominator)
    return q, r != 0


def _closed_bound(q: int, has_frac: bool, op: str):
    """Fractional constants make int-domain bounds CLOSED:
    col > 10.5 == col >= 11; col < 2.5 == col <= 2; col = 10.5 matches
    nothing.  divmod floors, so q is the floor for either sign."""
    if not has_frac:
        return (q, op)
    if op == "=":
        return None
    return (q, "<=") if op in ("<", "<=") else (q + 1, ">=")


def build_access_path(conds: List[Expression], index_uids: List[int],
                      uid_to_store_offset: dict, store) -> Optional[AccessPath]:
    """Best-effort range over a prefix of `index_uids` from the conjuncts."""
    eq_prefix: List[object] = []
    used: List[Expression] = []
    remaining = list(conds)
    rng = IndexRange()

    for depth, uid in enumerate(index_uids):
        store_off = uid_to_store_offset[uid]
        eq_val = None
        eq_cond = None
        lows, highs = [], []
        for cond in remaining:
            cc = _col_const(cond)
            if cc is None:
                continue
            col, const, op = cc
            if (col.unique_id if col.unique_id >= 0 else col.index) != uid:
                continue
            ke = _const_key(col, const, store, store_off, op)
            if ke is None:
                continue
            key, eff = ke
            if eff == "=":
                eq_val, eq_cond = key, cond
                break
            if eff == ">":
                lows.append((key, True, cond))
            elif eff == ">=":
                lows.append((key, False, cond))
            elif eff == "<":
                highs.append((key, True, cond))
            elif eff == "<=":
                highs.append((key, False, cond))
        if eq_val is not None:
            eq_prefix.append(eq_val)
            used.append(eq_cond)
            continue
        # range on this column terminates the prefix walk
        if lows:
            key, open_, cond = max(lows, key=lambda t: t[0])
            rng.low, rng.low_open = key, open_
            used.append(cond)
        if highs:
            key, open_, cond = min(highs, key=lambda t: t[0])
            rng.high, rng.high_open = key, open_
            used.append(cond)
        break

    if not eq_prefix and rng.low is None and rng.high is None:
        return None
    rng.eq_prefix = eq_prefix
    # keep access conds in the residual set too when they were only
    # approximate (string ranges via dict_bound are exact, so drop them)
    residual = [c for c in conds if c not in used]
    return AccessPath(index_uids, rng, used, residual)
