"""Logical optimization rules.

Reference: planner/core/optimizer.go:56-69 — the rule list applied in fixed
order (column prune, predicate pushdown, agg/topN pushdown, projection
elimination, ...).  Agg/topN/limit pushdown to the coprocessor happen at
physical time here (task split); the logical rules below normalize the tree
first.
"""

from __future__ import annotations

from typing import List, Optional, Set

from ..expr.expression import ColumnExpr, Constant, Expression, ScalarFunc
from .columns import Schema
from .logical import (
    LogicalAggregation,
    LogicalDataSource,
    LogicalDual,
    LogicalJoin,
    LogicalLimit,
    LogicalMaxOneRow,
    LogicalPlan,
    LogicalProjection,
    LogicalSelection,
    LogicalSort,
    LogicalTopN,
    LogicalUnion,
)

RULES = ("push_predicates", "reorder_joins", "prune_columns",
         "eliminate_projections", "merge_limit_sort")


def optimize_logical(plan: LogicalPlan, pctx=None) -> LogicalPlan:
    plan = push_predicates(plan)
    plan = reorder_joins(plan, pctx)  # after ppd: eq edges are populated
    prune_columns(plan, set(plan.schema.uids()))
    refresh_schemas(plan)
    plan = eliminate_projections(plan, top=True)
    plan = merge_limit_sort(plan)
    return plan


def refresh_schemas(plan: LogicalPlan):
    """Bottom-up schema rebuild after pruning: pass-through nodes captured
    their child's Schema OBJECT at build time; pruning replaces children's
    schemas, so stale references must be re-derived or physical remapping
    sees pre-prune column positions."""
    for c in plan.children:
        refresh_schemas(c)
    from .logical import LogicalWindow

    if isinstance(plan, (LogicalSelection, LogicalSort, LogicalTopN,
                         LogicalLimit, LogicalMaxOneRow)):
        plan.schema = plan.children[0].schema
    elif isinstance(plan, LogicalJoin):
        if plan.kind in ("inner", "left_outer"):
            plan.schema = Schema(
                list(plan.children[0].schema.cols)
                + list(plan.children[1].schema.cols)
            )
        else:  # semi kinds: output is the left child (+ flag col kept as-is)
            if plan.kind == "left_outer_semi":
                extra = plan.schema.cols[len(plan.schema.cols) - 1:]
                plan.schema = Schema(
                    list(plan.children[0].schema.cols) + list(extra)
                )
            else:
                plan.schema = plan.children[0].schema
    elif isinstance(plan, LogicalWindow):
        win_uids = {uid for uid, _ in plan.funcs}
        plan.schema = Schema(
            list(plan.children[0].schema.cols)
            + [c for c in plan.schema.cols if c.uid in win_uids]
        )


# ---------------------------------------------------------------------------
# column pruning (planner/core/rule_column_pruning.go)
# ---------------------------------------------------------------------------


def _expr_uids(exprs) -> Set[int]:
    out: Set[int] = set()
    for e in exprs:
        e.collect_columns(out)
    return out


def prune_columns(plan: LogicalPlan, needed: Set[int]):
    """Top-down: trim DataSource schemas to the columns actually used."""
    if isinstance(plan, LogicalDataSource):
        keep = [c for c in plan.schema.cols
                if c.uid in needed or c.uid in _expr_uids(plan.pushed_conds)]
        if not keep:
            keep = [plan.schema.cols[0]]  # scans need >= 1 column
        plan.schema = Schema(keep)
        return
    if isinstance(plan, LogicalProjection):
        prune_columns(plan.children[0], _expr_uids(plan.exprs))
        return
    if isinstance(plan, LogicalSelection):
        prune_columns(plan.children[0], needed | _expr_uids(plan.conds))
        return
    if isinstance(plan, LogicalAggregation):
        req = _expr_uids(plan.group_by)
        for a in plan.aggs:
            req |= _expr_uids(a.args)
        prune_columns(plan.children[0], req)
        return
    if isinstance(plan, LogicalJoin):
        req = set(needed)
        for l, r in plan.eq_conds:
            req |= _expr_uids([l, r])
        req |= _expr_uids(plan.other_conds)
        for c in plan.children:
            prune_columns(c, req)
        # shrink the join's own schema for semi joins (schema == left child)
        if plan.kind in ("inner", "left_outer"):
            lcols = [c for c in plan.children[0].schema.cols]
            rcols = [c for c in plan.children[1].schema.cols]
            by_uid = {c.uid: c for c in plan.schema.cols}
            cols = [by_uid.get(c.uid, c) for c in lcols + rcols]
            plan.schema = Schema(cols)
        return
    if isinstance(plan, (LogicalSort, LogicalTopN)):
        prune_columns(plan.children[0],
                      needed | _expr_uids([e for e, _ in plan.items]))
        return
    if isinstance(plan, LogicalUnion):
        # positional outputs: children keep full width
        for c in plan.children:
            prune_columns(c, set(c.schema.uids()))
        return
    from .logical import LogicalWindow

    if isinstance(plan, LogicalWindow):
        child = plan.children[0]
        win_uids = {uid for uid, _ in plan.funcs}
        req = (needed - win_uids) & set(child.schema.uids())
        for _, f in plan.funcs:
            req |= _expr_uids(f.args)
        req |= _expr_uids(plan.partition_by)
        req |= _expr_uids([e for e, _ in plan.order_by])
        prune_columns(child, req)
        plan.schema = Schema(
            list(child.schema.cols)
            + [c for c in plan.schema.cols if c.uid in win_uids]
        )
        return
    for c in plan.children:
        prune_columns(c, needed)


# ---------------------------------------------------------------------------
# predicate pushdown (planner/core/rule_predicate_push_down.go)
# ---------------------------------------------------------------------------


def push_predicates(plan: LogicalPlan) -> LogicalPlan:
    plan, rest = _ppd(plan, [])
    if rest:
        plan = LogicalSelection(plan, rest)
    return plan


def _ppd(plan: LogicalPlan, conds: List[Expression]):
    """Push `conds` into plan; returns (new_plan, conds that didn't sink)."""
    if isinstance(plan, LogicalSelection):
        child, rest = _ppd(plan.children[0], conds + plan.conds)
        return child, rest

    if isinstance(plan, LogicalDataSource):
        plan.pushed_conds.extend(conds)
        return plan, []

    if isinstance(plan, LogicalProjection):
        deeper, stay = [], []
        sub = {c.uid: e for c, e in zip(plan.schema.cols, plan.exprs)}
        child_uids = set(plan.children[0].schema.uids())
        for cond in conds:
            s = _substitute(cond, sub)
            # only push when the rewritten condition is evaluable below the
            # projection: a projection expr that is itself an aggregate
            # output (derived GROUP BY tables) references columns that do
            # not exist under the projection — pushing it produced a
            # row-level `sum(v) = c` filter that silently dropped every row
            if s is not None and _expr_uids([s]) <= child_uids:
                deeper.append(s)
            else:
                stay.append(cond)
        child, rest = _ppd(plan.children[0], deeper)
        plan.children = [child]
        if rest:
            plan.children = [LogicalSelection(child, rest)]
        return plan, stay

    if isinstance(plan, LogicalJoin):
        luids = set(plan.children[0].schema.uids())
        ruids = set(plan.children[1].schema.uids())
        lconds, rconds, stay = [], [], []
        for cond in conds:
            uids = _expr_uids([cond])
            if uids and uids <= luids:
                lconds.append(cond)
            elif uids and uids <= ruids and plan.kind == "inner":
                rconds.append(cond)
            elif plan.kind == "inner":
                # cross-table equality -> hash-join key (comma joins write
                # their join conditions in WHERE)
                pair = _as_join_eq(cond, luids, ruids)
                if pair is not None:
                    plan.eq_conds.append(pair)
                else:
                    stay.append(cond)
            else:
                stay.append(cond)
        # ON other-conds referencing only the inner side of an inner join
        if plan.kind == "inner" and plan.other_conds:
            keep = []
            for cond in plan.other_conds:
                uids = _expr_uids([cond])
                if uids and uids <= luids:
                    lconds.append(cond)
                elif uids and uids <= ruids:
                    rconds.append(cond)
                else:
                    keep.append(cond)
            plan.other_conds = keep
        lchild, lrest = _ppd(plan.children[0], lconds)
        rchild, rrest = _ppd(plan.children[1], rconds)
        if lrest:
            lchild = LogicalSelection(lchild, lrest)
        if rrest:
            rchild = LogicalSelection(rchild, rrest)
        plan.children = [lchild, rchild]
        return plan, stay

    if isinstance(plan, LogicalAggregation):
        guids = set()
        for g in plan.group_by:
            if isinstance(g, ColumnExpr):
                guids.add(g.unique_id)
        deeper, stay = [], []
        for cond in conds:
            uids = _expr_uids([cond])
            if uids and uids <= guids:
                deeper.append(cond)
            else:
                stay.append(cond)
        child, rest = _ppd(plan.children[0], deeper)
        if rest:
            child = LogicalSelection(child, rest)
        plan.children = [child]
        return plan, stay

    if isinstance(plan, (LogicalSort,)):
        child, rest = _ppd(plan.children[0], conds)
        if rest:
            child = LogicalSelection(child, rest)
        plan.children = [child]
        return plan, []

    from .logical import LogicalWindow

    if isinstance(plan, LogicalWindow):
        # only predicates on bare partition columns commute with a window
        # (they remove whole partitions)
        part_uids = set()
        for e in plan.partition_by:
            if isinstance(e, ColumnExpr):
                part_uids.add(e.unique_id)
        deeper, stay = [], []
        for cond in conds:
            uids = _expr_uids([cond])
            (deeper if uids and uids <= part_uids else stay).append(cond)
        child, rest = _ppd(plan.children[0], deeper)
        if rest:
            child = LogicalSelection(child, rest)
        plan.children = [child]
        return plan, stay

    if isinstance(plan, (LogicalTopN, LogicalLimit, LogicalMaxOneRow,
                         LogicalUnion, LogicalDual)):
        # filters do not commute with limits; recurse with nothing
        new_children = []
        for c in plan.children:
            nc, rest = _ppd(c, [])
            if rest:
                nc = LogicalSelection(nc, rest)
            new_children.append(nc)
        plan.children = new_children
        return plan, conds

    # default: stop
    new_children = []
    for c in plan.children:
        nc, rest = _ppd(c, [])
        if rest:
            nc = LogicalSelection(nc, rest)
        new_children.append(nc)
    plan.children = new_children
    return plan, conds


def _as_join_eq(cond: Expression, luids: set, ruids: set):
    """left_expr = right_expr over disjoint child column sets, or None."""
    if isinstance(cond, ScalarFunc) and cond.name == "=" and \
            len(cond.args) == 2:
        a, b = cond.args
        ua, ub = _expr_uids([a]), _expr_uids([b])
        if ua and ub:
            if ua <= luids and ub <= ruids:
                return (a, b)
            if ua <= ruids and ub <= luids:
                return (b, a)
    return None


def _substitute(cond: Expression, sub: dict) -> Optional[Expression]:
    """Rewrite cond in terms of projection inputs; None if impossible."""
    if isinstance(cond, ColumnExpr):
        e = sub.get(cond.unique_id)
        return e
    if isinstance(cond, Constant):
        return cond
    if isinstance(cond, ScalarFunc):
        args = []
        for a in cond.args:
            s = _substitute(a, sub)
            if s is None:
                return None
            args.append(s)
        return ScalarFunc(cond.name, args, cond.ftype, cond.meta)
    return None


# ---------------------------------------------------------------------------
# projection elimination (planner/core/rule_eliminate_projection.go)
# ---------------------------------------------------------------------------


def eliminate_projections(plan: LogicalPlan, top: bool = False) -> LogicalPlan:
    plan.children = [eliminate_projections(c) for c in plan.children]
    if isinstance(plan, LogicalProjection) and not top:
        child = plan.children[0]
        # the relabel below only survives into the physical plan when the
        # child OWNS its schema; passthrough nodes (Selection/Sort/Limit...)
        # re-derive theirs from below at physical build, losing the new
        # uids and crashing parent remaps (seen with filters over derived
        # GROUP BY tables)
        owns_schema = isinstance(
            child, (LogicalDataSource, LogicalAggregation, LogicalProjection)
        )
        if owns_schema and len(plan.exprs) == len(child.schema) and all(
            isinstance(e, ColumnExpr) and e.unique_id == c.uid
            for e, c in zip(plan.exprs, child.schema.cols)
        ):
            # identity projection: drop it, re-labelling the child's outputs
            # with the projection's uids/names so parent references survive
            from dataclasses import replace

            uid_map = {ccol.uid: pcol.uid for ccol, pcol in
                       zip(child.schema.cols, plan.schema.cols)}
            child.schema = Schema([
                replace(ccol, uid=pcol.uid, name=pcol.name,
                        display=pcol.display or ccol.display,
                        table=pcol.table or ccol.table)
                for ccol, pcol in zip(child.schema.cols, plan.schema.cols)
            ])
            if isinstance(child, LogicalDataSource):
                # a datasource's pushed_conds reference its pre-relabel
                # uids; left stale, _start_cop's scan remap misses them
                # and the cop Selection reads col #-1 (the LAST scan
                # column via Python negative indexing) — wrong rows on
                # any multi-column scan.  Caught by lint.plancheck.
                child.pushed_conds = [
                    c.remap_uids(uid_map) for c in child.pushed_conds
                ]
            return child
    return plan


# ---------------------------------------------------------------------------
# Limit(Sort) -> TopN
# ---------------------------------------------------------------------------


def merge_limit_sort(plan: LogicalPlan) -> LogicalPlan:
    plan.children = [merge_limit_sort(c) for c in plan.children]
    if isinstance(plan, LogicalLimit) and len(plan.children) == 1:
        c = plan.children[0]
        if isinstance(c, LogicalSort):
            return LogicalTopN(c.children[0], c.items, plan.limit,
                               plan.offset)
        if isinstance(c, LogicalProjection) and \
                isinstance(c.children[0], LogicalSort):
            s = c.children[0]
            c.children = [LogicalTopN(s.children[0], s.items, plan.limit,
                                      plan.offset)]
            return c
        if isinstance(c, LogicalProjection) and plan.offset == 0:
            # LIMIT commutes through a row-wise projection: pushing it
            # below lets the cop scan stop early (rule_topn_push_down's
            # limit case) — projections cannot add or drop rows
            c.children = [LogicalLimit(c.children[0], plan.limit, 0)]
            return c
    return plan


# ---------------------------------------------------------------------------
# greedy join reorder (planner/core/rule_join_reorder.go)
# ---------------------------------------------------------------------------


def _est_member(p: LogicalPlan, pctx) -> float:
    """Crude cardinality estimate for a join-group member."""
    if isinstance(p, LogicalDataSource):
        rows = float(max(getattr(p.table, "row_count", 0) or 0, 0))
        st = None
        if pctx is not None and pctx.stats is not None:
            try:
                st = pctx.stats.get(p.table.id)
            except Exception:
                st = None
        if st is not None and st.row_count:
            rows = float(st.row_count)
        elif rows == 0:
            try:
                rows = float(sum(pctx.storage.table(pid).base_rows
                                 for pid in p.table.physical_ids()))
            except Exception:
                rows = 1000.0
        if p.pushed_conds:
            rows *= 0.25 ** min(len(p.pushed_conds), 2)
        return max(rows, 1.0)
    if isinstance(p, LogicalSelection):
        return max(_est_member(p.children[0], pctx) * 0.25, 1.0)
    if isinstance(p, LogicalAggregation):
        return max(_est_member(p.children[0], pctx) * 0.1, 1.0)
    if p.children:
        return _est_member(p.children[0], pctx)
    return 1000.0


def reorder_joins(plan: LogicalPlan, pctx=None,
                  parent_inner: bool = False) -> LogicalPlan:
    """Greedy stats-driven reorder of maximal inner-join groups
    (rule_join_reorder.go's greedy solver): start from the smallest member,
    repeatedly join the connected member minimizing the estimated result.
    Left-deep output; cross joins (no connecting eq edge) go last.

    The solver runs ONCE per maximal group: a join whose parent is also an
    inner join is part of the parent's group and is skipped here."""
    is_inner = isinstance(plan, LogicalJoin) and plan.kind == "inner"
    if not is_inner or parent_inner:
        plan.children = [reorder_joins(c, pctx, is_inner)
                         for c in plan.children]
        return plan

    members: List[LogicalPlan] = []
    eqs: List[Tuple[Expression, Expression]] = []
    others: List[Expression] = []

    def collect(p):
        if isinstance(p, LogicalJoin) and p.kind == "inner":
            eqs.extend(p.eq_conds)
            others.extend(p.other_conds)
            for c in p.children:
                collect(c)
        else:
            members.append(p)

    collect(plan)
    if len(members) < 3:
        plan.children = [reorder_joins(c, pctx, True) for c in plan.children]
        return plan

    uid_of = {}  # uid -> member index (schemas are reorder-invariant, so
    for i, m in enumerate(members):  # validate edges BEFORE recursing)
        for u in m.schema.uids():
            uid_of[u] = i

    def side(e) -> Optional[int]:
        us: set = set()
        e.collect_columns(us)
        idxs = {uid_of.get(u) for u in us}
        if None in idxs:
            # references a column no member produces (correlated outer
            # column): not a clean edge — bail rather than misclassify
            return None
        return idxs.pop() if len(idxs) == 1 else None

    edges = []  # (i, j, l_expr, r_expr) with l on member i
    bad = False
    for l, r in eqs:
        i, j = side(l), side(r)
        if i is None or j is None or i == j:
            bad = True
            break
        edges.append((i, j, l, r))
    if bad:
        # unexpected shape: keep the syntactic order, but still reorder
        # nested groups past non-inner boundaries below this one
        plan.children = [reorder_joins(c, pctx, True) for c in plan.children]
        return plan

    members = [reorder_joins(m, pctx) for m in members]
    est = [_est_member(m, pctx) for m in members]
    joined = {min(range(len(members)), key=lambda i: est[i])}
    order = [next(iter(joined))]
    cur_rows = est[order[0]]
    while len(order) < len(members):
        connected = set()
        for i, j, _, _ in edges:
            if (i in joined) != (j in joined):
                connected.add(j if i in joined else i)
        if connected:
            # eq edge: FK-ish assumption — result near the larger side
            nxt = min(connected, key=lambda c: max(cur_rows, est[c]))
            cur_rows = max(cur_rows, est[nxt])
        else:
            remaining = [i for i in range(len(members)) if i not in joined]
            nxt = min(remaining, key=lambda c: est[c])
            cur_rows = cur_rows * est[nxt]
        joined.add(nxt)
        order.append(nxt)

    # rebuild left-deep
    placed_eq = [False] * len(edges)
    placed_other = [False] * len(others)
    built = members[order[0]]
    built_members = {order[0]}
    built_uids = set(built.schema.uids())
    for mi in order[1:]:
        m = members[mi]
        muids = set(m.schema.uids())
        eq_here = []
        for k, (i, j, l, r) in enumerate(edges):
            if placed_eq[k]:
                continue
            if i in built_members and j == mi:
                eq_here.append((l, r))
                placed_eq[k] = True
            elif j in built_members and i == mi:
                eq_here.append((r, l))
                placed_eq[k] = True
        avail = built_uids | muids
        oth_here = []
        for k, c in enumerate(others):
            if placed_other[k]:
                continue
            us: set = set()
            c.collect_columns(us)
            us &= set(uid_of)
            if us <= avail:
                oth_here.append(c)
                placed_other[k] = True
        built = LogicalJoin(
            built, m, "inner", eq_here, oth_here,
            Schema(list(built.schema.cols) + list(m.schema.cols)),
        )
        built_members.add(mi)
        built_uids = avail
    # anything unplaced (eq with both sides inside one step, etc.)
    leftovers = [ScalarFunc("=", [l, r], _bool_ft(), {})
                 for k, (i, j, l, r) in enumerate(edges) if not placed_eq[k]]
    leftovers += [c for k, c in enumerate(others) if not placed_other[k]]
    if leftovers:
        built = LogicalSelection(built, leftovers)
    return built


def _bool_ft():
    from ..types import ty_int

    return ty_int(False)
