from .server import MySQLServer, serve_forever

__all__ = ["MySQLServer", "serve_forever"]
