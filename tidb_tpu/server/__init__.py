from .http_status import StatusServer
from .server import MySQLServer, serve_forever

__all__ = ["MySQLServer", "StatusServer", "serve_forever"]
