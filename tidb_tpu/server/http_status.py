"""HTTP status/metrics endpoint.

Reference: server/http_status.go:74-115 — the tidb-server status port
(default 10080) serving /metrics (Prometheus), /status (JSON build/
connection info), and the /schema inspector.  Stdlib http.server in a
daemon thread; no new dependencies.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..metrics import REGISTRY
from ..util_concurrency import witness_stats

VERSION = "8.0.11-tidb-tpu-0.1.0"


def _fusion_section(snap: dict) -> dict:
    """Per-reason fusion-split breakdown (ISSUE 11): the measured
    inventory of why fragments still split to host tails."""
    try:
        from ..copr.fusion import SPLIT_REASONS

        return {
            "splits_total": snap.get("fusion_splits_total", 0),
            "by_reason": {
                r: snap.get(
                    "fusion_splits_reason_"
                    + r.replace("-", "_") + "_total", 0)
                for r in SPLIT_REASONS
            },
        }
    except Exception as e:  # pragma: no cover - defensive
        return {"error": repr(e)}


def _layout_section() -> dict:
    """The /status layout payload (never lets a tuner hiccup 500 the
    status port)."""
    try:
        from ..layout import status_section

        return status_section()
    except Exception as e:  # pragma: no cover - defensive
        return {"error": repr(e)}


def _profile_section() -> dict:
    """Continuous-profiling summary (ISSUE 13): rotating flame windows
    with the top self-time stacks; the full folded text is /flame."""
    try:
        from ..trace import PROFILER

        return PROFILER.status_section()
    except Exception as e:  # pragma: no cover - defensive
        return {"error": repr(e)}


def _resgroups_section(domain) -> dict:
    """Resource-control plane (ISSUE 17): per-group token balance,
    parked waiters, lifetime RU (device-ms) and throttle raises, plus
    the fleet RU counters and throttle-wait quantiles."""
    try:
        from ..metrics import REGISTRY

        out = {"groups": domain.resgroups.snapshot(),
               "ru_consumed": REGISTRY.snapshot().get(
                   "resgroup_ru_consumed_total", 0.0)}
        hs = REGISTRY.hist_stats("resgroup_throttle_wait_ms")
        if hs is not None:
            out["throttle_wait_ms"] = hs
        return out
    except Exception as e:  # pragma: no cover - defensive
        return {"error": repr(e)}


def _dataplane_section(domain) -> dict:
    """Sharded data plane (ISSUE 18): the host's partition map (epoch,
    owners, members), per-table shard state, and the exchange/re-shard
    counters that the 2-host bench receipt reads."""
    try:
        from ..dataplane import get_dataplane
        from ..metrics import REGISTRY

        dp = get_dataplane(domain.storage)
        snap = REGISTRY.snapshot()
        out = {"active": dp is not None}
        if dp is not None:
            out.update(dp.snapshot())
        out["metrics"] = {
            name: snap.get(name, 0)
            for name in (
                "dataplane_queries_total",
                "dataplane_local_fragments_total",
                "dataplane_remote_fragments_total",
                "dataplane_exchange_bytes_total",
                "dataplane_partitions_scanned_total",
                "dataplane_partitions_loaded_total",
                "dataplane_partitions_moved_total",
                "dataplane_reshards_total",
                "dataplane_epoch_retries_total",
                "dataplane_bypass_total",
                "dataplane_peer_lost_total",
                "dataplane_errors_total",
                # replication & failover (ISSUE 20)
                "dataplane_replica_promotions_total",
                "dataplane_cold_reloads_total",
                "dataplane_replica_fills_total",
                "dataplane_replica_fill_errors_total",
                "dataplane_replica_reads_total",
                "dataplane_failovers_total",
                "dataplane_failover_bypass_total",
                "dataplane_hedged_fragments_total",
                "dataplane_hedge_wins_total",
                "dataplane_hedge_wasted_bytes_total",
                "dataplane_rpc_wasted_bytes_total",
                "dataplane_served_bytes_total",
                "dataplane_dedup_hits_total",
                "dataplane_conn_dials_total",
                "dataplane_conn_reuse_total",
                "dataplane_conn_evictions_total",
            )
        }
        return out
    except Exception as e:  # pragma: no cover - defensive
        return {"error": repr(e)}


def _slo_section(domain) -> dict:
    """Per-statement-class SLO state (ISSUE 13): threshold, error-budget
    burn counters and latency quantiles from the log2 histograms.  An
    ``auto`` class (ISSUE 20) additionally reports the rolling-window
    baseline its derived threshold comes from."""
    try:
        from ..metrics import REGISTRY, STMT_CLASSES
        from ..session.vars import SessionVars
        from ..trace.slo import SLO_AUTO, is_auto, resolve_threshold_ms

        # the SAME read Session._observe_slo acts on (global scope with
        # SYSVAR_DEFAULTS fallback) — the reported threshold must never
        # desync from the enforced one
        gvars = SessionVars(domain.global_vars)
        snap = REGISTRY.snapshot()
        out = {}
        for cls in STMT_CLASSES:
            raw = gvars.get_global_str(f"tidb_tpu_slo_{cls}_ms", "0")
            thr = resolve_threshold_ms(raw, cls)
            ok = snap.get(f"slo_{cls}_ok_total", 0)
            breach = snap.get(f"slo_{cls}_breach_total", 0)
            total = ok + breach
            sec = {"threshold_ms": thr, "ok": ok, "breach": breach,
                   "burn": round(breach / total, 6) if total else 0.0}
            if is_auto(raw):
                sec["mode"] = "auto"
                sec["auto"] = SLO_AUTO.snapshot(cls)
            hs = REGISTRY.hist_stats(f"stmt_latency_{cls}_ms")
            if hs is not None:
                sec.update({"count": hs["count"], "p50_ms": hs["p50"],
                            "p95_ms": hs["p95"], "p99_ms": hs["p99"]})
            out[cls] = sec
        return out
    except Exception as e:  # pragma: no cover - defensive
        return {"error": repr(e)}


def _memory_section() -> dict:
    """Device-memory telemetry (ISSUE 13): bytes/capacity/high-water for
    every named ByteCapCache (mesh columns, cold tier, per-tile cache)."""
    try:
        from ..copr.cache import memory_stats

        return {"caches": memory_stats()}
    except Exception as e:  # pragma: no cover - defensive
        return {"error": repr(e)}


def _fleet_section() -> dict:
    """Fleet-merged metrics (ISSUE 13): counters summed across hosts,
    histograms merged bucket-wise, gauges kept per-host.  LocalPlane
    degenerates to a single-member fleet."""
    try:
        from ..coord import get_plane
        from ..metrics import merge_fleet

        plane = get_plane()
        # refresh=False: the /status memory section just ran
        # memory_stats(), the cache gauges are already current
        merged = merge_fleet(plane.fleet_metrics(refresh=False))
        merged["kind"] = plane.kind
        return merged
    except Exception as e:  # pragma: no cover - defensive
        return {"error": repr(e)}


class StatusServer:
    def __init__(self, domain, host: str = "127.0.0.1", port: int = 10080):
        self.domain = domain
        self.host = host
        self.port = port
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    def start(self):
        domain = self.domain

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                if path == "/metrics":
                    # refresh pull-time gauges (device-cache bytes /
                    # watermarks) so scrapes see live values
                    try:
                        from ..copr.cache import memory_stats

                        memory_stats()
                    except Exception:
                        pass
                    lines = REGISTRY.prometheus_lines()
                    body = ("\n".join(lines) + "\n").encode()
                    self._send(200, body, "text/plain; version=0.0.4")
                    return
                if path == "/flame":
                    # standard folded-stacks text (flamegraph.pl /
                    # speedscope / inferno consumable) over the
                    # profiler's retained windows
                    try:
                        from ..trace import PROFILER

                        body = PROFILER.folded().encode()
                    except Exception as e:
                        body = f"# profiler unavailable: {e!r}\n".encode()
                    self._send(200, body, "text/plain")
                    return
                if path in ("/status", "/"):
                    from ..coord import get_plane
                    from ..copr.cache import PROGRAM_CACHES
                    from ..copr.device_health import DEVICE_HEALTH
                    from ..metrics import COORD_STATUS_METRICS
                    from ..trace import TRACE_RING

                    running = sum(
                        1 for s in domain.sessions.values()
                        if getattr(s, "stmt_start", None) is not None)
                    recent = []
                    for tr in list(TRACE_RING)[-8:]:
                        try:
                            tot = tr.phase_totals()
                            recent.append({
                                "sql": tr.sql[:128],
                                "conn_id": tr.conn_id,
                                "duration_ms": round(tr.duration_ms(), 3),
                                "compile_ms": round(tot["compile_ms"], 3),
                                "transfer_bytes": tot["transfer_bytes"],
                                "device_ms": round(tot["device_ms"], 3),
                                "readback_ms": round(tot["readback_ms"], 3),
                                "backoff_ms": round(tot["backoff_ms"], 3),
                                "backfill_ms": round(
                                    tot.get("backfill_ms", 0.0), 3),
                                "wire_bytes": tot["wire_bytes"],
                                "engines": tot["engines"],
                            })
                        except Exception:
                            continue  # a live trace mutating mid-walk
                    plane = get_plane()
                    view = plane.view()
                    snap = REGISTRY.snapshot()
                    body = json.dumps({
                        "version": VERSION,
                        "git_hash": "",
                        "ddl_schema_version":
                            domain.catalog.schema_version,
                        "connections": len(domain.sessions),
                        "running_statements": running,
                        "gc_safe_point":
                            domain.maintenance.last_safepoint,
                        # circuit-breaker summary (PR-2 follow-up (d)):
                        # operators watching the status port see a sick
                        # chip without querying information_schema
                        "tripped_devices":
                            list(DEVICE_HEALTH.tripped_ids()),
                        # N most recent finished query traces with their
                        # per-phase totals (the trace subsystem's ring)
                        "recent_traces": recent,
                        # LRU-bounded compiled-program caches (tile/mesh/
                        # MPP/micro-batch): with shape buckets on, hit
                        # rate tracks query SHAPE CLASSES, not literals
                        "compiled_programs": {
                            c.name: c.stats() for c in PROGRAM_CACHES
                        },
                        # coordination plane (ISSUE 9): membership epoch
                        # + per-process healthy device sets, and the
                        # failover / span-forwarding / handoff counters
                        "coord": {
                            "kind": plane.kind,
                            "epoch": view.epoch,
                            "formed": view.formed,
                            "members": {
                                str(p): list(ids) for p, ids
                                in sorted(view.members.items())
                            },
                            "metrics": {
                                name: snap.get(name, 0)
                                for name in COORD_STATUS_METRICS
                            },
                        },
                        # adaptive data layout (ISSUE 10): per-column
                        # encoding/tier decisions, hot/cold tier byte
                        # gauges and the cold-tier traffic counters
                        "layout": _layout_section(),
                        # zero-host-tail compilation (ISSUE 11): region
                        # splits by reason — regressions in fusion
                        # coverage are visible per cause at a glance
                        "fusion": _fusion_section(snap),
                        # continuous profiling (ISSUE 13): rotating
                        # flame windows, top self-time stacks (full
                        # folded text on /flame)
                        "profile": _profile_section(),
                        # per-statement-class SLOs: thresholds, error-
                        # budget burn, p50/p95/p99 from log2 histograms
                        "slo": _slo_section(domain),
                        # device-memory telemetry: per-cache bytes,
                        # capacity and high-water marks
                        "memory": _memory_section(),
                        # fleet-merged metrics: counters summed across
                        # hosts, histograms bucket-merged, gauges
                        # per-host (LocalPlane = single-member fleet)
                        "fleet": _fleet_section(),
                        # lock-order witness (ISSUE 16): guarded
                        # acquisitions, max held depth, violations
                        # (all zero with TIDB_TPU_LOCKCHECK unset)
                        "lockcheck": witness_stats(),
                        # resource groups (ISSUE 17): token balances,
                        # waiters, lifetime RU and throttle counts
                        "resgroups": _resgroups_section(domain),
                        # sharded data plane (ISSUE 18): partition map,
                        # shard state, exchange/re-shard counters
                        "dataplane": _dataplane_section(domain),
                    }).encode()
                    self._send(200, body, "application/json")
                    return
                if path == "/device-health":
                    # full breaker state, mirroring information_schema.
                    # TIDB_TPU_DEVICE_HEALTH (region_cache.go's store
                    # health surfaced on http_status.go's /regions model)
                    from ..copr.device_health import DEVICE_HEALTH

                    body = json.dumps({
                        "devices": [{
                            "device_id": st.device_id,
                            "state": st.state,
                            "error_count": st.error_count,
                            "consecutive_errors": st.consecutive_errors,
                            "trip_count": st.trip_count,
                            "last_error": st.last_error,
                        } for st in DEVICE_HEALTH.snapshot()],
                        "tripped":
                            list(DEVICE_HEALTH.tripped_ids()),
                    }).encode()
                    self._send(200, body, "application/json")
                    return
                if path == "/schema":
                    isc = domain.catalog.info_schema()
                    out = {}
                    for db in isc.schema_names():
                        out[db] = [
                            {"name": t.name, "id": t.id,
                             "is_view": t.is_view,
                             "partitions": [p.name for p in
                                            t.partition_info.defs]
                             if t.partition_info else None}
                            for t in isc.tables(db)
                        ]
                    self._send(200, json.dumps(out).encode(),
                               "application/json")
                    return
                self._send(404, b"not found\n", "text/plain")

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="tidb-tpu-status",
            daemon=True)
        self._thread.start()
        return self.host, self.port

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
