"""HTTP status/metrics endpoint.

Reference: server/http_status.go:74-115 — the tidb-server status port
(default 10080) serving /metrics (Prometheus), /status (JSON build/
connection info), and the /schema inspector.  Stdlib http.server in a
daemon thread; no new dependencies.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..metrics import REGISTRY

VERSION = "8.0.11-tidb-tpu-0.1.0"


def _fusion_section(snap: dict) -> dict:
    """Per-reason fusion-split breakdown (ISSUE 11): the measured
    inventory of why fragments still split to host tails."""
    try:
        from ..copr.fusion import SPLIT_REASONS

        return {
            "splits_total": snap.get("fusion_splits_total", 0),
            "by_reason": {
                r: snap.get(
                    "fusion_splits_reason_"
                    + r.replace("-", "_") + "_total", 0)
                for r in SPLIT_REASONS
            },
        }
    except Exception as e:  # pragma: no cover - defensive
        return {"error": repr(e)}


def _layout_section() -> dict:
    """The /status layout payload (never lets a tuner hiccup 500 the
    status port)."""
    try:
        from ..layout import status_section

        return status_section()
    except Exception as e:  # pragma: no cover - defensive
        return {"error": repr(e)}


class StatusServer:
    def __init__(self, domain, host: str = "127.0.0.1", port: int = 10080):
        self.domain = domain
        self.host = host
        self.port = port
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    def start(self):
        domain = self.domain

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                if path == "/metrics":
                    lines = []
                    for name, val in sorted(REGISTRY.snapshot().items()):
                        metric = "tidb_tpu_" + name
                        lines.append(f"{metric} {val}")
                    body = ("\n".join(lines) + "\n").encode()
                    self._send(200, body, "text/plain; version=0.0.4")
                    return
                if path in ("/status", "/"):
                    from ..coord import get_plane
                    from ..copr.cache import PROGRAM_CACHES
                    from ..copr.device_health import DEVICE_HEALTH
                    from ..metrics import COORD_STATUS_METRICS
                    from ..trace import TRACE_RING

                    running = sum(
                        1 for s in domain.sessions.values()
                        if getattr(s, "stmt_start", None) is not None)
                    recent = []
                    for tr in list(TRACE_RING)[-8:]:
                        try:
                            tot = tr.phase_totals()
                            recent.append({
                                "sql": tr.sql[:128],
                                "conn_id": tr.conn_id,
                                "duration_ms": round(tr.duration_ms(), 3),
                                "compile_ms": round(tot["compile_ms"], 3),
                                "transfer_bytes": tot["transfer_bytes"],
                                "device_ms": round(tot["device_ms"], 3),
                                "readback_ms": round(tot["readback_ms"], 3),
                                "backoff_ms": round(tot["backoff_ms"], 3),
                                "backfill_ms": round(
                                    tot.get("backfill_ms", 0.0), 3),
                                "wire_bytes": tot["wire_bytes"],
                                "engines": tot["engines"],
                            })
                        except Exception:
                            continue  # a live trace mutating mid-walk
                    plane = get_plane()
                    view = plane.view()
                    snap = REGISTRY.snapshot()
                    body = json.dumps({
                        "version": VERSION,
                        "git_hash": "",
                        "ddl_schema_version":
                            domain.catalog.schema_version,
                        "connections": len(domain.sessions),
                        "running_statements": running,
                        "gc_safe_point":
                            domain.maintenance.last_safepoint,
                        # circuit-breaker summary (PR-2 follow-up (d)):
                        # operators watching the status port see a sick
                        # chip without querying information_schema
                        "tripped_devices":
                            list(DEVICE_HEALTH.tripped_ids()),
                        # N most recent finished query traces with their
                        # per-phase totals (the trace subsystem's ring)
                        "recent_traces": recent,
                        # LRU-bounded compiled-program caches (tile/mesh/
                        # MPP/micro-batch): with shape buckets on, hit
                        # rate tracks query SHAPE CLASSES, not literals
                        "compiled_programs": {
                            c.name: c.stats() for c in PROGRAM_CACHES
                        },
                        # coordination plane (ISSUE 9): membership epoch
                        # + per-process healthy device sets, and the
                        # failover / span-forwarding / handoff counters
                        "coord": {
                            "kind": plane.kind,
                            "epoch": view.epoch,
                            "formed": view.formed,
                            "members": {
                                str(p): list(ids) for p, ids
                                in sorted(view.members.items())
                            },
                            "metrics": {
                                name: snap.get(name, 0)
                                for name in COORD_STATUS_METRICS
                            },
                        },
                        # adaptive data layout (ISSUE 10): per-column
                        # encoding/tier decisions, hot/cold tier byte
                        # gauges and the cold-tier traffic counters
                        "layout": _layout_section(),
                        # zero-host-tail compilation (ISSUE 11): region
                        # splits by reason — regressions in fusion
                        # coverage are visible per cause at a glance
                        "fusion": _fusion_section(snap),
                    }).encode()
                    self._send(200, body, "application/json")
                    return
                if path == "/device-health":
                    # full breaker state, mirroring information_schema.
                    # TIDB_TPU_DEVICE_HEALTH (region_cache.go's store
                    # health surfaced on http_status.go's /regions model)
                    from ..copr.device_health import DEVICE_HEALTH

                    body = json.dumps({
                        "devices": [{
                            "device_id": st.device_id,
                            "state": st.state,
                            "error_count": st.error_count,
                            "consecutive_errors": st.consecutive_errors,
                            "trip_count": st.trip_count,
                            "last_error": st.last_error,
                        } for st in DEVICE_HEALTH.snapshot()],
                        "tripped":
                            list(DEVICE_HEALTH.tripped_ids()),
                    }).encode()
                    self._send(200, body, "application/json")
                    return
                if path == "/schema":
                    isc = domain.catalog.info_schema()
                    out = {}
                    for db in isc.schema_names():
                        out[db] = [
                            {"name": t.name, "id": t.id,
                             "is_view": t.is_view,
                             "partitions": [p.name for p in
                                            t.partition_info.defs]
                             if t.partition_info else None}
                            for t in isc.tables(db)
                        ]
                    self._send(200, json.dumps(out).encode(),
                               "application/json")
                    return
                self._send(404, b"not found\n", "text/plain")

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="tidb-tpu-status",
            daemon=True)
        self._thread.start()
        return self.host, self.port

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
