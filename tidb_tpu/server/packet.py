"""MySQL wire packet framing + primitive codecs.

Reference: server/packetio.go (3-byte little-endian length + sequence id
framing), util/hack + protocol encoders in server/conn.go.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Tuple

MAX_PACKET = 1 << 24 - 1


def lenenc_int(n: int) -> bytes:
    if n < 0xFB:
        return bytes([n])
    if n < (1 << 16):
        return b"\xfc" + struct.pack("<H", n)
    if n < (1 << 24):
        return b"\xfd" + struct.pack("<I", n)[:3]
    return b"\xfe" + struct.pack("<Q", n)


def read_lenenc_int(buf: bytes, pos: int) -> Tuple[int, int]:
    c = buf[pos]
    if c < 0xFB:
        return c, pos + 1
    if c == 0xFC:
        return struct.unpack_from("<H", buf, pos + 1)[0], pos + 3
    if c == 0xFD:
        return int.from_bytes(buf[pos + 1:pos + 4], "little"), pos + 4
    return struct.unpack_from("<Q", buf, pos + 1)[0], pos + 9


def lenenc_str(s: bytes) -> bytes:
    return lenenc_int(len(s)) + s


def read_lenenc_str(buf: bytes, pos: int) -> Tuple[bytes, int]:
    n, pos = read_lenenc_int(buf, pos)
    return buf[pos:pos + n], pos + n


class PacketWriter:
    def __init__(self, writer):
        self.writer = writer
        self.seq = 0

    def reset_seq(self):
        self.seq = 0

    async def send(self, payload: bytes):
        off = 0
        n = len(payload)
        while True:
            chunk = payload[off:off + 0xFFFFFF]
            header = len(chunk).to_bytes(3, "little") + bytes([self.seq & 0xFF])
            self.writer.write(header + chunk)
            self.seq += 1
            off += len(chunk)
            if off >= n and len(chunk) != 0xFFFFFF:
                break
        await self.writer.drain()


class PacketReader:
    def __init__(self, reader):
        self.reader = reader
        self.seq = 0

    async def recv(self) -> Optional[bytes]:
        parts = []
        while True:
            header = await self.reader.readexactly(4)
            length = int.from_bytes(header[:3], "little")
            self.seq = header[3] + 1
            body = await self.reader.readexactly(length) if length else b""
            parts.append(body)
            if length != 0xFFFFFF:
                break
        return b"".join(parts)
