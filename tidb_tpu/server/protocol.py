"""MySQL protocol payloads: handshake, OK/ERR/EOF, column defs, row codecs.

Reference: server/conn.go (writeInitialHandshake :600s, handshake response
parse, writeOK/writeError), server/column.go (column definition 41),
server/util.go (dumpTextRow/dumpBinaryRow).
"""

from __future__ import annotations

import struct
from typing import List, Optional

from ..types import FieldType, TypeKind
from .packet import lenenc_int, lenenc_str

PROTOCOL_VERSION = 10
SERVER_VERSION = b"8.0.11-tidb-tpu-1.0"

# capability flags
CLIENT_LONG_PASSWORD = 1
CLIENT_FOUND_ROWS = 2
CLIENT_LONG_FLAG = 4
CLIENT_CONNECT_WITH_DB = 8
CLIENT_PROTOCOL_41 = 512
CLIENT_TRANSACTIONS = 8192
CLIENT_SECURE_CONNECTION = 32768
CLIENT_PLUGIN_AUTH = 1 << 19
CLIENT_DEPRECATE_EOF = 1 << 24

SERVER_CAPS = (
    CLIENT_LONG_PASSWORD | CLIENT_FOUND_ROWS | CLIENT_LONG_FLAG
    | CLIENT_CONNECT_WITH_DB | CLIENT_PROTOCOL_41 | CLIENT_TRANSACTIONS
    | CLIENT_SECURE_CONNECTION | CLIENT_PLUGIN_AUTH
)

# column types (mysql protocol)
T_DECIMAL = 0x00
T_TINY = 0x01
T_LONGLONG = 0x08
T_DOUBLE = 0x05
T_NULL = 0x06
T_DATE = 0x0A
T_DATETIME = 0x0C
T_VARCHAR = 0x0F
T_NEWDECIMAL = 0xF6
T_VAR_STRING = 0xFD

_KIND_TO_MYSQL = {
    TypeKind.NULLTYPE: T_NULL,
    TypeKind.INT: T_LONGLONG,
    TypeKind.UINT: T_LONGLONG,
    TypeKind.BOOL: T_TINY,
    TypeKind.FLOAT: T_DOUBLE,
    TypeKind.DECIMAL: T_NEWDECIMAL,
    TypeKind.STRING: T_VAR_STRING,
    TypeKind.DATE: T_DATE,
    TypeKind.DATETIME: T_DATETIME,
}


def handshake_v10(conn_id: int, salt: bytes) -> bytes:
    out = bytes([PROTOCOL_VERSION]) + SERVER_VERSION + b"\x00"
    out += struct.pack("<I", conn_id)
    out += salt[:8] + b"\x00"
    out += struct.pack("<H", SERVER_CAPS & 0xFFFF)
    out += bytes([33])  # charset utf8
    out += struct.pack("<H", 2)  # status: autocommit
    out += struct.pack("<H", (SERVER_CAPS >> 16) & 0xFFFF)
    out += bytes([21])  # auth data len
    out += b"\x00" * 10
    out += salt[8:20] + b"\x00"
    out += b"mysql_native_password\x00"
    return out


def parse_handshake_response(data: bytes) -> dict:
    caps = struct.unpack_from("<I", data, 0)[0]
    pos = 4 + 4 + 1 + 23  # caps, max packet, charset, filler
    end = data.index(b"\x00", pos)
    user = data[pos:end].decode("utf8", "replace")
    pos = end + 1
    if caps & CLIENT_SECURE_CONNECTION:
        alen = data[pos]
        pos += 1
        auth = data[pos:pos + alen]
        pos += alen
    else:
        end = data.index(b"\x00", pos)
        auth = data[pos:end]
        pos = end + 1
    db = ""
    if caps & CLIENT_CONNECT_WITH_DB and pos < len(data):
        end = data.find(b"\x00", pos)
        if end < 0:
            end = len(data)
        db = data[pos:end].decode("utf8", "replace")
    return {"caps": caps, "user": user, "auth": auth, "db": db}


def ok_packet(affected: int = 0, last_insert_id: int = 0,
              status: int = 2, warnings: int = 0) -> bytes:
    return (b"\x00" + lenenc_int(affected) + lenenc_int(last_insert_id)
            + struct.pack("<HH", status, warnings))


def eof_packet(status: int = 2, warnings: int = 0) -> bytes:
    return b"\xfe" + struct.pack("<HH", warnings, status)


def err_packet(code: int, message: str, state: str = "HY000") -> bytes:
    return (b"\xff" + struct.pack("<H", code) + b"#" + state.encode()
            + message.encode("utf8", "replace")[:400])


def column_def(name: str, ft: Optional[FieldType]) -> bytes:
    mt = wire_kind(ft)
    charset = 63 if mt in (T_LONGLONG, T_DOUBLE) else 33
    out = lenenc_str(b"def")           # catalog
    out += lenenc_str(b"")             # schema
    out += lenenc_str(b"")             # table
    out += lenenc_str(b"")             # org_table
    out += lenenc_str(name.encode("utf8", "replace"))
    out += lenenc_str(name.encode("utf8", "replace"))
    out += bytes([0x0C])
    out += struct.pack("<H", charset)
    out += struct.pack("<I", 1024)     # column length
    out += bytes([mt])
    out += struct.pack("<H", 0)        # flags
    decimals = ft.scale if ft and ft.kind == TypeKind.DECIMAL else 0
    out += bytes([decimals])
    out += b"\x00\x00"
    return out


def text_row(values) -> bytes:
    out = b""
    for v in values:
        if v is None:
            out += b"\xfb"
        else:
            if isinstance(v, float):
                s = repr(v)
            else:
                s = str(v)
            out += lenenc_str(s.encode("utf8", "replace"))
    return out


def wire_kind(ft: Optional[FieldType]) -> int:
    """Column type actually used on the wire.  DATE/DATETIME/DECIMAL go as
    strings (the session pre-formats them), so they are declared VAR_STRING
    and both text and binary rows encode them as lenenc strings."""
    if ft is None:
        return T_VAR_STRING
    if ft.kind in (TypeKind.INT, TypeKind.UINT, TypeKind.BOOL):
        return T_LONGLONG
    if ft.kind == TypeKind.FLOAT:
        return T_DOUBLE
    return T_VAR_STRING


def binary_row(values, fts) -> bytes:
    """Binary-protocol resultset row (conn_stmt dumpBinaryRow): 0x00 header,
    NULL bitmap with offset 2, then values encoded per declared wire type."""
    n = len(values)
    bitmap = bytearray((n + 9) // 8)
    body = b""
    for i, v in enumerate(values):
        if v is None:
            pos = i + 2
            bitmap[pos // 8] |= 1 << (pos % 8)
            continue
        wk = wire_kind(fts[i] if fts and i < len(fts) else None)
        if wk == T_LONGLONG:
            body += struct.pack("<q", int(v))
        elif wk == T_DOUBLE:
            body += struct.pack("<d", float(v))
        else:
            s = repr(v) if isinstance(v, float) else str(v)
            body += lenenc_str(s.encode("utf8", "replace"))
    return b"\x00" + bytes(bitmap) + body
