"""MySQL-wire server: asyncio listener bridging connections to sessions.

Reference: server/server.go (Server, connection loop), server/conn.go:800
(clientConn.dispatch), conn_stmt.go (prepared-statement commands).  SQL
execution itself runs in a thread pool (sessions are synchronous; numpy/JAX
release the GIL), so one slow query doesn't stall other connections —
the goroutine-per-conn model mapped onto asyncio + executor threads.

Admission control & graceful drain (server.go onConn/kickIdleConnection +
tidb-server SIGTERM handling):

- a hard connection cap: past `max_connections` the client gets a fast
  ERR 1040 instead of a handshake (MySQL's Too many connections);
- a bounded executor queue: statements past the worker pool's capacity
  wait in a bounded admission queue with a queue deadline; past the bound
  (or the deadline) the statement is REJECTED with a MySQL error instead
  of queueing unboundedly — overload sheds load at the front door;
- graceful drain: shutdown()/SIGTERM stops the listener, lets in-flight
  statements run to their own deadlines within the drain budget, then
  cancels survivors through their QueryScope (reason 'shutdown') and
  closes connections cleanly.
"""

from __future__ import annotations

import asyncio
import os
import struct
import time as _time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Optional

from ..errors import TiDBTPUError
from ..metrics import REGISTRY
from ..session import Domain, ResultSet
from . import protocol as P
from .packet import PacketReader, PacketWriter, read_lenenc_int

COM_QUIT = 0x01
COM_INIT_DB = 0x02
COM_QUERY = 0x03
COM_FIELD_LIST = 0x04
COM_PING = 0x0E
COM_STMT_PREPARE = 0x16
COM_STMT_EXECUTE = 0x17
COM_STMT_CLOSE = 0x19
COM_STMT_RESET = 0x1A


class MySQLServer:
    def __init__(self, domain: Optional[Domain] = None, host: str = "127.0.0.1",
                 port: int = 4000, workers: int = 8,
                 max_connections: int = 512,
                 max_queued: Optional[int] = None,
                 queue_deadline_s: float = 10.0):
        self.domain = domain or Domain()
        self.host = host
        self.port = port
        self.workers = workers
        self.pool = ThreadPoolExecutor(max_workers=workers)
        self._server: Optional[asyncio.AbstractServer] = None
        # ---- admission bounds (server.go Server.rwlock + clients map) --
        self.max_connections = max_connections
        # waiters allowed behind the busy worker pool; past this the
        # statement fast-rejects instead of queueing unboundedly
        self.max_queued = workers * 4 if max_queued is None else max_queued
        self.queue_deadline_s = queue_deadline_s
        self._admission: Optional[asyncio.Semaphore] = None  # loop-bound
        self._queued = 0
        self._nconns = 0
        self._draining = False
        # live connections: asyncio task -> (session, writer); drain
        # cancels scopes and closes writers through this registry
        self._conns: Dict[object, tuple] = {}
        # periodic eager session checkpointing (lifecycle follow-up (d)):
        # started with the server when tidb_tpu_handoff_checkpoint_s > 0
        self._checkpoint_task: Optional[asyncio.Task] = None
        # True while the plane holds a checkpoint THIS server parked: an
        # empty collection then CLEARS the parked bundle instead of
        # leaving a stale one for the next restart to resurrect
        self._checkpointed = False

    async def start(self):
        self._admission = asyncio.Semaphore(self.workers)
        self._draining = False
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        addr = self._server.sockets[0].getsockname()
        self.port = addr[1]
        # rolling-restart handoff (coord plane): adopt any session state
        # a draining predecessor parked — prepared statements + session
        # sysvars replay into fresh sessions at THIS process's epoch
        try:
            from ..coord import get_plane
            from ..lifecycle import replay_session_states

            states = get_plane().take_handoff()
            if states:
                replay_session_states(self.domain, states)
        except Exception:
            REGISTRY.inc("coord_handoff_failed_total")
        # periodic eager checkpointing: a HARD-killed process (no drain)
        # loses at most one interval's worth of prepared-session churn,
        # because the plane already holds a recent handoff bundle the
        # replacement replays.  The sysvar is re-read every tick, so
        # SET GLOBAL tidb_tpu_handoff_checkpoint_s enables/disables the
        # policy on a live server.
        self._checkpoint_task = asyncio.create_task(
            self._checkpoint_loop())
        return addr

    def _checkpoint_interval_s(self) -> float:
        from ..session.vars import SessionVars

        return float(SessionVars(self.domain.global_vars).get_int(
            "tidb_tpu_handoff_checkpoint_s", 0))

    async def _checkpoint_loop(self):
        from ..coord import get_plane
        from ..lifecycle import collect_session_states

        while not self._draining:
            iv = self._checkpoint_interval_s()
            await asyncio.sleep(iv if iv > 0 else 1.0)
            if iv <= 0 or self._draining:
                continue
            try:
                states = collect_session_states(self.domain)
                if states:
                    get_plane().handoff_put(states)
                    self._checkpointed = True
                    REGISTRY.inc("coord_handoff_checkpoint_total")
                elif self._checkpointed:
                    # every prepared session is gone: clear the parked
                    # bundle, or a later restart would replay ghost
                    # sessions no client owns
                    get_plane().take_handoff()
                    self._checkpointed = False
            except asyncio.CancelledError:
                raise
            except Exception:
                # a dead coordinator must never take the server down;
                # the drain-time handoff still gets its own attempt
                REGISTRY.inc("coord_handoff_failed_total")

    async def stop(self):
        """Immediate stop: drain with a zero budget (in-flight statements
        are cancelled right away with reason 'shutdown')."""
        await self.shutdown(drain_s=0.0)

    async def shutdown(self, drain_s: float = 15.0):
        """Graceful drain (tidb-server SIGTERM: gracefulShutdown):
        1. stop accepting — the listener closes, new connects fail fast;
        2. in-flight statements keep running up to `drain_s` (each still
           bounded by its own max_execution_time deadline);
        3. survivors are cancelled through their QueryScope with reason
           'shutdown' (ERR 1053 to the client at the next host seam);
        4. connections close and the worker pool shuts down."""
        self._draining = True
        if self._checkpoint_task is not None:
            self._checkpoint_task.cancel()
            self._checkpoint_task = None
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        loop = asyncio.get_running_loop()
        deadline = loop.time() + max(drain_s, 0.0)
        while loop.time() < deadline:
            busy = [s for _t, (s, _w) in list(self._conns.items())
                    if getattr(s, "stmt_start", None) is not None]
            if not busy:
                break
            await asyncio.sleep(0.02)
        # cancel survivors: the scope wakes backoff sleeps, fan-out
        # workers and SLEEP()s; the statement errors at its next seam.
        # The sweep REPEATS while waiting for statements to unwind — a
        # statement that raced past the draining checks into execution
        # is cancelled on the next pass instead of surviving the drain.
        cancelled = 0
        unwind_deadline = loop.time() + 5.0
        while True:
            busy = [s for _t, (s, _w) in list(self._conns.items())
                    if getattr(s, "stmt_start", None) is not None]
            for sess in busy:
                sc = getattr(sess, "_scope", None)
                if sc is None or not sc.cancelled():
                    cancelled += 1
                sess.cancel_query("shutdown")
            if not busy or loop.time() >= unwind_deadline:
                break
            await asyncio.sleep(0.02)
        if cancelled:
            REGISTRY.inc("server_drain_cancelled_total", cancelled)
            await asyncio.sleep(0.05)  # flush the ERR 1053 writes
        # session-state handoff (rolling restart, coord plane): park
        # every prepared session on the coordinator BEFORE connections
        # close, so the replacement process replays them when it rejoins
        # at a new epoch.  A failed put (chaos site coord/handoff, dead
        # coordinator) must never block the drain — the sessions are
        # lost, counted, and the shutdown completes.
        try:
            from ..coord import get_plane
            from ..lifecycle import collect_session_states

            states = collect_session_states(self.domain)
            if states:
                get_plane().handoff_put(states)
            elif self._checkpointed:
                # a periodic checkpoint parked sessions that have since
                # gone away: drain-time truth is "nothing to hand off"
                get_plane().take_handoff()
            self._checkpointed = False
        except Exception:
            REGISTRY.inc("coord_handoff_failed_total")
        try:
            # graceful departure is independent of handoff success: the
            # epoch must bump NOW (not at lease expiry) so survivors
            # rebuild immediately even when the handoff was lost
            from ..coord import get_plane

            get_plane().leave()
        except Exception:
            REGISTRY.inc("coord_rpc_errors_total")
        # unblock connection loops parked in pr.recv() and wait for the
        # handlers to unwind (they run their own session cleanup)
        for _t, (_s, writer) in list(self._conns.items()):
            try:
                writer.close()
            except Exception:
                pass
        tasks = list(self._conns)
        if tasks:
            await asyncio.wait(tasks, timeout=5.0)
        self.pool.shutdown(wait=False)

    # ------------------------------------------------------------------
    async def _handle(self, reader, writer):
        pw0 = PacketWriter(writer)
        if self._draining:
            # reject-at-accept during drain (a connect can race the
            # listener close): MySQL's shutdown-in-progress error
            await pw0.send(P.err_packet(
                1053, "Server shutdown in progress", "08S01"))
            writer.close()
            return
        if self._nconns >= self.max_connections:
            # hard cap (MySQL max_connections): ERR instead of handshake,
            # so overload costs the client one round trip, not a stall
            REGISTRY.inc("server_connections_rejected_total")
            await pw0.send(P.err_packet(
                1040, "Too many connections", "08004"))
            writer.close()
            return
        self._nconns += 1
        task = asyncio.current_task()
        sess = None
        try:
            sess = self.domain.new_session()
            self._conns[task] = (sess, writer)
            pr, pw = PacketReader(reader), pw0
            loop = asyncio.get_running_loop()
            prepared: Dict[int, str] = {}
            next_stmt_id = [1]
            salt = os.urandom(20)
            await pw.send(P.handshake_v10(sess.conn_id, salt))
            resp = await pr.recv()
            hs = P.parse_handshake_response(resp)
            pw.seq = pr.seq
            # mysql_native_password verification against the grant tables
            # (server/conn.go openSessionAndDoAuth analog); the client's
            # address picks the most specific user@host account
            peer = writer.get_extra_info("peername")
            client_host = peer[0] if peer else "localhost"
            account = self.domain.priv.auth(hs["user"], hs["auth"], salt,
                                            host=client_host)
            if account is None:
                await pw.send(P.err_packet(
                    1045,
                    f"Access denied for user '{hs['user']}'"
                    f"@'{client_host}'",
                    "28000"))
                return
            sess.user = account
            # default roles activate at login (MySQL activate_all_roles
            # off: only the DEFAULT set)
            sess.active_roles = sorted(
                self.domain.priv.default_roles(account))
            if hs["db"]:
                try:
                    sess.execute(f"use {hs['db']}")
                except TiDBTPUError:
                    pass
            await pw.send(P.ok_packet())

            while True:
                pr.seq = 0
                # socket wait measured at the asyncio level: it becomes
                # the statement's wire.read span, so traces distinguish
                # network/client wait from admission-queue wait
                t_recv = _time.perf_counter_ns()
                data = await pr.recv()
                recv_wait_ns = _time.perf_counter_ns() - t_recv
                if not data:
                    break
                pw.seq = pr.seq
                cmd, payload = data[0], data[1:]
                if cmd == COM_QUIT:
                    break
                if cmd == COM_PING:
                    await pw.send(P.ok_packet())
                    continue
                if cmd == COM_INIT_DB:
                    await self._run_sql(
                        sess, f"use {payload.decode()}", pw, loop,
                        recv_wait_ns=recv_wait_ns,
                    )
                    continue
                if cmd == COM_QUERY:
                    sql = payload.decode("utf8", "replace")
                    await self._run_sql(sess, sql, pw, loop,
                                        recv_wait_ns=recv_wait_ns)
                    continue
                if cmd == COM_FIELD_LIST:
                    await pw.send(P.eof_packet())
                    continue
                if cmd == COM_STMT_PREPARE:
                    sql = payload.decode("utf8", "replace")
                    sid = next_stmt_id[0]
                    next_stmt_id[0] += 1
                    n_params = _count_params(sql)
                    prepared[sid] = {"sql": sql, "n": n_params,
                                     "types": None}
                    out = (b"\x00" + struct.pack("<I", sid)
                           + struct.pack("<H", 0)          # columns
                           + struct.pack("<H", n_params)
                           + b"\x00" + struct.pack("<H", 0))
                    await pw.send(out)
                    for _ in range(n_params):
                        await pw.send(P.column_def("?", None))
                    if n_params:
                        await pw.send(P.eof_packet())
                    continue
                if cmd == COM_STMT_EXECUTE:
                    sid = struct.unpack_from("<I", payload, 0)[0]
                    st = prepared.get(sid)
                    if st is None:
                        await pw.send(P.err_packet(1243, "unknown stmt"))
                        continue
                    params, st["types"] = _parse_exec_params(
                        payload, st["n"], st["types"]
                    )
                    await self._run_sql(sess, st["sql"], pw, loop,
                                        params=params, binary=True,
                                        recv_wait_ns=recv_wait_ns)
                    continue
                if cmd in (COM_STMT_CLOSE, COM_STMT_RESET):
                    sid = struct.unpack_from("<I", payload, 0)[0]
                    prepared.pop(sid, None)
                    if cmd == COM_STMT_RESET:
                        await pw.send(P.ok_packet())
                    continue
                await pw.send(P.err_packet(1047, f"unknown command {cmd}"))
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            self._conns.pop(task, None)
            self._nconns -= 1
            if sess is not None:
                sess.close()  # unpin snapshots + rollback
                sess._release_table_locks()  # MySQL frees on disconnect
                self.domain.sessions.pop(sess.conn_id, None)
            writer.close()

    async def _run_sql(self, sess, sql: str, pw: PacketWriter, loop,
                       params=None, binary: bool = False,
                       recv_wait_ns: int = 0):
        # ---- bounded admission (the overload front door) --------------
        # the worker pool admits `workers` statements; up to max_queued
        # more wait (bounded by queue_deadline_s); anything past that is
        # REJECTED NOW — under overload the queue must not grow without
        # bound, and a fast error beats a stuck client
        if self._draining:
            # statements arriving after drain started are refused (the
            # survivor-cancel sweep must not race freshly admitted work)
            await self._reject_shutdown(pw, sql)
            return
        sem = self._admission
        wait_ns = 0
        if sem is not None:
            if sem.locked() and self._queued >= self.max_queued:
                await self._reject_overload(pw, sql, "admission queue full")
                return
            t0 = _time.perf_counter_ns()
            self._queued += 1
            # live queue-depth gauge: the serving layer's ADAPTIVE
            # micro-batch window reads this to widen under pressure
            # (queued statements = batching opportunity) and shrink
            # back when the queue drains
            REGISTRY.set("admission_queue_depth", float(self._queued))
            try:
                await asyncio.wait_for(sem.acquire(),
                                       timeout=self.queue_deadline_s)
            except asyncio.TimeoutError:
                await self._reject_overload(
                    pw, sql, "admission queue deadline exceeded "
                             f"({self.queue_deadline_s:.1f}s)")
                return
            finally:
                self._queued -= 1
                REGISTRY.set("admission_queue_depth", float(self._queued))
            wait_ns = _time.perf_counter_ns() - t0
            REGISTRY.observe("admission_wait_ms", wait_ns / 1e6)
        try:
            if self._draining:
                # drain began while this statement waited in the queue
                await self._reject_shutdown(pw, sql)
                return
            await self._run_sql_admitted(sess, sql, pw, loop, params,
                                         binary, recv_wait_ns, wait_ns)
        finally:
            if sem is not None:
                sem.release()

    async def _reject_overload(self, pw: PacketWriter, sql: str, what: str):
        """Fast overload rejection: one source of truth for the error
        (ServerOverloadedError), the metrics and the termination record."""
        from ..errors import ServerOverloadedError

        err = ServerOverloadedError(what)
        REGISTRY.inc("admission_rejected_total")
        REGISTRY.inc("stmt_terminated_overload_total")
        self.domain.record_termination(sql, "overload")
        await pw.send(P.err_packet(err.code, str(err), "08004"))

    async def _reject_shutdown(self, pw: PacketWriter, sql: str):
        """Refuse a statement arriving mid-drain: same metric + summary
        accounting as every other termination reason."""
        from ..errors import ServerShutdownError

        err = ServerShutdownError()
        REGISTRY.inc("stmt_terminated_shutdown_total")
        self.domain.record_termination(sql, "shutdown")
        await pw.send(P.err_packet(err.code, str(err), "08S01"))

    async def _run_sql_admitted(self, sess, sql: str, pw: PacketWriter,
                                loop, params, binary: bool,
                                recv_wait_ns: int, admission_wait_ns: int):
        # wire.read attribution: the statement's trace root records how
        # many bytes the COM_QUERY/COM_STMT_EXECUTE payload carried and
        # how long the server waited on the socket for it (an asyncio-
        # level wire.read span, distinct from admission-queue wait)
        sess._pending_wire_read = (
            len(sql.encode("utf8", "replace")), recv_wait_ns)
        sess._pending_admission_wait_ns = admission_wait_ns
        try:
            rss = await loop.run_in_executor(
                self.pool, lambda: sess.execute(sql, params)
            )
        except TiDBTPUError as e:
            # typed errors carry their MySQL code (errors.py hierarchy)
            await pw.send(P.err_packet(getattr(e, "code", 1105), str(e)))
            return
        except Exception as e:  # pragma: no cover - defensive
            await pw.send(P.err_packet(1105, f"internal error: {e}"))
            return
        rs = rss[-1] if rss else ResultSet()
        if not rs.is_query:
            await pw.send(P.ok_packet(rs.affected_rows, rs.last_insert_id,
                                      warnings=len(rs.warnings)))
            return
        t0 = _time.perf_counter_ns()
        nbytes = 0
        fts = rs.ftypes
        await pw.send(bytes([len(rs.headers)]))
        for i, h in enumerate(rs.headers):
            await pw.send(P.column_def(
                h, fts[i] if fts and i < len(fts) else None
            ))
        await pw.send(P.eof_packet())
        encode = (lambda r: P.binary_row(r, fts)) if binary else P.text_row
        for row in rs.rows:
            pkt = encode(row)
            nbytes += len(pkt)
            await pw.send(pkt)
        await pw.send(P.eof_packet())
        tr = getattr(sess, "last_trace", None)
        if tr is not None and tr.finished and tr.sql == sql:
            # result encode+write time, appended onto the finished trace
            # (the statement ended before its rows hit the socket)
            tr.add_span("wire.write", _time.perf_counter_ns() - t0,
                        bytes=nbytes, rows=len(rs.rows))


def _count_params(sql: str) -> int:
    """Placeholder count via the real parser (a raw '?' scan miscounts
    question marks inside string literals); falls back to the scan only
    when the statement does not parse at PREPARE time."""
    try:
        from ..parser.parser import Parser

        p = Parser(sql)
        p.parse_statements()
        return p.n_params
    except Exception:
        return sql.count("?")


def _parse_exec_params(payload: bytes, n_params: int, cached_types):
    """COM_STMT_EXECUTE payload -> (values, types).  Types arrive only on
    the first execute (new_params_bound_flag=1); later executes reuse the
    cached ones per protocol."""
    if n_params == 0:
        return [], cached_types
    pos = 4 + 1 + 4  # stmt_id, flags, iteration count (cmd byte stripped)
    null_bytes = (n_params + 7) // 8
    null_bitmap = payload[pos:pos + null_bytes]
    pos += null_bytes
    new_bound = payload[pos]
    pos += 1
    types = []
    if new_bound:
        for _ in range(n_params):
            types.append((payload[pos], payload[pos + 1]))
            pos += 2
    elif cached_types:
        types = cached_types
    values = []
    for i in range(n_params):
        if null_bitmap[i // 8] & (1 << (i % 8)):
            values.append(None)
            continue
        t = types[i][0] if types else 0xFD
        if t in (0x01,):  # tiny
            values.append(struct.unpack_from("<b", payload, pos)[0])
            pos += 1
        elif t in (0x02,):  # short
            values.append(struct.unpack_from("<h", payload, pos)[0])
            pos += 2
        elif t in (0x03,):  # long
            values.append(struct.unpack_from("<i", payload, pos)[0])
            pos += 4
        elif t in (0x08,):  # longlong
            values.append(struct.unpack_from("<q", payload, pos)[0])
            pos += 8
        elif t in (0x04,):  # float
            values.append(struct.unpack_from("<f", payload, pos)[0])
            pos += 4
        elif t in (0x05,):  # double
            values.append(struct.unpack_from("<d", payload, pos)[0])
            pos += 8
        else:  # string-ish
            n, pos = read_lenenc_int(payload, pos)
            values.append(payload[pos:pos + n].decode("utf8", "replace"))
            pos += n
    return values, types


def serve_forever(host: str = "127.0.0.1", port: int = 4000,
                  domain: Optional[Domain] = None,
                  drain_s: float = 15.0):
    """Blocking entry point (tidb-server/main.go analog).

    Shutdown-aware: SIGTERM/SIGINT resolve a future instead of the old
    `while True: sleep(3600)` loop (which ignored both and could only be
    SIGKILLed).  On signal the server drains gracefully — stops
    accepting, lets in-flight statements finish within `drain_s`, cancels
    survivors with termination reason 'shutdown' — and this function
    RETURNS."""

    async def main():
        srv = MySQLServer(domain, host, port)
        await srv.start()
        print(f"tidb-tpu listening on {srv.host}:{srv.port}")
        loop = asyncio.get_running_loop()
        stop = loop.create_future()

        def request_stop(*_a):
            if not stop.done():
                stop.set_result(None)

        import signal

        for signum in (signal.SIGTERM, signal.SIGINT):
            try:
                loop.add_signal_handler(signum, request_stop)
            except (NotImplementedError, RuntimeError):
                # platforms/loops without signal-handler support fall
                # back to the interpreter-level handler
                signal.signal(signum,
                              lambda *_a: loop.call_soon_threadsafe(
                                  request_stop))
        await stop
        print("tidb-tpu draining...")
        await srv.shutdown(drain_s=drain_s)
        print("tidb-tpu stopped")

    asyncio.run(main())
