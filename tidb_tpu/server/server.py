"""MySQL-wire server: asyncio listener bridging connections to sessions.

Reference: server/server.go (Server, connection loop), server/conn.go:800
(clientConn.dispatch), conn_stmt.go (prepared-statement commands).  SQL
execution itself runs in a thread pool (sessions are synchronous; numpy/JAX
release the GIL), so one slow query doesn't stall other connections —
the goroutine-per-conn model mapped onto asyncio + executor threads.
"""

from __future__ import annotations

import asyncio
import os
import struct
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Optional

from ..errors import TiDBTPUError
from ..session import Domain, ResultSet
from . import protocol as P
from .packet import PacketReader, PacketWriter, read_lenenc_int

COM_QUIT = 0x01
COM_INIT_DB = 0x02
COM_QUERY = 0x03
COM_FIELD_LIST = 0x04
COM_PING = 0x0E
COM_STMT_PREPARE = 0x16
COM_STMT_EXECUTE = 0x17
COM_STMT_CLOSE = 0x19
COM_STMT_RESET = 0x1A


class MySQLServer:
    def __init__(self, domain: Optional[Domain] = None, host: str = "127.0.0.1",
                 port: int = 4000, workers: int = 8):
        self.domain = domain or Domain()
        self.host = host
        self.port = port
        self.pool = ThreadPoolExecutor(max_workers=workers)
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self):
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        addr = self._server.sockets[0].getsockname()
        self.port = addr[1]
        return addr

    async def stop(self):
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        self.pool.shutdown(wait=False)

    # ------------------------------------------------------------------
    async def _handle(self, reader, writer):
        sess = self.domain.new_session()
        pr, pw = PacketReader(reader), PacketWriter(writer)
        loop = asyncio.get_running_loop()
        prepared: Dict[int, str] = {}
        next_stmt_id = [1]
        try:
            salt = os.urandom(20)
            await pw.send(P.handshake_v10(sess.conn_id, salt))
            resp = await pr.recv()
            hs = P.parse_handshake_response(resp)
            pw.seq = pr.seq
            # mysql_native_password verification against the grant tables
            # (server/conn.go openSessionAndDoAuth analog); the client's
            # address picks the most specific user@host account
            peer = writer.get_extra_info("peername")
            client_host = peer[0] if peer else "localhost"
            account = self.domain.priv.auth(hs["user"], hs["auth"], salt,
                                            host=client_host)
            if account is None:
                await pw.send(P.err_packet(
                    1045,
                    f"Access denied for user '{hs['user']}'"
                    f"@'{client_host}'",
                    "28000"))
                return
            sess.user = account
            # default roles activate at login (MySQL activate_all_roles
            # off: only the DEFAULT set)
            sess.active_roles = sorted(
                self.domain.priv.default_roles(account))
            if hs["db"]:
                try:
                    sess.execute(f"use {hs['db']}")
                except TiDBTPUError:
                    pass
            await pw.send(P.ok_packet())

            while True:
                pr.seq = 0
                data = await pr.recv()
                if not data:
                    break
                pw.seq = pr.seq
                cmd, payload = data[0], data[1:]
                if cmd == COM_QUIT:
                    break
                if cmd == COM_PING:
                    await pw.send(P.ok_packet())
                    continue
                if cmd == COM_INIT_DB:
                    await self._run_sql(
                        sess, f"use {payload.decode()}", pw, loop
                    )
                    continue
                if cmd == COM_QUERY:
                    sql = payload.decode("utf8", "replace")
                    await self._run_sql(sess, sql, pw, loop)
                    continue
                if cmd == COM_FIELD_LIST:
                    await pw.send(P.eof_packet())
                    continue
                if cmd == COM_STMT_PREPARE:
                    sql = payload.decode("utf8", "replace")
                    sid = next_stmt_id[0]
                    next_stmt_id[0] += 1
                    n_params = _count_params(sql)
                    prepared[sid] = {"sql": sql, "n": n_params,
                                     "types": None}
                    out = (b"\x00" + struct.pack("<I", sid)
                           + struct.pack("<H", 0)          # columns
                           + struct.pack("<H", n_params)
                           + b"\x00" + struct.pack("<H", 0))
                    await pw.send(out)
                    for _ in range(n_params):
                        await pw.send(P.column_def("?", None))
                    if n_params:
                        await pw.send(P.eof_packet())
                    continue
                if cmd == COM_STMT_EXECUTE:
                    sid = struct.unpack_from("<I", payload, 0)[0]
                    st = prepared.get(sid)
                    if st is None:
                        await pw.send(P.err_packet(1243, "unknown stmt"))
                        continue
                    params, st["types"] = _parse_exec_params(
                        payload, st["n"], st["types"]
                    )
                    await self._run_sql(sess, st["sql"], pw, loop,
                                        params=params, binary=True)
                    continue
                if cmd in (COM_STMT_CLOSE, COM_STMT_RESET):
                    sid = struct.unpack_from("<I", payload, 0)[0]
                    prepared.pop(sid, None)
                    if cmd == COM_STMT_RESET:
                        await pw.send(P.ok_packet())
                    continue
                await pw.send(P.err_packet(1047, f"unknown command {cmd}"))
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            sess.close()  # unpin snapshots + rollback
            sess._release_table_locks()  # MySQL frees them on disconnect
            self.domain.sessions.pop(sess.conn_id, None)
            writer.close()

    async def _run_sql(self, sess, sql: str, pw: PacketWriter, loop,
                       params=None, binary: bool = False):
        import time as _time

        # wire.read attribution: the statement's trace root records how
        # many bytes the COM_QUERY/COM_STMT_EXECUTE payload carried
        sess._pending_wire_read = len(sql.encode("utf8", "replace"))
        try:
            rss = await loop.run_in_executor(
                self.pool, lambda: sess.execute(sql, params)
            )
        except TiDBTPUError as e:
            # typed errors carry their MySQL code (errors.py hierarchy)
            await pw.send(P.err_packet(getattr(e, "code", 1105), str(e)))
            return
        except Exception as e:  # pragma: no cover - defensive
            await pw.send(P.err_packet(1105, f"internal error: {e}"))
            return
        rs = rss[-1] if rss else ResultSet()
        if not rs.is_query:
            await pw.send(P.ok_packet(rs.affected_rows, rs.last_insert_id,
                                      warnings=len(rs.warnings)))
            return
        t0 = _time.perf_counter_ns()
        nbytes = 0
        fts = rs.ftypes
        await pw.send(bytes([len(rs.headers)]))
        for i, h in enumerate(rs.headers):
            await pw.send(P.column_def(
                h, fts[i] if fts and i < len(fts) else None
            ))
        await pw.send(P.eof_packet())
        encode = (lambda r: P.binary_row(r, fts)) if binary else P.text_row
        for row in rs.rows:
            pkt = encode(row)
            nbytes += len(pkt)
            await pw.send(pkt)
        await pw.send(P.eof_packet())
        tr = getattr(sess, "last_trace", None)
        if tr is not None and tr.finished and tr.sql == sql:
            # result encode+write time, appended onto the finished trace
            # (the statement ended before its rows hit the socket)
            tr.add_span("wire.write", _time.perf_counter_ns() - t0,
                        bytes=nbytes, rows=len(rs.rows))


def _count_params(sql: str) -> int:
    """Placeholder count via the real parser (a raw '?' scan miscounts
    question marks inside string literals); falls back to the scan only
    when the statement does not parse at PREPARE time."""
    try:
        from ..parser.parser import Parser

        p = Parser(sql)
        p.parse_statements()
        return p.n_params
    except Exception:
        return sql.count("?")


def _parse_exec_params(payload: bytes, n_params: int, cached_types):
    """COM_STMT_EXECUTE payload -> (values, types).  Types arrive only on
    the first execute (new_params_bound_flag=1); later executes reuse the
    cached ones per protocol."""
    if n_params == 0:
        return [], cached_types
    pos = 4 + 1 + 4  # stmt_id, flags, iteration count (cmd byte stripped)
    null_bytes = (n_params + 7) // 8
    null_bitmap = payload[pos:pos + null_bytes]
    pos += null_bytes
    new_bound = payload[pos]
    pos += 1
    types = []
    if new_bound:
        for _ in range(n_params):
            types.append((payload[pos], payload[pos + 1]))
            pos += 2
    elif cached_types:
        types = cached_types
    values = []
    for i in range(n_params):
        if null_bitmap[i // 8] & (1 << (i % 8)):
            values.append(None)
            continue
        t = types[i][0] if types else 0xFD
        if t in (0x01,):  # tiny
            values.append(struct.unpack_from("<b", payload, pos)[0])
            pos += 1
        elif t in (0x02,):  # short
            values.append(struct.unpack_from("<h", payload, pos)[0])
            pos += 2
        elif t in (0x03,):  # long
            values.append(struct.unpack_from("<i", payload, pos)[0])
            pos += 4
        elif t in (0x08,):  # longlong
            values.append(struct.unpack_from("<q", payload, pos)[0])
            pos += 8
        elif t in (0x04,):  # float
            values.append(struct.unpack_from("<f", payload, pos)[0])
            pos += 4
        elif t in (0x05,):  # double
            values.append(struct.unpack_from("<d", payload, pos)[0])
            pos += 8
        else:  # string-ish
            n, pos = read_lenenc_int(payload, pos)
            values.append(payload[pos:pos + n].decode("utf8", "replace"))
            pos += n
    return values, types


def serve_forever(host: str = "127.0.0.1", port: int = 4000,
                  domain: Optional[Domain] = None):
    """Blocking entry point (tidb-server/main.go analog)."""

    async def main():
        srv = MySQLServer(domain, host, port)
        await srv.start()
        print(f"tidb-tpu listening on {srv.host}:{srv.port}")
        while True:
            await asyncio.sleep(3600)

    asyncio.run(main())
