"""Shape-bucketed plan serving & query micro-batching.

The serving subsystem sits between the server's admission gate (PR 5)
and the coprocessor engines: its job is to make thousands of concurrent
clients share the small number of compiled XLA programs and device
dispatches the hardware actually needs.

Two mechanisms (ROADMAP "shape-bucketed plan serving + query
micro-batching"; grounding: TQP batches relational work into tensor
runtimes, Flare amortizes compilation across whole stages — here across
*queries*):

- **Shape buckets** (`buckets.py` + hooks in the copr engines): compiled
  programs are keyed on the query's SHAPE CLASS, not its literal shape
  or literal constants.  Row counts pad to next-power-of-two tile
  classes (masked rows), TopN budgets and probe key-sets pad to pow2,
  and predicate constants are HOISTED out of the program into runtime
  parameter vectors (`params.py`), so `l_shipdate <= '1998-09-02'` and
  `l_shipdate <= '1998-07-01'` run the SAME cached XLA program.
  Steady-state compile-cache hit rate becomes a function of query shape
  class.

- **Micro-batching** (`batcher.py`): identical-fingerprint point/agg
  statements arriving within a bounded window coalesce into ONE vmapped
  device dispatch over stacked parameter vectors; per-query results
  scatter back to each waiting connection.  Per-query QueryScope
  cancel/deadline is honored throughout — a killed member is masked
  out, never blocking the batch.

Config rides the sysvars `tidb_tpu_shape_buckets`,
`tidb_tpu_microbatch_window_ms` and `tidb_tpu_microbatch_max`; the
batcher and bucket policy are process-wide resources (like
max_connections), so a SET applies to the whole server.
"""

from __future__ import annotations

import threading
from typing import Dict

from .buckets import shape_bucket, topn_budget  # noqa: F401
from .params import hoist_conds  # noqa: F401
from ..util_concurrency import make_lock

#: sysvar names that feed the process-wide serving config
_SYSVARS = ("tidb_tpu_shape_buckets", "tidb_tpu_microbatch_window_ms",
            "tidb_tpu_microbatch_max")

_mu = make_lock("serving:_mu")
_CONFIG: Dict[str, float] = {
    # defaults mirror session/vars.py SYSVAR_DEFAULTS
    "shape_buckets": True,
    "microbatch_window_ms": 0.0,
    "microbatch_max": 32,
}


def config() -> Dict[str, float]:
    with _mu:
        return dict(_CONFIG)


def configure(**kw):
    """Override serving config directly (tests / embedders)."""
    with _mu:
        for k, v in kw.items():
            if k in _CONFIG:
                _CONFIG[k] = v


def refresh_from_vars(sess_vars):
    """Pull the serving sysvars out of a SessionVars overlay (called by
    SET; session values overlay globals, so the LAST writer wins — these
    knobs configure a process-wide resource)."""
    configure(
        shape_buckets=sess_vars.get_bool("tidb_tpu_shape_buckets"),
        microbatch_window_ms=float(
            sess_vars.get_int("tidb_tpu_microbatch_window_ms", 0)),
        microbatch_max=max(sess_vars.get_int("tidb_tpu_microbatch_max", 32),
                           1),
    )


def shape_buckets_enabled() -> bool:
    return bool(_CONFIG["shape_buckets"])


def microbatch_window_s() -> float:
    return float(_CONFIG["microbatch_window_ms"]) / 1000.0


#: adaptive-window shape: idle servers halve the configured window (a
#: lone statement should not sit out a pointless wait), pressure widens
#: it linearly with admission-queue depth (queued statements ARE the
#: batching opportunity) up to this cap
ADAPTIVE_MAX_FACTOR = 8.0
ADAPTIVE_IDLE_FACTOR = 0.5


def effective_window_s() -> float:
    """The ADAPTIVE micro-batch window: `tidb_tpu_microbatch_window_ms`
    scaled by live admission-queue pressure (the gauge the server's
    bounded admission maintains).  depth 0 → half the base window;
    each queued statement adds half a base window, capped at
    ADAPTIVE_MAX_FACTOR.  The effective value is published as the
    `serving_effective_window_ms` gauge on /metrics."""
    base = microbatch_window_s()
    if base <= 0.0:
        return 0.0
    from ..metrics import REGISTRY

    depth = REGISTRY.get("admission_queue_depth")
    factor = (ADAPTIVE_IDLE_FACTOR if depth <= 0
              else min(1.0 + depth / 2.0, ADAPTIVE_MAX_FACTOR))
    w = base * factor
    REGISTRY.set("serving_effective_window_ms", w * 1000.0)
    return w


def microbatch_max() -> int:
    return int(_CONFIG["microbatch_max"])


def try_run_microbatch(storage, req):
    """Distsql hook: serve `req` through the micro-batcher when eligible;
    None when ineligible/disabled or when the batch attempt failed benignly
    (the caller falls through to the mesh / fan-out rungs).  Lifecycle
    errors (kill/timeout/shutdown) propagate."""
    if microbatch_window_s() <= 0.0:
        return None
    from .batcher import try_run_batched

    return try_run_batched(storage, req)
