"""Query micro-batching: N identical-shape statements, one device dispatch.

The continuous-batching idea from inference serving applied to SQL: a
point/agg statement's device cost is dominated by per-dispatch overhead
(launch + readback round trips), not by the arithmetic, so N concurrent
clients issuing the same SHAPE of statement should cost ~one dispatch,
not N.  The batcher keys waiting statements by their hoisted-parameter
program fingerprint (serving/params.py) + table version + ranges; the
first arrival becomes the LEADER and holds a bounded window
(`tidb_tpu_microbatch_window_ms`, early-closed at
`tidb_tpu_microbatch_max` members) during which identical-fingerprint
arrivals join.  The leader then runs ONE vmapped per-tile program over
the stacked parameter vectors and scatters per-member results back.

Lifecycle contract: every member waits scope-interruptibly — a KILLed
or deadline-expired member raises immediately and is masked out of the
batch (its slot still computes; nobody reads it).  A batch-level
dispatch failure (chaos site `serving/batch_dispatch`) fails the batch
members back to the solo mesh/fan-out rungs, never corrupting results.

Eligibility is strict so batched results are bit-identical to solo
runs: single non-partitioned table, no MVCC delta in range, dense-mode
aggregation or bare filter, no joins/probes/projection/topn.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..errors import TiDBTPUError
from ..metrics import REGISTRY
from ..store.fault import FAILPOINTS
from ..util_concurrency import make_lock, witness_wait_check

log = logging.getLogger("tidb_tpu.serving")

#: host gather slice for batched filter results (mirrors distsql streaming)
STREAM_ROWS = 1 << 16

#: largest table (in tiles) the batcher will serve: the batched path runs
#: a per-tile dispatch loop, which amortizes beautifully for point/agg
#: shapes but must not pull huge analytic scans off the one-dispatch
#: mesh program (and it bounds the leader's dispatch-loop length, which
#: is the batch's cancellation granularity)
import os as _os  # noqa: E402

MAX_BATCH_TILES = int(_os.environ.get("TIDB_TPU_MICROBATCH_MAX_TILES", "64"))


class _Member:
    """One waiting statement's slot in a batch."""

    __slots__ = ("pi", "pf", "scope", "event", "result", "error",
                 "batch_size", "wait_ns", "limit")

    def __init__(self, pi: np.ndarray, pf: np.ndarray, scope,
                 limit: Optional[int] = None):
        self.pi = pi
        self.pf = pf
        self.scope = scope
        self.limit = limit
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.batch_size = 1
        self.wait_ns = 0


class _Group:
    """One key's open batch.  Its queue state (`members`, `closed`)
    belongs to the BATCHER's mutex, not a lock of its own — declared
    for lint.concur's cross-object guard rule.  `full` is the lock-free
    leader-wakeup Event: reads/waits on it never need the mutex."""

    __slots__ = ("members", "closed", "full")
    _guarded_by_ = "serving.batcher:MicroBatcher._mu"

    def __init__(self):
        self.members: List[_Member] = []
        self.closed = False
        self.full = threading.Event()


class MicroBatcher:
    """Per-fingerprint batching queues.  The leader (first arrival for a
    key) owns the window and the dispatch; followers park on their slot
    event with scope-interruptible waits."""

    def __init__(self):
        self._mu = make_lock("serving.batcher:MicroBatcher._mu")
        self._groups: Dict[tuple, _Group] = {}

    def submit(self, key: tuple, member: _Member, window_s: float,
               max_batch: int, runner):
        """Join (or open) the batch for `key`; returns the member's
        result or raises its error.  `runner(live_members)` is invoked
        once per batch by the leader and must fill each live member's
        `result`."""
        t0 = time.perf_counter_ns()
        with self._mu:
            g = self._groups.get(key)
            if g is not None and not g.closed \
                    and len(g.members) < max_batch:
                g.members.append(member)
                if len(g.members) >= max_batch:
                    g.full.set()
                leader = False
            else:
                g = _Group()
                g.members.append(member)
                self._groups[key] = g
                leader = True
        if not leader:
            return self._await(member, t0)
        # ---- leader: hold the window, then dispatch -------------------
        # the wait wakes on batch-full, the window deadline, OR the
        # leader's own cancel/deadline (a KILLed leader must not sit out
        # the window; it closes the group early and is masked below)
        wait_s = window_s
        rem = member.scope.remaining_s()
        if rem is not None:
            wait_s = min(wait_s, rem)
        deadline = time.monotonic() + max(wait_s, 0.0)
        while not g.full.is_set() and not member.scope.cancelled():
            left = deadline - time.monotonic()
            if left <= 0:
                break
            self._window_wait(g, min(left, 0.02))
        with self._mu:
            g.closed = True
            if self._groups.get(key) is g:
                del self._groups[key]
            members = list(g.members)
        # a cancelled member is masked out of the dispatch: it never
        # blocks the batch, and its own wait raises its scope error
        live = [m for m in members if not m.scope.cancelled()]
        now = time.perf_counter_ns()
        for m in members:
            m.batch_size = len(members)
            m.wait_ns = now - t0
        try:
            if live:
                REGISTRY.inc("serving_batches_total")
                REGISTRY.inc("serving_batched_stmts_total", len(live))
                REGISTRY.observe("serving_batch_size", len(live))
                runner(live)
        except BaseException as e:  # noqa: BLE001 — scattered to members
            REGISTRY.inc("serving_batch_errors_total")
            for m in live:
                if m.result is None and m.error is None:
                    m.error = e
        finally:
            for m in members:
                m.event.set()
        return self._await(member, t0)

    def _window_wait(self, g: "_Group", timeout_s: float):
        """The leader's batching-window park: the registry mutex (or any
        ranked lock) held here would stall every statement sharing the
        lock for a full window — the wait-witness trips instead."""
        witness_wait_check("MicroBatcher group.full.wait")
        g.full.wait(timeout_s)

    def _member_wait(self, member: "_Member") -> bool:
        """One poll tick of a parked member (scope-interruptible)."""
        witness_wait_check("MicroBatcher member.event.wait")
        return member.event.wait(0.02)

    def _await(self, member: _Member, t0: int):
        # scope-interruptible park: a killed/deadline member unblocks at
        # the next poll tick instead of waiting out the batch
        while not self._member_wait(member):
            if member.scope.cancelled():
                member.wait_ns = time.perf_counter_ns() - t0
                raise member.scope.error()
        member.scope.check()
        if member.error is not None:
            raise member.error
        return member.result


BATCHER = MicroBatcher()


def _batch_params(live: List[_Member], b_pad: int):
    """Stack per-member parameter vectors to [B_pad, P]; padded slots
    replicate member 0 (their outputs are computed and discarded — the
    pow2 pad keeps the vmapped program's jit signature per batch CLASS)."""
    rows_i = [m.pi for m in live] + [live[0].pi] * (b_pad - len(live))
    rows_f = [m.pf for m in live] + [live[0].pf] * (b_pad - len(live))
    return np.stack(rows_i), np.stack(rows_f)


def _get_vmapped(fp: str, an, kind: str, col_order):
    from ..copr import jax_engine as je
    import jax

    fn = _VMAPPED.get(fp)
    if fn is None:
        core = je._tile_core(an, kind, col_order, with_params=True)
        fn = jax.jit(jax.vmap(
            core, in_axes=(None, None, None, None, None, 0, 0)))
        _VMAPPED.put(fp, fn)
    return fn


from ..copr.cache import ProgramCache  # noqa: E402

_VMAPPED = ProgramCache("microbatch")


def _run_batch(ctx: dict, live: List[_Member]):
    """Leader-side batched execution: one vmapped device dispatch per
    tile over the stacked parameter vectors, per-member results
    scattered into each slot."""
    from . import shape_bucket
    from ..copr import jax_engine as je
    from ..trace import span

    table = ctx["table"]
    an = ctx["an"]
    kind = ctx["kind"]
    col_order = ctx["col_order"]
    B = len(live)
    b_pad = shape_bucket(B)
    PI, PF = _batch_params(live, b_pad)
    vfn = _get_vmapped(ctx["fp"], an, kind, col_order)
    tags = je._agg_tags(an.agg) if kind == "agg" else None
    accums: List[Optional[dict]] = [None] * B
    handles: List[List[np.ndarray]] = [[] for _ in range(B)]
    counts = [0] * B
    # per-member LIMITs: the batch key buckets the limit CLASS (pow2) so
    # `LIMIT 5` and `LIMIT 7` filters share a batch; each member's exact
    # limit applies to its own slot here and at result-slice time
    limits = [m.limit for m in live]
    TILE = je.TILE

    done = False
    for start, end in ctx["ranges"]:
        if done:
            break
        for tile_start in range((start // TILE) * TILE, end, TILE):
            t0 = max(tile_start, start)
            t1 = min(tile_start + TILE, end)
            if t0 >= t1:
                continue
            # host seam between dispatches: if EVERY member is dead the
            # batch aborts (each member raises its own scope error);
            # individual dead members just stop being waited on
            if all(m.scope.cancelled() for m in live):
                return
            tile_idx = tile_start // TILE
            datas, valids = [], []
            for ci in col_order:
                d, v = je.DEVICE_CACHE.get_tile(
                    table, an.scan.columns[ci], tile_idx, tile_start,
                    min(tile_start + TILE, table.base_rows))
                datas.append(d)
                valids.append(v)
            lo = np.int64(t0 - tile_start)
            hi = np.int64(t1 - tile_start)
            del_mask = je._all_true(None)  # batch eligibility => no deletes
            FAILPOINTS.hit("serving/batch_dispatch", size=B, tile=tile_idx)
            # membership guard (coordination follow-up (a)): a lost
            # member between mesh build and this vmapped dispatch raises
            # CoordEpochMismatch out of the batch — the runner's error
            # scatter fails every live member back to the SOLO rungs,
            # which rebuild from the new broadcast (parity-preserving)
            from ..copr.parallel import _check_membership_epoch

            _check_membership_epoch()
            # resource-group admission (ISSUE 17): the leader thread
            # carries its own statement scope, so the batch's device
            # time is charged to the LEADER's group — followers ride
            # free (matching TiDB, where the runaway/RU ledger bills
            # the session that issued the physical request)
            from ..copr.chunking import observe_chunk
            from ..lifecycle import chunk_admission

            bt0 = time.perf_counter()
            with span("copr.device.execute", batch=B, tile=tile_idx):
                with chunk_admission():
                    out = vfn(datas, valids, lo, hi, del_mask, PI, PF)
            observe_chunk("batch", (time.perf_counter() - bt0) * 1000.0,
                          int(t1 - t0))
            if kind == "agg":
                gcount, results = out
                with span("copr.readback") as rsp:
                    gh = je._np_tree(gcount)
                    rh = [je._np_tree(r) for r in results]
                    rsp.set(bytes=gh.nbytes)
                for b in range(B):
                    rb = [
                        (tag, tuple(x[b] for x in r)
                         if isinstance(r, tuple) else r[b])
                        for tag, r in zip(tags, rh)
                    ]
                    accums[b] = je._merge_device_agg(
                        accums[b], gh[b], rb, table, an, tile_start)
            else:  # filter (no projection by eligibility)
                m_out, _outs = out
                with span("copr.readback") as rsp:
                    mh = je._np_tree(m_out)
                    rsp.set(bytes=mh.nbytes)
                for b in range(B):
                    sel = np.flatnonzero(mh[b])
                    if limits[b] is not None:
                        sel = sel[: max(limits[b] - counts[b], 0)]
                    if len(sel):
                        handles[b].append(sel + tile_start)
                        counts[b] += len(sel)
                if all(lm is not None and c >= lm
                       for lm, c in zip(limits, counts)):
                    done = True
                    break

    for b, m in enumerate(live):
        if kind == "agg":
            if accums[b] is None:
                m.result = ("agg", [])
            else:
                m.result = ("agg",
                            [je._device_agg_to_chunk(accums[b], table, an)])
        else:
            hs = (np.concatenate(handles[b]) if handles[b]
                  else np.zeros(0, dtype=np.int64))
            m.result = ("filter", hs)


def try_run_batched(storage, req):
    """Serve `req` through the micro-batcher; None when ineligible or
    when the batch attempt failed benignly (callers fall through to the
    mesh / per-region rungs — re-running solo preserves parity).
    Lifecycle errors (kill/timeout/shutdown) propagate."""
    from . import effective_window_s, hoist_conds, microbatch_max
    from ..copr import jax_engine as je
    from ..copr.ir import DAG
    from ..copr.jax_eval import JaxUnsupported
    from ..lifecycle import current_scope
    from ..trace import span
    import jax

    dag = DAG.from_dict(req.dag)
    tid = dag.scan.table_id
    if not req.ranges or any(kr.table_id != tid for kr in req.ranges):
        return None  # partitioned fan-out: solo paths handle it
    if jax.process_count() > 1:
        return None
    try:
        table = storage.table(tid)
    except Exception:
        return None
    if table.base_rows == 0 or table.base_ts > req.ts:
        return None
    if (table.base_rows + je.TILE - 1) // je.TILE > MAX_BATCH_TILES:
        return None  # big analytic scans stay on the one-dispatch mesh
    try:
        an = je._Analyzed(dag, table)
    except JaxUnsupported:
        return None
    if an.probes or an.lookups or an.topn is not None:
        return None
    kind = "agg" if an.agg is not None else "filter"
    if kind == "agg" and an.agg_mode != "dense":
        return None
    if kind == "filter" and an.proj_exprs is not None:
        return None
    deleted, inserted = table.delta_overlay(req.ts, 0, 1 << 62)
    if deleted or inserted:
        # members read at different TSOs; only delta-free tables make
        # the base scan ts-independent (and thus batchable)
        return None
    col_order = an.needed_cols()
    hoisted = hoist_conds(an)
    pi, pf = hoisted if hoisted is not None else (
        np.zeros(0, dtype=np.int64), np.zeros(0, dtype=np.float64))
    # the DAG fingerprint serializes columns by SCAN-OUTPUT index + type
    # kind (fine for program identity: the program reads whatever arrays
    # it is fed) — but batch members SHARE the leader's loaded arrays,
    # so the batch key must also pin which STORE columns those indices
    # resolve to, or `where k = ?` and `where g = ?` would merge
    store_cols = tuple(an.scan.columns[ci] for ci in col_order)
    fp = (je._fingerprint(an, kind)
          + f"|cols={col_order}|store={store_cols}"
          + f"|mb|hp={len(pi)},{len(pf)}")
    ranges = tuple(
        (max(kr.start, 0), min(kr.end, table.base_rows))
        for kr in req.ranges
    )
    # LIMIT values hoist out of the batch key into per-member slots: the
    # key carries only the pow2 limit CLASS (serving follow-up (d)), so
    # parameter-different LIMITs share one batch and one vmapped program
    from . import shape_bucket as _bucket

    limit_class = None if an.limit is None else _bucket(an.limit, floor=16)
    key = (fp, table.store_uid, table.base_version, ranges, limit_class,
           je.TILE)
    member = _Member(pi, pf, current_scope(), limit=an.limit)
    ctx = {"table": table, "an": an, "kind": kind,
           "col_order": col_order, "fp": fp, "ranges": ranges}
    with span("serving.batch", kind=kind) as sp:
        try:
            res = BATCHER.submit(key, member, effective_window_s(),
                                 microbatch_max(),
                                 lambda live: _run_batch(ctx, live))
        except TiDBTPUError:
            raise  # kill / deadline / shutdown: the statement's own fate
        except BaseException as e:  # noqa: BLE001 — fall back to solo
            log.warning("micro-batch dispatch failed; falling back to "
                        "solo execution: %s", e)
            sp.set(batch=member.batch_size, outcome="error")
            return None
        finally:
            REGISTRY.observe("serving_batch_wait_ms", member.wait_ns / 1e6)
        sp.set(batch=member.batch_size,
               wait_ms=round(member.wait_ns / 1e6, 3))
    if res[0] == "agg":
        return [c for c in res[1] if c.num_rows > 0]
    hs = res[1]
    if an.limit is not None:
        hs = hs[: an.limit]
    chunks = []
    for off in range(0, len(hs), STREAM_ROWS):
        c = table.gather_chunk(list(an.scan.columns),
                               hs[off: off + STREAM_ROWS])
        if c.num_rows:
            chunks.append(c)
    return chunks
