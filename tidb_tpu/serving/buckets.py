"""Shape-class bucketing policy.

One rule everywhere: sizes pad UP to the next power of two (with a small
floor), and padded slots are masked — never read as data.  A compiled
XLA program is specialized on its operand shapes, so bucketing makes the
program cache key a function of the size CLASS rather than the literal
size: a table growing 33 -> 50 tiles, a TopN limit changing 5 -> 7, or a
micro-batch filling 3 of 4 slots all reuse the same compiled program.
"""

from __future__ import annotations


def shape_bucket(n: int, floor: int = 1) -> int:
    """Next power of two >= max(n, floor)."""
    n = max(int(n), int(floor), 1)
    return 1 << (n - 1).bit_length()


def topn_budget(limit: int) -> int:
    """Device TopN budget for a LIMIT: pow2-bucketed with a floor of 16
    so nearby limits share one compiled kernel (the exact limit is
    re-applied host-side by the final merge)."""
    from . import shape_buckets_enabled

    if not shape_buckets_enabled():
        return max(int(limit), 1)
    return shape_bucket(limit, floor=16)
