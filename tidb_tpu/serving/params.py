"""Predicate-constant hoisting: literal -> runtime parameter slot.

Compiled device programs bake `Constant` leaves in as XLA literals, so
`WHERE k = 5` and `WHERE k = 7` compile two programs even though they
are the same query SHAPE.  Hoisting rewrites comparison constants into
`ParamConst` slots that read from a runtime parameter vector instead:
the program fingerprint serializes the SLOT (not the value), parameter-
different queries share one cached program, and the micro-batcher can
vmap that program over a stack of per-query parameter vectors.

Scope is deliberately narrow: only constants that are direct operands
of comparison predicates (=, !=, <, <=, >, >=, IN) hoist — those are
what vary between parameterized point/agg statements.  Structural
constants (arithmetic like `1 - l_discount`, ROUND digits, CASE arms)
stay baked: they define the query shape itself.

This module is host-only (no jax): hoisting happens after the dict
rewrite, before fingerprint/compile, and the host CPU engine still
evaluates `ParamConst` by its retained literal value.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from ..expr.expression import Constant, Expression, ScalarFunc
from ..types import TypeKind
from ..types.values import parse_date, parse_datetime

#: predicate heads whose constant operands hoist into parameter slots
_CMP_OPS = frozenset({"=", "!=", "<", "<=", ">", ">=", "in"})


@dataclass
class ParamConst(Constant):
    """A hoisted constant: serializes as its slot for fingerprinting and
    compiles as a read from the runtime parameter vector, but keeps its
    literal `value` so host-side evaluation is unchanged."""

    #: ("i" | "f", index) — which parameter vector, and where in it
    param_slot: Optional[tuple] = None


def _numeric_value(c: Constant):
    """The hoistable numeric payload of a constant, or None.

    DATE/DATETIME string literals pre-parse here (the device `_const`
    path parses them at trace time — a hoisted slot must carry the
    already-parsed int).  Anything non-numeric (raw strings that the
    dict rewrite did not code, wide decimals, JSON) stays baked."""
    v = c.value
    if v is None or c.ftype is None:
        return None
    k = c.ftype.kind
    if k == TypeKind.JSON or (k == TypeKind.DECIMAL
                              and getattr(c.ftype, "is_wide_decimal", False)):
        return None
    if isinstance(v, str):
        try:
            if k == TypeKind.DATE:
                return int(parse_date(v))
            if k == TypeKind.DATETIME:
                return int(parse_datetime(v))
        except (ValueError, TypeError):
            return None
        return None  # raw string constant (dict rewrite handles or rejects)
    if isinstance(v, bool):
        return int(v)
    if isinstance(v, (int, np.integer)):
        return int(v)
    if isinstance(v, (float, np.floating)):
        return float(v) if k == TypeKind.FLOAT else None
    return None


def _hoist_leaf(e: Expression, i64: List[int], f64: List[float]):
    """ParamConst for a hoistable constant operand, else None."""
    if not isinstance(e, Constant) or isinstance(e, ParamConst):
        return None
    v = _numeric_value(e)
    if v is None:
        return None
    if e.ftype.kind == TypeKind.FLOAT:
        f64.append(float(v))
        slot = ("f", len(f64) - 1)
    else:
        i64.append(int(v))
        slot = ("i", len(i64) - 1)
    return ParamConst(e.value, e.ftype, param_slot=slot)


def _walk(e: Expression, i64: List[int], f64: List[float]) -> Expression:
    if not isinstance(e, ScalarFunc):
        return e
    if e.name == "in":
        # IN-lists bucket by pow2 LENGTH: when every list element hoists,
        # the list pads to the next power of two with repeats of the last
        # element (x IN (5, 5) ≡ x IN (5)), so `k IN (1,2,3)` and
        # `k IN (7,8,9,10)` compile ONE program with 4 parameter slots —
        # IN-lists of nearby length share a fused fragment
        items = e.args[1:]
        if items and all(
                isinstance(a, Constant) and not isinstance(a, ParamConst)
                and _numeric_value(a) is not None for a in items):
            from .buckets import shape_bucket

            pad = shape_bucket(len(items))
            padded = list(items) + [items[-1]] * (pad - len(items))
            new_args = [_walk(e.args[0], i64, f64)]
            for a in padded:
                new_args.append(_hoist_leaf(a, i64, f64))
            return ScalarFunc("in", new_args, e.ftype, e.meta)
    if e.name in _CMP_OPS:
        new_args = []
        for a in e.args:
            hoisted = _hoist_leaf(a, i64, f64)
            new_args.append(hoisted if hoisted is not None
                            else _walk(a, i64, f64))
        return ScalarFunc(e.name, new_args, e.ftype, e.meta)
    return ScalarFunc(e.name, [_walk(a, i64, f64) for a in e.args],
                      e.ftype, e.meta)


def hoist_conds(an) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Hoist comparison constants out of `an.conds` in place.

    Returns (i64_params, f64_params) when anything hoisted (an.conds now
    carries ParamConst slots), else None (an untouched).  Gated on the
    shape-bucket sysvar so disabling buckets restores literal-baked
    programs exactly."""
    from . import shape_buckets_enabled

    if not shape_buckets_enabled() or not getattr(an, "conds", None):
        return None
    i64: List[int] = []
    f64: List[float] = []
    new_conds = [_walk(c, i64, f64) for c in an.conds]
    if not i64 and not f64:
        return None
    an.conds = new_conds
    return (np.array(i64, dtype=np.int64), np.array(f64, dtype=np.float64))
