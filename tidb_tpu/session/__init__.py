from .domain import Domain
from .session import ResultSet, Session
from .vars import SessionVars

__all__ = ["Domain", "Session", "ResultSet", "SessionVars"]
