"""SQL plan management (bindinfo-lite).

Reference: bindinfo/handle.go:122 (the bind-record cache consulted before
planning), :545 (capture), bindinfo/session_handle.go (SESSION scope).
Grammar matches the reference:

    CREATE [GLOBAL | SESSION] BINDING FOR <stmt> USING <hinted stmt>
    DROP   [GLOBAL | SESSION] BINDING FOR <stmt>
    SHOW   [GLOBAL | SESSION] BINDINGS

Bindings key on the normalized digest of the original statement; when a
statement's digest matches, the HINTED statement's AST is planned instead
and its /*+ ... */ hints override the optimizer knobs for that plan only
(the planner consults them through Session._pctx).  Supported hints:
MERGE_JOIN, HASH_JOIN, INL_JOIN / INDEX_JOIN, INL_HASH_JOIN,
NO_INDEX_JOIN.  Global bindings live on the Domain, session bindings on
the Session; SESSION shadows GLOBAL (bindinfo/session_handle.go order).
"""

from __future__ import annotations

import re
from typing import Optional, Tuple

from ..errors import PlanError
from ..parser import parse
from .domain import sql_digest

_BINDING_RE = re.compile(
    r"^\s*(create|drop)\s+(?:(global|session)\s+)?binding\s+for\s",
    re.I | re.S)
_SHOW_RE = re.compile(
    r"^\s*show\s+(?:(global|session)\s+)?bindings\s*;?\s*$", re.I)
_HINT_RE = re.compile(r"/\*\+(.*?)\*/", re.S)


def is_binding_stmt(sql: str) -> bool:
    return bool(_BINDING_RE.match(sql) or _SHOW_RE.match(sql))


def extract_hints(sql: str) -> frozenset:
    names = set()
    for body in _HINT_RE.findall(sql):
        for tok in re.split(r"[\s,()]+", body):
            if tok:
                names.add(tok.lower())
    return frozenset(names)


def _split_for_using(tail: str) -> Tuple[str, str]:
    """'<orig> USING <hinted>' -> (orig, hinted): the splitting USING is at
    paren-depth 0, outside quotes, followed by a statement keyword (so JOIN
    ... USING (cols) never matches)."""
    low = tail.lower()
    depth = 0
    i, n = 0, len(tail)
    while i < n:
        c = tail[i]
        if c in "'\"":
            q = c
            i += 1
            while i < n and tail[i] != q:
                i += 2 if tail[i] == "\\" else 1
            i += 1
            continue
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
        elif depth == 0 and low.startswith("using", i) and \
                (i == 0 or not low[i - 1].isalnum()) and \
                (i + 5 >= n or not low[i + 5].isalnum()):
            rest = low[i + 5:].lstrip()
            if re.match(r"(/\*|select|insert|update|delete)\b", rest) or \
                    rest.startswith("/*"):
                return tail[:i].strip(), tail[i + 5:].strip()
        i += 1
    raise PlanError("CREATE BINDING requires USING <hinted statement>")


def _store(session, is_global: bool) -> dict:
    if is_global:
        if not hasattr(session.domain, "bindings"):
            session.domain.bindings = {}
        return session.domain.bindings
    if not hasattr(session, "_bindings"):
        session._bindings = {}
    return session._bindings


def _bump(session, is_global: bool):
    if is_global:
        session.domain.bindings_version = getattr(
            session.domain, "bindings_version", 0) + 1
    else:
        session._bindings_version = getattr(
            session, "_bindings_version", 0) + 1


def handle(session, sql: str):
    from .session import ResultSet

    m = _SHOW_RE.match(sql)
    if m:
        scope = (m.group(1) or "session").lower()
        rows = []
        for scope_name, store in (("session", _store(session, False)),
                                  ("global", _store(session, True))):
            if scope in (scope_name,) or m.group(1) is None:
                for digest, b in sorted(store.items()):
                    rows.append((b["original"], b["hinted"], scope_name))
        return ResultSet(["Original_sql", "Bind_sql", "Scope"], rows,
                         is_query=True)
    m = _BINDING_RE.match(sql)
    verb = m.group(1).lower()
    is_global = (m.group(2) or "session").lower() == "global"
    # binding DDL short-circuits the normal statement path (execute()
    # dispatches here before parsing), so the privilege and snapshot
    # guards must run here (ADVICE r4 #3):
    # - it is a write: reject under SET tidb_snapshot
    # - GLOBAL bindings rewrite every session's plans: SUPER required
    #   (TiDB gates global bind DDL the same way)
    if session._snapshot_ts is not None:
        from ..errors import ExecutorError

        raise ExecutorError(
            "can not execute write statement when 'tidb_snapshot' is set")
    if is_global:
        session.domain.priv.require(
            session.user, "super",
            roles=tuple(getattr(session, "active_roles", ())))
    tail = sql[m.end():].strip().rstrip(";")
    if verb == "create":
        orig, hinted = _split_for_using(tail)
        # both sides must parse, and they must normalize to the SAME
        # digest (bindinfo/handle.go CreateBindRecord validation): the
        # binding carries HINTS for the user's statement — it never
        # substitutes the stored literals for the incoming ones
        parse(orig)
        clean = re.sub(r"/\*.*?\*/", " ", hinted, flags=re.S)
        parse(clean)
        if sql_digest(orig) != sql_digest(clean):
            raise PlanError(
                "CREATE BINDING: the hinted statement must match the "
                "original (same normalized digest)")
        store = _store(session, is_global)
        store[sql_digest(orig)] = {
            "original": orig,
            "hinted": hinted,
            "hints": extract_hints(hinted),
        }
        _bump(session, is_global)
        return ResultSet()
    # DROP
    digest = sql_digest(tail)
    store = _store(session, is_global)
    if store.pop(digest, None) is not None:
        if is_global:
            # a dropped captured binding must be RE-capturable: forget the
            # sighting count so two fresh sightings trigger capture again
            getattr(session.domain, "_capture_seen", {}).pop(digest, None)
        _bump(session, is_global)
    return ResultSet()


def apply_binding(session, stmt) -> Tuple[object, Optional[frozenset]]:
    """Attach a matched binding's HINTS to the user's statement
    (handle.go:122 — the match runs on the normalized digest before
    planning).  The incoming statement is NEVER swapped for the stored
    text: literals differ between digest-equal statements, and executing
    the stored literals would return another query's answer."""
    sql = getattr(stmt, "_sql_text", None)
    if sql is None:
        return stmt, None
    # EXPLAIN wraps the statement: bindings match the inner text
    probe = re.sub(r"^\s*(explain|trace)\s+(analyze\s+)?", "", sql,
                   flags=re.I)
    digest = sql_digest(probe)
    b = _store(session, False).get(digest) or \
        _store(session, True).get(digest)
    if b is None:
        return stmt, None
    from ..metrics import REGISTRY

    REGISTRY.inc("binding_hits_total")
    return stmt, b["hints"]


# ---------------------------------------------------------------------------
# baseline capture (bindinfo/handle.go:545 CaptureBaselines role)
# ---------------------------------------------------------------------------


def _plan_hints(phys) -> frozenset:
    """Derive optimizer hints that pin the CURRENT plan's join choices
    (what the reference encodes as bind SQL hint comments)."""
    hints = set()

    def walk(p):
        nm = type(p).__name__
        if nm == "PhysMergeJoin":
            hints.add("merge_join")
        elif nm == "PhysIndexJoin":
            hints.add("inl_join")
        elif nm in ("PhysHashJoin", "PhysDeviceJoinReader"):
            # the device broadcast join IS the hash join relocated into
            # the cop task; HASH_JOIN re-plans to the same family
            hints.add("hash_join")
        for c in getattr(p, "children", []):
            walk(c)
        for attr in ("reader", "build_plan"):
            r = getattr(p, attr, None)
            if r is not None:
                walk(r)

    walk(phys)
    return frozenset(hints)


def maybe_capture(session, sql: str, stmt, phys) -> None:
    """When tidb_capture_plan_baselines is on, a SELECT digest seen for
    the SECOND time captures a GLOBAL binding that pins its current plan
    (handle.go:545 — capture runs off stmt-summary frequency >= 2).

    Guards mirror explicit CREATE GLOBAL BINDING (handle()): capture
    publishes into every session's plans, so only SUPER sessions
    capture, never under tidb_snapshot, and never from a plan that a
    SESSION binding shaped (a private experiment must not go global)."""
    try:
        if not session.vars.get_bool("tidb_capture_plan_baselines"):
            return
    except Exception:
        return
    from ..parser import ast

    if not isinstance(stmt, (ast.SelectStmt,)):
        return
    if session._snapshot_ts is not None:
        return
    if not session.domain.priv.check(
            session.user, "super",
            roles=tuple(getattr(session, "active_roles", ()))):
        return
    digest = sql_digest(sql)
    if digest in _store(session, False):
        return  # session-binding-shaped plan: don't promote it globally
    dom = session.domain
    seen = getattr(dom, "_capture_seen", None)
    if seen is None:
        seen = dom._capture_seen = {}
    if len(seen) >= 4096 and digest not in seen:
        seen.clear()  # bounded, like the stmt-summary cap
    n = seen.get(digest, 0) + 1
    seen[digest] = n
    # capture exactly on the second sighting; DROP BINDING resets the
    # counter (handle() pops _capture_seen), so a dropped captured
    # binding is recapturable by two fresh sightings without paying the
    # hint walk on every later execution
    if n != 2:
        return
    store = _store(session, True)
    if digest in store:
        return  # explicit binding wins
    hints = _plan_hints(phys)
    if not hints:
        return  # nothing plan-shaping to pin: a binding would be noise
    hint_txt = "/*+ " + ", ".join(sorted(h.upper() for h in hints)) + " */ "
    m = re.match(r"\s*select\b", sql, re.I)
    if m is None:
        return
    hinted = sql[:m.end() - 6] + "select " + hint_txt + sql[m.end():]
    store[digest] = {
        "original": sql,
        "hinted": hinted,
        "hints": hints,
        "captured": True,
    }
    _bump(session, True)
