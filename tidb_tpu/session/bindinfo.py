"""SQL plan management (bindinfo-lite).

Reference: bindinfo/handle.go:122 (the bind-record cache consulted before
planning), :545 (capture), bindinfo/session_handle.go (SESSION scope).
Grammar matches the reference:

    CREATE [GLOBAL | SESSION] BINDING FOR <stmt> USING <hinted stmt>
    DROP   [GLOBAL | SESSION] BINDING FOR <stmt>
    SHOW   [GLOBAL | SESSION] BINDINGS

Bindings key on the normalized digest of the original statement; when a
statement's digest matches, the HINTED statement's AST is planned instead
and its /*+ ... */ hints override the optimizer knobs for that plan only
(the planner consults them through Session._pctx).  Supported hints:
MERGE_JOIN, HASH_JOIN, INL_JOIN / INDEX_JOIN, INL_HASH_JOIN,
NO_INDEX_JOIN.  Global bindings live on the Domain, session bindings on
the Session; SESSION shadows GLOBAL (bindinfo/session_handle.go order).
"""

from __future__ import annotations

import re
from typing import Optional, Tuple

from ..errors import PlanError
from ..parser import parse
from .domain import sql_digest

_BINDING_RE = re.compile(
    r"^\s*(create|drop)\s+(?:(global|session)\s+)?binding\s+for\s",
    re.I | re.S)
_SHOW_RE = re.compile(
    r"^\s*show\s+(?:(global|session)\s+)?bindings\s*;?\s*$", re.I)
_HINT_RE = re.compile(r"/\*\+(.*?)\*/", re.S)


def is_binding_stmt(sql: str) -> bool:
    return bool(_BINDING_RE.match(sql) or _SHOW_RE.match(sql))


def extract_hints(sql: str) -> frozenset:
    names = set()
    for body in _HINT_RE.findall(sql):
        for tok in re.split(r"[\s,()]+", body):
            if tok:
                names.add(tok.lower())
    return frozenset(names)


def _split_for_using(tail: str) -> Tuple[str, str]:
    """'<orig> USING <hinted>' -> (orig, hinted): the splitting USING is at
    paren-depth 0, outside quotes, followed by a statement keyword (so JOIN
    ... USING (cols) never matches)."""
    low = tail.lower()
    depth = 0
    i, n = 0, len(tail)
    while i < n:
        c = tail[i]
        if c in "'\"":
            q = c
            i += 1
            while i < n and tail[i] != q:
                i += 2 if tail[i] == "\\" else 1
            i += 1
            continue
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
        elif depth == 0 and low.startswith("using", i) and \
                (i == 0 or not low[i - 1].isalnum()) and \
                (i + 5 >= n or not low[i + 5].isalnum()):
            rest = low[i + 5:].lstrip()
            if re.match(r"(/\*|select|insert|update|delete)\b", rest) or \
                    rest.startswith("/*"):
                return tail[:i].strip(), tail[i + 5:].strip()
        i += 1
    raise PlanError("CREATE BINDING requires USING <hinted statement>")


def _store(session, is_global: bool) -> dict:
    if is_global:
        if not hasattr(session.domain, "bindings"):
            session.domain.bindings = {}
        return session.domain.bindings
    if not hasattr(session, "_bindings"):
        session._bindings = {}
    return session._bindings


def _bump(session, is_global: bool):
    if is_global:
        session.domain.bindings_version = getattr(
            session.domain, "bindings_version", 0) + 1
    else:
        session._bindings_version = getattr(
            session, "_bindings_version", 0) + 1


def handle(session, sql: str):
    from .session import ResultSet

    m = _SHOW_RE.match(sql)
    if m:
        scope = (m.group(1) or "session").lower()
        rows = []
        for scope_name, store in (("session", _store(session, False)),
                                  ("global", _store(session, True))):
            if scope in (scope_name,) or m.group(1) is None:
                for digest, b in sorted(store.items()):
                    rows.append((b["original"], b["hinted"], scope_name))
        return ResultSet(["Original_sql", "Bind_sql", "Scope"], rows,
                         is_query=True)
    m = _BINDING_RE.match(sql)
    verb = m.group(1).lower()
    is_global = (m.group(2) or "session").lower() == "global"
    # binding DDL short-circuits the normal statement path (execute()
    # dispatches here before parsing), so the privilege and snapshot
    # guards must run here (ADVICE r4 #3):
    # - it is a write: reject under SET tidb_snapshot
    # - GLOBAL bindings rewrite every session's plans: SUPER required
    #   (TiDB gates global bind DDL the same way)
    if session._snapshot_ts is not None:
        from ..errors import ExecutorError

        raise ExecutorError(
            "can not execute write statement when 'tidb_snapshot' is set")
    if is_global:
        session.domain.priv.require(session.user, "super")
    tail = sql[m.end():].strip().rstrip(";")
    if verb == "create":
        orig, hinted = _split_for_using(tail)
        # both sides must parse; the hinted side is what gets planned
        parse(orig)
        parse(re.sub(r"/\*.*?\*/", " ", hinted, flags=re.S))
        store = _store(session, is_global)
        store[sql_digest(orig)] = {
            "original": orig,
            "hinted": hinted,
            "hints": extract_hints(hinted),
        }
        _bump(session, is_global)
        return ResultSet()
    # DROP
    digest = sql_digest(tail)
    store = _store(session, is_global)
    if store.pop(digest, None) is not None:
        _bump(session, is_global)
    return ResultSet()


def apply_binding(session, stmt) -> Tuple[object, Optional[frozenset]]:
    """Swap a statement for its bound hinted form (handle.go:122 — the
    match runs on the normalized digest before planning)."""
    sql = getattr(stmt, "_sql_text", None)
    if sql is None:
        return stmt, None
    # EXPLAIN wraps the statement: bindings match the inner text
    probe = re.sub(r"^\s*(explain|trace)\s+(analyze\s+)?", "", sql,
                   flags=re.I)
    digest = sql_digest(probe)
    b = _store(session, False).get(digest) or \
        _store(session, True).get(digest)
    if b is None:
        return stmt, None
    from ..metrics import REGISTRY

    REGISTRY.inc("binding_hits_total")
    clean = re.sub(r"/\*.*?\*/", " ", b["hinted"], flags=re.S)
    bound = parse(clean)[0]
    bound._sql_text = sql  # cache key stays on the original text
    # EXPLAIN/TRACE plan the target, not the wrapper
    target = getattr(stmt, "target", None)
    if target is not None and not isinstance(bound, type(stmt)):
        stmt.target = bound
        return stmt, b["hints"]
    return bound, b["hints"]
