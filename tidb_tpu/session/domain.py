"""Domain: the per-process singleton owning storage, catalog and globals.

Reference: domain/domain.go:60 — Domain owns the infoschema cache, DDL,
stats handle, sysvar cache, background loops.  In-process here: the catalog
IS the schema authority (no lease/reload loop needed), globals are a dict.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional

from ..catalog import Catalog
from ..statistics import StatsHandle
from ..store.storage import BlockStorage
from .vars import SessionVars


import re as _re
from ..util_concurrency import make_rlock

_NUM_RE = _re.compile(r"\b\d+(?:\.\d+)?\b")
_STR_RE = _re.compile(r"'(?:[^'\\]|\\.)*'|\"(?:[^\"\\]|\\.)*\"")
_WS_RE = _re.compile(r"\s+")
_OP_RE = _re.compile(r"\s*(<=|>=|<>|!=|=|<|>)\s*")
_IN_RE = _re.compile(r"in\s*\((?:\s*\?\s*,?)+\)")


def sql_digest(sql: str) -> str:
    """Normalized statement text: literals -> ?, IN lists collapsed,
    whitespace folded, lowercased (parser.Normalize + DigestHash role)."""
    s = _STR_RE.sub("?", sql)
    s = _NUM_RE.sub("?", s)
    s = _OP_RE.sub(r" \1 ", s)
    s = _WS_RE.sub(" ", s).strip().lower()
    s = _IN_RE.sub("in (...)", s)
    return s[:512]


class Domain:
    def __init__(self, storage: Optional[BlockStorage] = None,
                 data_dir: Optional[str] = None):
        if storage is not None and data_dir is not None:
            # an injected storage has no persisters attached — accepting
            # data_dir here would persist the catalog but silently lose
            # table data on restart
            raise ValueError(
                "pass data_dir to BlockStorage(...) when injecting storage"
            )
        self.data_dir = data_dir
        self.storage = storage or BlockStorage(data_dir=data_dir)
        self.catalog = Catalog(self.storage)
        self.stats = StatsHandle(self.storage)
        from .priv import PrivManager

        self.priv = PrivManager(data_dir)
        self.catalog.on_table_dropped = self.stats.drop
        # per-domain resource-control plane (ISSUE 17): named groups
        # with device-time token buckets; statements resolve their
        # group at scope-creation time (session.execute)
        from ..lifecycle import ResourceGroupRegistry

        self.resgroups = ResourceGroupRegistry()
        self.global_vars: Dict[str, str] = {}
        self._mu = make_rlock("session.domain:Domain._mu")
        # ring buffer of recent log records -> information_schema.
        # cluster_log (executor/cluster_reader.go memtable role); ONE
        # process-wide handler — re-pointed at the newest Domain's ring so
        # discarded domains don't accumulate handlers or leak deques
        import collections

        self.log_ring = collections.deque(maxlen=512)
        _attach_log_ring(self.log_ring)
        self._conn_counter = 0
        self.sessions: Dict[int, object] = {}  # conn_id -> Session (weak-ish)
        self.digest_summary = {}  # digest -> per-statement-shape aggregates
        # LOCK TABLES registry: (db, table) -> {"mode": read|write,
        # "owners": {conn_id}} — read locks shard across sessions, write
        # locks have one owner (reference: ddl/table_lock.go role)
        self.table_locks: Dict[tuple, dict] = {}
        # structured slow-query log (trace/slowlog.py): file-backed when
        # the domain persists, memory-ring otherwise; feeds
        # INFORMATION_SCHEMA.SLOW_QUERY with per-phase columns
        from ..trace import SlowQueryLog

        slow_path = None
        if data_dir:
            import os as _os

            _os.makedirs(data_dir, exist_ok=True)
            slow_path = _os.path.join(data_dir, "slow_query.log")
        self.slow_log = SlowQueryLog(
            slow_path, max_bytes=self._slow_log_max_bytes())
        # continuous profiler (ISSUE 13): every finished trace folds
        # into the rotating flame windows; chains onto the trace export
        # hook (never replacing a coord plane's forwarder), idempotent
        from ..trace import install_profiler

        install_profiler()
        if data_dir:
            self._recover(data_dir)
        self._bootstrap()
        from .maintenance import MaintenanceWorker

        self.maintenance = MaintenanceWorker(self)
        self.maintenance.start()

    def _recover(self, data_dir: str):
        """Reload catalog + table data persisted by a previous process
        (SURVEY.md §3.4: recovery = reload; no local checkpoints beyond
        the store itself)."""
        import os

        os.makedirs(data_dir, exist_ok=True)
        meta = os.path.join(data_dir, "catalog.json")
        if os.path.exists(meta):
            with open(meta) as f:
                self.catalog.load_json(f.read())
            self.storage.load_persisted()
            resume_jobs = True
        else:
            resume_jobs = False

        def persist(catalog):
            tmp = meta + ".tmp"
            with open(tmp, "w") as f:
                f.write(catalog.to_json())
            os.replace(tmp, meta)

        self.catalog.on_ddl = persist
        if resume_jobs:
            # finish DDL jobs a dead process left mid-ladder (owner resume,
            # ddl_worker.go:362): backfills continue from their checkpoint
            self.catalog.resume_pending_jobs()
        self._purge_orphan_files(data_dir)

    def _purge_orphan_files(self, data_dir: str):
        """Remove table files no catalog entry references: the recycle
        bin (RECOVER TABLE flashback) is process-lifetime, so a restart
        within the GC window would otherwise leak dropped tables' files
        on disk forever."""
        import os
        import re

        tdir = os.path.join(data_dir, "tables")
        if not os.path.isdir(tdir):
            return
        live: set = set()
        isc = self.catalog.info_schema()
        for db in isc.schema_names():
            for t in isc.tables(db):
                live.update(t.physical_ids())
        for fn in os.listdir(tdir):
            m = re.match(r"t(\d+)\.(base\.npz|delta\.log)$", fn)
            if m and int(m.group(1)) not in live:
                try:
                    os.remove(os.path.join(tdir, fn))
                except OSError:
                    pass

    def _bootstrap(self):
        """Create system schemas (session/bootstrap.go analog)."""
        for db in ("test", "mysql", "information_schema"):
            if not self.catalog.info_schema().has_schema(db):
                self.catalog.create_database(db, if_not_exists=True)

    def new_session(self):
        from .session import Session

        with self._mu:
            self._conn_counter += 1
            s = Session(self, conn_id=self._conn_counter)
            self.sessions[self._conn_counter] = s
            return s

    def kill(self, conn_id: int, query_only: bool = True):
        s = self.sessions.get(conn_id)
        if s is not None:
            s.kill(query_only)

    def maybe_auto_analyze(self, table_ids):
        """Post-DML auto-analyze check (update.go:621-639 analog, run inline
        instead of on a background ticker).  A touched partition refreshes
        the whole partitioned table so the merged logical-id row count the
        planner reads stays current."""
        isc = self.catalog.info_schema()
        done = set()
        for tid in table_ids:
            try:
                if not self.stats.need_auto_analyze(tid):
                    continue
                owner = isc.table_by_id(tid)
                if owner is not None and owner.id not in done:
                    # schema-aware analyze keeps index NDV stats fresh
                    # (a bare analyze_table would silently drop them)
                    done.add(owner.id)
                    self.stats.analyze(owner)
                else:
                    self.stats.analyze_table(tid)
            except Exception:
                pass  # stats are advisory; never fail the statement

    def _slow_log_max_bytes(self) -> int:
        from .vars import SYSVAR_DEFAULTS

        try:
            return int(self.global_vars.get(
                "tidb_tpu_slow_log_max_bytes",
                SYSVAR_DEFAULTS["tidb_tpu_slow_log_max_bytes"][0]))
        except (TypeError, ValueError):
            return 0

    def _digest_row(self, digest: str, sql: str) -> dict:
        """Get-or-create one statement summary row; caller holds _mu.
        Bounded like the reference's stmtsummary cap."""
        st = self.digest_summary.get(digest)
        if st is None:
            if len(self.digest_summary) >= 5000:
                self.digest_summary.clear()
            st = self.digest_summary[digest] = {
                "count": 0, "sum_latency": 0.0, "max_latency": 0.0,
                "sum_rows": 0, "sample": sql[:256],
            }
        return st

    def record_stmt(self, sql: str, dur_s: float, rows: int):
        from ..metrics import REGISTRY

        REGISTRY.inc("statements_total")
        REGISTRY.observe("statement_duration_seconds", dur_s)
        digest = sql_digest(sql)
        with self._mu:
            # per-digest aggregates (util/stmtsummary/statement_summary.go
            # :59,:213 — keyed on the normalized statement)
            st = self._digest_row(digest, sql)
            st["count"] += 1
            st["sum_latency"] += dur_s
            st["max_latency"] = max(st["max_latency"], dur_s)
            st["sum_rows"] += rows

    def record_termination(self, sql: str, term: str):
        """Per-digest abnormal-ending counts for the statement summary
        (expensivequery.go's kill accounting, folded into stmtsummary).
        'ok'/'error' endings are the count/latency aggregates' job; only
        lifecycle terminations are tallied here."""
        if term in ("ok", "error"):
            return
        digest = sql_digest(sql)
        with self._mu:
            # terminated statements may never reach record_stmt: get-or-
            # create the digest row so the termination is not invisible
            st = self._digest_row(digest, sql)
            tm = st.setdefault("terminations", {})
            tm[term] = tm.get(term, 0) + 1

    def record_trace(self, tr, totals: dict, dur_ms: float, slow: bool):
        """Fold a finished QueryTrace into the per-digest statement
        summary (phase aggregates from the span tree — the one
        execution-stats path) and, when it crossed the threshold, build
        the structured slow-log entry with per-phase columns."""
        digest = sql_digest(tr.sql)
        with self._mu:
            st = self.digest_summary.get(digest)
            if st is not None:
                ph = st.setdefault("phases", {
                    "compile_ms": 0.0, "device_ms": 0.0,
                    "transfer_bytes": 0, "readback_ms": 0.0,
                    "backoff_ms": 0.0})
                ph["compile_ms"] += totals["compile_ms"]
                ph["device_ms"] += totals["device_ms"]
                ph["transfer_bytes"] += totals["transfer_bytes"]
                ph["readback_ms"] += totals["readback_ms"]
                ph["backoff_ms"] += totals["backoff_ms"]
        if not slow:
            return
        import time as _time

        entry = {
            "time": _time.strftime("%Y-%m-%d %H:%M:%S",
                                   _time.localtime(tr.start_time)),
            "conn_id": tr.conn_id,
            "query": tr.sql[:512],
            "query_time": round(dur_ms / 1000.0, 6),
            "parse_ms": round(totals["parse_ms"], 3),
            "plan_ms": round(totals["plan_ms"], 3),
            "compile_ms": round(totals["compile_ms"], 3),
            "compile_hits": totals["compile_hits"],
            "compile_misses": totals["compile_misses"],
            "transfer_bytes": totals["transfer_bytes"],
            "device_ms": round(totals["device_ms"], 3),
            "readback_ms": round(totals["readback_ms"], 3),
            "readback_bytes": totals["readback_bytes"],
            "backoff_ms": round(totals["backoff_ms"], 3),
            "backfill_ms": round(totals.get("backfill_ms", 0.0), 3),
            "cop_tasks": totals["cop_tasks"],
            "engines": totals["engines"],
            "devices": totals["devices"],
            "rows": totals.get("result_rows", 0),
            "termination": (tr.root.attrs or {}).get("termination", "ok"),
        }
        # the rotation cap is a GLOBAL sysvar; refresh it on the write
        # path so SET GLOBAL takes effect without a restart
        self.slow_log.max_bytes = self._slow_log_max_bytes()
        self.slow_log.record(entry)
        from ..metrics import REGISTRY

        REGISTRY.inc("slow_queries_total")


class _RingLogHandler(logging.Handler):
    """Process-wide singleton handler feeding the newest Domain's ring."""

    def __init__(self):
        super().__init__()
        self.ring = None

    def emit(self, record):
        ring = self.ring
        if ring is None:
            return
        try:
            ring.append((record.created, record.levelname,
                         record.name, record.getMessage()[:400]))
        except Exception:  # noqa: BLE001 - logging must never raise
            pass


_RING_HANDLER = _RingLogHandler()


def _attach_log_ring(ring):
    logger = logging.getLogger("tidb_tpu")
    if _RING_HANDLER not in logger.handlers:
        logger.addHandler(_RING_HANDLER)
    _RING_HANDLER.ring = ring
