"""Domain background maintenance: GC worker, compaction scheduling, and
the expensive-query watchdog.

Reference:
- store/tikv/gcworker/gc_worker.go:213-289 — the GC leader computes a
  safepoint (now - gc_life_time), then drives version GC; here the version
  chains live in each TableStore's delta, so GC prunes them directly.
- util/expensivequery/expensivequery.go:50-154 — a ticker that logs
  statements running past a threshold and enforces max_execution_time.
- TiFlash's delta-merge compaction scheduling (maybe_compact here).

One daemon thread per Domain; `tick()` is public and synchronous so tests
drive maintenance deterministically.
"""

from __future__ import annotations

import logging
import threading
import time

from ..metrics import REGISTRY
from ..store.oracle import compose_ts
from ..util_concurrency import witness_wait_check

log = logging.getLogger("tidb_tpu.maintenance")


class MaintenanceWorker:
    def __init__(self, domain, interval_s: float = 10.0):
        self.domain = domain
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.last_safepoint = 0
        self.flagged: dict = {}  # (conn_id, stmt_start) -> True (log once)

    # ---- lifecycle -----------------------------------------------------
    def start(self):
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="tidb-tpu-maintenance", daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def _idle_wait(self) -> bool:
        """Park until the next tick or stop.  A held-lock park would
        starve whoever needs that lock for a whole interval, so the
        wait-witness guards the site (tests call this directly under a
        deliberately held lock to pin the negative)."""
        witness_wait_check("MaintenanceWorker._stop.wait")
        return self._stop.wait(self.interval_s)

    def _loop(self):
        while not self._idle_wait():
            try:
                self.tick()
            except Exception:
                log.exception("maintenance tick failed")

    # ---- one maintenance round ----------------------------------------
    def tick(self):
        self.run_gc()
        self.run_compaction()
        self.sweep_orphan_locks()
        self.watch_expensive()
        REGISTRY.inc("maintenance_ticks_total")

    def _gc_life_s(self) -> float:
        raw = self.domain.global_vars.get("tidb_gc_life_time", "600")
        try:
            return float(raw)
        except ValueError:
            return 600.0

    def run_gc(self):
        """Prune MVCC version chains below the safepoint.  The safepoint
        never passes a live transaction's start_ts — a reader at start_ts
        must keep seeing its snapshot (gc_worker.go calcSafePoint checks
        active txns via PD's min-start-ts the same way)."""
        storage = self.domain.storage
        now_ms = int(time.time() * 1000)
        safepoint = compose_ts(now_ms - int(self._gc_life_s() * 1000), 0)
        # recycle-bin purge runs on EVERY tick, independent of the MVCC
        # safepoint: a pinned snapshot must not let dropped-table stores
        # accumulate in RAM/disk forever
        purged = self.domain.catalog.purge_recycle_bin(self._gc_life_s())
        if purged:
            REGISTRY.inc("gc_recycle_bin_purged_total", purged)
        floor = storage.live_txn_floor()
        if floor is not None:
            safepoint = min(safepoint, floor - 1)
        pinned = storage.pinned_read_floor()
        if pinned is not None:
            # sessions pinned via SET tidb_snapshot read at their pinned
            # TSO outside any transaction — hold the safepoint for them too
            safepoint = min(safepoint, pinned - 1)
        if safepoint <= self.last_safepoint:
            return
        self.last_safepoint = safepoint
        REGISTRY.set("gc_safe_point", safepoint)
        pruned = 0
        for tid in list(storage.table_ids()):
            try:
                pruned += storage.table(tid).gc(safepoint)
            except Exception:
                continue  # dropped concurrently
        if pruned:
            REGISTRY.inc("gc_versions_pruned_total", pruned)

    def run_compaction(self):
        """Delta-merge scheduling: fold oversized deltas into base blocks
        so scans stay columnar (TiFlash background delta-merge)."""
        storage = self.domain.storage
        for tid in list(storage.table_ids()):
            try:
                storage.maybe_compact(tid)
            except Exception:
                pass  # raced a drop/lock; next tick retries

    def sweep_orphan_locks(self) -> int:
        """Proactively resolve TTL-expired locks whose owner txn this
        process no longer tracks (crashed sessions).  Without the sweep,
        resolution is on-access only: an orphan lock on a cold row blocks
        the first writer to touch it for a full lock-wait — the reference
        runs the same proactive pass in the GC worker
        (gc_worker.go resolveLocks over the scanned range)."""
        from ..store.txn import resolve_lock

        storage = self.domain.storage
        resolved = 0
        for tid in list(storage.table_ids()):
            try:
                store = storage.table(tid)
            except Exception:
                continue  # dropped concurrently
            for h, lk in list(store.locks.items()):
                if storage.txn_alive(lk.start_ts):
                    continue  # live owner: never steal its locks
                if not storage.oracle.is_expired(lk.start_ts, lk.ttl_ms):
                    continue
                try:
                    resolve_lock(storage, tid, h)
                    resolved += 1
                except Exception:
                    continue  # raced a concurrent access-path resolution
        if resolved:
            REGISTRY.inc("orphan_locks_resolved_total", resolved)
            log.info("resolved %d orphan lock(s)", resolved)
        return resolved

    def watch_expensive(self):
        """Flag statements running past tidb_expensive_query_time_threshold
        (log + metric, once per statement) and kill those exceeding the
        session's max_execution_time (expensivequery.go:50-154)."""
        try:
            thresh = float(self.domain.global_vars.get(
                "tidb_expensive_query_time_threshold", "60"))
        except ValueError:
            thresh = 60.0
        now = time.time()
        for conn_id, sess in list(self.domain.sessions.items()):
            start = getattr(sess, "stmt_start", None)
            sql = getattr(sess, "stmt_sql", "")
            if start is None:
                continue
            elapsed = now - start
            key = (conn_id, start)
            if elapsed >= thresh and key not in self.flagged:
                self.flagged[key] = True
                REGISTRY.inc("expensive_queries_total")
                log.warning("expensive query (%.1fs, conn %s): %.200s",
                            elapsed, conn_id, sql)
            max_ms = 0
            try:
                max_ms = sess.vars.get_int("max_execution_time")
            except Exception:
                pass
            if max_ms > 0 and elapsed * 1000 >= max_ms:
                REGISTRY.inc("expensive_queries_killed_total")
                log.warning("killing over-time query (conn %s): %.200s",
                            conn_id, sql)
                # backstop only: the statement's own QueryScope carries
                # the max_execution_time deadline and fires at the next
                # host seam; the watchdog covers sessions whose deadline
                # was raised mid-flight and legacy ctx-only paths.  The
                # reason stays 'timeout' so the termination report does
                # not depend on who noticed first.
                sess.cancel_query("timeout")
        # bounded memory for the once-per-statement markers
        if len(self.flagged) > 1024:
            dead = [k for k in self.flagged
                    if getattr(self.domain.sessions.get(k[0]), "stmt_start",
                               None) != k[1]]
            for k in dead:
                del self.flagged[k]
