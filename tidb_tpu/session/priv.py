"""Privilege statements (minimal RBAC surface).

Reference: privilege/privileges (MySQL-compatible priv tables cached in
Handle, cache.go:1037) and executor/grant.go / revoke.go / simple.go user
management.  Round-1 scope: user registry + global grants recorded on the
domain; enforcement hooks come with the server layer.
"""

from __future__ import annotations

from ..errors import KVError
from ..parser import ast


def _users(domain) -> dict:
    if not hasattr(domain, "users"):
        domain.users = {"root@%": {"password": "", "privs": {"ALL"}}}
    return domain.users


def handle(session, s):
    users = _users(session.domain)
    if isinstance(s, ast.CreateUserStmt):
        key = s.user
        if key in users and not s.if_not_exists:
            raise KVError(f"user {s.user!r} exists")
        users.setdefault(key, {"password": s.password, "privs": set()})
    elif isinstance(s, ast.DropUserStmt):
        if s.user not in users and not s.if_exists:
            raise KVError(f"user {s.user!r} does not exist")
        users.pop(s.user, None)
    elif isinstance(s, ast.SetPasswordStmt):
        u = users.get(s.user)
        if u is None:
            raise KVError(f"user {s.user!r} does not exist")
        u["password"] = s.password
    elif isinstance(s, ast.GrantStmt):
        u = users.setdefault(s.user, {"password": "", "privs": set()})
        u["privs"].update(p.upper() for p in s.privs)
    elif isinstance(s, ast.RevokeStmt):
        u = users.get(s.user)
        if u is not None:
            for p in s.privs:
                u["privs"].discard(p.upper())
    elif isinstance(s, ast.FlushStmt):
        pass
    from .session import ResultSet

    return ResultSet()
