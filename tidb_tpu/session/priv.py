"""Privileges: user registry, grant tables, authentication, and the
plan-time privilege check.

Reference: privilege/privileges/cache.go:1037 (MySQLPrivilege request
check over cached user/db/table_priv rows), planner/optimize.go:128-131
(CheckPrivilege on the visitInfo list before planning), server/conn.go
(mysql_native_password handshake), executor/grant.go / revoke.go /
simple.go (user management).

Shape here: one PrivManager on the Domain holding
``user@host -> {password_stage2, global privs, per-db privs, per-table
privs}``; sessions carry ``session.user`` and every statement passes
through :func:`check_stmt` before dispatch — the optimize.go choke point.
In-process sessions default to root (trusted), the wire server
authenticates and sets the real user.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Dict, List, Optional, Set, Tuple

from ..errors import KVError, PrivilegeError
from ..parser import ast
from ..util_concurrency import make_rlock

# statement privilege names (mysql.user column surface subset)
DML_PRIVS = {"select", "insert", "update", "delete"}
DDL_PRIVS = {"create", "drop", "alter", "index", "create view"}
ADMIN_PRIVS = {"create user", "super", "process", "grant option"}
KNOWN_PRIVS = DML_PRIVS | DDL_PRIVS | ADMIN_PRIVS | {"all"}


def _norm_user(u: str) -> str:
    return u if "@" in u else f"{u}@%"


def _host_matches(pattern: str, host: str) -> bool:
    """MySQL host matching: % and _ are LIKE wildcards, case-insensitive;
    'localhost' and loopback addresses are interchangeable."""
    import fnmatch

    pattern = pattern.lower()
    host = (host or "localhost").lower()
    if host in ("127.0.0.1", "::1"):
        if pattern == "localhost":
            return True
    if pattern == host:
        return True
    glob = pattern.replace("*", "[*]").replace("?", "[?]")
    glob = glob.replace("%", "*").replace("_", "?")
    return fnmatch.fnmatchcase(host, glob)


def _host_specificity(pattern: str) -> tuple:
    """Sort key: literal hosts first, then fewer wildcards, then longer
    literal text (privilege/privileges/cache.go sortFromIdx rule)."""
    wild = pattern.count("%") + pattern.count("_")
    return (wild, -len(pattern.replace("%", "").replace("_", "")))


def _stage2(password: str) -> str:
    """mysql_native_password stored hash: SHA1(SHA1(password)), hex."""
    if not password:
        return ""
    return hashlib.sha1(
        hashlib.sha1(password.encode()).digest()).hexdigest()


class PrivManager:
    def __init__(self, data_dir: Optional[str] = None):
        self.data_dir = data_dir
        # server pool runs GRANTs concurrently
        self._mu = make_rlock("session.priv:PrivManager._mu")
        self.users: Dict[str, dict] = {}
        if data_dir is not None:
            self._load()
        if "root@%" not in self.users:
            self.users["root@%"] = self._new_user("")
            self.users["root@%"]["global"].add("all")

    @staticmethod
    def _new_user(password: str, is_role: bool = False) -> dict:
        return {"password": _stage2(password), "global": set(),
                "dbs": {}, "tables": {}, "roles": set(),
                "default_roles": set(), "is_role": is_role}

    # ---- persistence (mysql.* system tables analog) -------------------
    def _path(self) -> Optional[str]:
        if self.data_dir is None:
            return None
        return os.path.join(self.data_dir, "users.json")

    def _save(self):
        p = self._path()
        if p is None:
            return
        blob = {}
        for k, u in self.users.items():
            blob[k] = {
                "password": u["password"],
                "global": sorted(u["global"]),
                "dbs": {d: sorted(v) for d, v in u["dbs"].items()},
                "tables": {f"{d} {t}": sorted(v)
                           for (d, t), v in u["tables"].items()},
                "roles": sorted(u.get("roles", ())),
                "default_roles": sorted(u.get("default_roles", ())),
                "is_role": bool(u.get("is_role")),
            }
        tmp = p + ".tmp"
        with open(tmp, "w") as f:
            json.dump(blob, f)
        os.replace(tmp, p)

    def _load(self):
        p = self._path()
        if p is None or not os.path.exists(p):
            return
        with open(p) as f:
            blob = json.load(f)
        for k, u in blob.items():
            self.users[k] = {
                "password": u["password"],
                "global": set(u["global"]),
                "dbs": {d: set(v) for d, v in u["dbs"].items()},
                "tables": {tuple(key.split(" ", 1)): set(v)
                           for key, v in u["tables"].items()},
                "roles": set(u.get("roles", ())),
                "default_roles": set(u.get("default_roles", ())),
                "is_role": bool(u.get("is_role")),
            }

    # ---- user management ----------------------------------------------
    def create_user(self, user: str, password: str, if_not_exists: bool):
        key = _norm_user(user)
        with self._mu:
            return self._create_user_locked(key, user, password,
                                            if_not_exists)

    def _create_user_locked(self, key, user, password, if_not_exists):
        if key in self.users:
            if if_not_exists:
                return
            raise KVError(f"user {user!r} exists")
        self.users[key] = self._new_user(password)
        self._save()

    def drop_user(self, user: str, if_exists: bool):
        key = _norm_user(user)
        with self._mu:
            if key not in self.users and not if_exists:
                raise KVError(f"user {user!r} does not exist")
            self.users.pop(key, None)
            # a dropped account (user OR role) must not linger in other
            # accounts' role lists: a later CREATE ROLE under the same
            # name would silently re-attach
            for other in self.users.values():
                other.get("roles", set()).discard(key)
                other.get("default_roles", set()).discard(key)
            self._save()

    def set_password(self, user: str, password: str):
        key = _norm_user(user)
        with self._mu:
            u = self.users.get(key)
            if u is None:
                raise KVError(f"user {user!r} does not exist")
            u["password"] = _stage2(password)
            self._save()

    # ---- roles (MySQL 8 roles; executor/simple.go SET ROLE family) -----
    def create_role(self, role: str, if_not_exists: bool):
        key = _norm_user(role)
        with self._mu:
            if key in self.users:
                if if_not_exists:
                    return
                raise KVError(f"role {role!r} exists")
            self.users[key] = self._new_user("", is_role=True)
            self._save()

    def drop_role(self, role: str, if_exists: bool):
        key = _norm_user(role)
        with self._mu:
            u = self.users.get(key)
            if u is None or not u.get("is_role"):
                if if_exists:
                    return
                raise KVError(f"role {role!r} does not exist")
            del self.users[key]
            for other in self.users.values():
                other.get("roles", set()).discard(key)
                other.get("default_roles", set()).discard(key)
            self._save()

    def grant_role(self, roles: List[str], user: str):
        with self._mu:
            u = self.users.get(_norm_user(user))
            if u is None:
                raise KVError(f"user {user!r} does not exist")
            for r in roles:
                rk = _norm_user(r)
                ru = self.users.get(rk)
                if ru is None or not ru.get("is_role"):
                    raise KVError(f"role {r!r} does not exist")
                u.setdefault("roles", set()).add(rk)
            self._save()

    def revoke_role(self, roles: List[str], user: str):
        with self._mu:
            u = self.users.get(_norm_user(user))
            if u is None:
                raise KVError(f"user {user!r} does not exist")
            for r in roles:
                u.get("roles", set()).discard(_norm_user(r))
                u.get("default_roles", set()).discard(_norm_user(r))
            self._save()

    def set_default_roles(self, user: str, roles) -> None:
        """roles: iterable of names, or the strings 'all'/'none'."""
        with self._mu:
            u = self.users.get(_norm_user(user))
            if u is None:
                raise KVError(f"user {user!r} does not exist")
            if roles == "all":
                u["default_roles"] = set(u.get("roles", ()))
            elif roles == "none":
                u["default_roles"] = set()
            else:
                want = {_norm_user(r) for r in roles}
                missing = want - u.get("roles", set())
                if missing:
                    raise KVError(
                        f"role(s) {sorted(missing)} not granted to {user}")
                u["default_roles"] = want
            self._save()

    def granted_roles(self, user: str) -> Set[str]:
        with self._mu:
            u = self.users.get(_norm_user(user))
            return set(u.get("roles", ())) if u else set()

    def default_roles(self, user: str) -> Set[str]:
        with self._mu:
            u = self.users.get(_norm_user(user))
            return set(u.get("default_roles", ())) if u else set()

    def grant(self, user: str, privs: List[str], level: str):
        key = _norm_user(user)
        with self._mu:
            u = self.users.get(key)
            if u is None:
                # NO_AUTO_CREATE_USER (MySQL 5.7+): a typo'd grantee must
                # not become a password-less login
                raise KVError(
                    f"user {user!r} does not exist (create it first)")
            privset = {p.lower() for p in privs}
            bad = privset - KNOWN_PRIVS
            if bad:
                raise KVError(f"unknown privilege(s) {sorted(bad)}")
            db, table = _parse_level(level)
            if db is None:
                u["global"] |= privset
            elif table is None:
                u["dbs"].setdefault(db, set()).update(privset)
            else:
                u["tables"].setdefault((db, table), set()).update(privset)
            self._save()

    def revoke(self, user: str, privs: List[str], level: str):
        with self._mu:
            u = self.users.get(_norm_user(user))
            if u is None:
                return
            privset = {p.lower() for p in privs}
            db, table = _parse_level(level)
            if db is None:
                tgt = u["global"]
            elif table is None:
                tgt = u["dbs"].get(db)
            else:
                tgt = u["tables"].get((db, table))
            if tgt is not None:
                _revoke_from(tgt, privset)
            self._save()

    # ---- checks --------------------------------------------------------
    def match_account(self, name: str, host: str):
        """Resolve (login name, client host) to the most specific account
        key, MySQL-style: exact hosts beat patterns, fewer wildcards beat
        more (privilege/privileges/cache.go connectionVerification)."""
        with self._mu:
            cands = []
            for key, u in self.users.items():
                if u.get("is_role"):
                    continue  # MySQL roles are created LOCKED: no login
                uname, _, pat = key.rpartition("@")
                if uname == name and _host_matches(pat, host):
                    cands.append((key, pat))
        if not cands:
            return None
        host_l = (host or "localhost").lower()
        # an exact pattern==host match outranks aliases ('127.0.0.1'
        # account beats 'localhost' for a 127.0.0.1 client) — determinism
        # does not depend on dict order
        cands.sort(key=lambda kp: (kp[1].lower() != host_l,)
                   + _host_specificity(kp[1]))
        return cands[0][0]

    def auth(self, user: str, token: bytes, salt: bytes,
             host: str = "%"):
        """mysql_native_password: token = SHA1(pw) XOR
        SHA1(salt + SHA1(SHA1(pw))); verify against the stored stage2 of
        the MOST SPECIFIC account whose host pattern matches the client.
        Returns the matched account key ('name@pattern') or None."""
        key = self.match_account(user, host)
        u = self.users.get(key) if key is not None else None
        if u is None:
            return None
        stored = u["password"]
        if not stored:
            return key if len(token) == 0 else None
        if len(token) != 20:
            return None
        stage2 = bytes.fromhex(stored)
        mix = hashlib.sha1(salt + stage2).digest()
        stage1 = bytes(a ^ b for a, b in zip(token, mix))
        return key if hashlib.sha1(stage1).digest() == stage2 else None

    def check(self, user: str, priv: str, db: Optional[str] = None,
              table: Optional[str] = None, roles=()) -> bool:
        """True when the user holds `priv` directly OR through any of the
        session's ACTIVE roles (privilege merge,
        privileges/cache.go RequestVerification with activeRoles)."""
        if self._check_one(_norm_user(user), priv, db, table):
            return True
        return any(self._check_one(_norm_user(r), priv, db, table)
                   for r in roles)

    def _check_one(self, key: str, priv: str, db, table) -> bool:
        u = self.users.get(key)
        if u is None:
            return False
        priv = priv.lower()
        g = u["global"]
        if "all" in g or priv in g:
            return True
        if db is not None:
            dbl = db.lower()
            dp = u["dbs"].get(dbl, ())
            if "all" in dp or priv in dp:
                return True
            if table is not None:
                tp = u["tables"].get((dbl, table.lower()), ())
                if "all" in tp or priv in tp:
                    return True
        return False

    def require(self, user: str, priv: str, db: Optional[str] = None,
                table: Optional[str] = None, roles=()):
        if not self.check(user, priv, db, table, roles=roles):
            target = f"{db}.{table}" if table else (db or "*")
            raise PrivilegeError(priv.upper(), user, target)

    def show_grants(self, user: str) -> List[str]:
        key = _norm_user(user)
        with self._mu:
            return self._show_grants_locked(key, user)

    def _show_grants_locked(self, key, user) -> List[str]:
        u = self.users.get(key)
        if u is None:
            raise KVError(f"user {user!r} does not exist")
        name, host = key.rsplit("@", 1)
        ident = f"'{name}'@'{host}'"
        out = []
        g = u["global"]
        if g:
            out.append(f"GRANT {_fmt(g)} ON *.* TO {ident}")
        else:
            out.append(f"GRANT USAGE ON *.* TO {ident}")
        for db in sorted(u["dbs"]):
            if u["dbs"][db]:
                out.append(f"GRANT {_fmt(u['dbs'][db])} ON `{db}`.* "
                           f"TO {ident}")
        for (db, t) in sorted(u["tables"]):
            privs = u["tables"][(db, t)]
            if privs:
                out.append(f"GRANT {_fmt(privs)} ON `{db}`.`{t}` "
                           f"TO {ident}")
        roles = sorted(u.get("roles", ()))
        if roles:
            rid = ", ".join(
                "`{}`@`{}`".format(*r.rsplit("@", 1)) for r in roles)
            out.append(f"GRANT {rid} TO {ident}")
        return out


def _fmt(privs: Set[str]) -> str:
    if "all" in privs:
        return "ALL PRIVILEGES"
    return ", ".join(p.upper() for p in sorted(privs))


def _revoke_from(held: Set[str], revoked: Set[str]):
    """MySQL revoke semantics at one grant level: REVOKE ALL empties the
    level; revoking a specific privilege from a holder of ALL first expands
    ALL into its constituent privileges (grant.go/revoke.go behavior)."""
    if "all" in revoked:
        held.clear()
        return
    if "all" in held:
        held.discard("all")
        held.update(KNOWN_PRIVS - {"all"})
    held -= revoked


def _parse_level(level: str) -> Tuple[Optional[str], Optional[str]]:
    """'*.*' -> (None, None); 'db.*' -> (db, None); 'db.t' -> (db, t)."""
    level = (level or "*.*").strip()
    if level in ("*.*", "*", ""):
        return None, None
    if "." in level:
        db, t = level.split(".", 1)
        db = db.strip("`").lower()
        t = t.strip("`").lower()
        return (db, None) if t == "*" else (db, t)
    return level.strip("`").lower(), None


# ---------------------------------------------------------------------------
# plan-time statement check (planner/optimize.go:128-131 analog)
# ---------------------------------------------------------------------------


def _walk_tables(node, out: List[ast.TableName]):
    """Generic AST walk collecting every referenced TableName (covers
    subqueries/joins/unions via dataclass-field recursion)."""
    if isinstance(node, ast.TableName):
        out.append(node)
        return
    if isinstance(node, (list, tuple)):
        for x in node:
            _walk_tables(x, out)
        return
    if isinstance(node, ast.Node):
        for f in getattr(node, "__dataclass_fields__", {}):
            _walk_tables(getattr(node, f), out)


def check_stmt(session, s) -> None:
    """Raise PrivilegeError unless session.user may run statement `s`
    (directly or through the session's ACTIVE roles).  root (ALL at
    global scope) short-circuits — the common in-process path costs one
    dict lookup."""
    pm = session.domain.priv
    user = session.user
    roles = tuple(getattr(session, "active_roles", ()))
    u = pm.users.get(_norm_user(user))
    if u is not None and "all" in u["global"]:
        return
    def db_of(tn: ast.TableName) -> str:
        return (tn.db or session.current_db).lower()

    def tables_of(node) -> List[ast.TableName]:
        out: List[ast.TableName] = []
        _walk_tables(node, out)
        return out

    if isinstance(s, (ast.SelectStmt, ast.UnionStmt, ast.ExplainStmt,
                      ast.TraceStmt)):
        for tn in tables_of(s):
            pm.require(user, "select", db_of(tn), tn.name.lower(),
                       roles=roles)
        return
    if isinstance(s, (ast.InsertStmt, ast.UpdateStmt, ast.DeleteStmt,
                      ast.LoadDataStmt)):
        need = {ast.InsertStmt: "insert", ast.UpdateStmt: "update",
                ast.DeleteStmt: "delete", ast.LoadDataStmt: "insert"}[
                    type(s)]
        target = s.table
        pm.require(user, need, db_of(target), target.name.lower(),
                   roles=roles)
        for tn in tables_of(s):
            if tn is target:
                continue
            pm.require(user, "select", db_of(tn), tn.name.lower(),
                       roles=roles)
        return
    if isinstance(s, ast.CreateTableStmt):
        pm.require(user, "create", db_of(s.table), roles=roles)
        return
    if isinstance(s, ast.CreateViewStmt):
        pm.require(user, "create view", db_of(s.name), roles=roles)
        return
    if isinstance(s, (ast.DropTableStmt, ast.TruncateTableStmt)):
        tns = s.tables if isinstance(s, ast.DropTableStmt) else [s.table]
        for tn in tns:
            pm.require(user, "drop", db_of(tn), roles=roles)
        return
    if isinstance(s, (ast.AlterTableStmt, ast.RenameTableStmt)):
        tn = s.table if isinstance(s, ast.AlterTableStmt) else s.old
        pm.require(user, "alter", db_of(tn), roles=roles)
        return
    if isinstance(s, (ast.CreateIndexStmt, ast.DropIndexStmt)):
        pm.require(user, "index", db_of(s.table), roles=roles)
        return
    if isinstance(s, ast.RecoverTableStmt):
        pm.require(user, "create", db_of(s.table), roles=roles)
        return
    if isinstance(s, ast.CreateDatabaseStmt):
        pm.require(user, "create", s.name.lower(), roles=roles)
        return
    if isinstance(s, ast.DropDatabaseStmt):
        pm.require(user, "drop", s.name.lower(), roles=roles)
        return
    if isinstance(s, (ast.CreateUserStmt, ast.DropUserStmt,
                      ast.SetPasswordStmt, ast.CreateRoleStmt,
                      ast.DropRoleStmt, ast.GrantRoleStmt,
                      ast.RevokeRoleStmt)):
        pm.require(user, "create user", roles=roles)
        return
    if isinstance(s, ast.SetRoleStmt):
        return  # activating roles granted to yourself
    if isinstance(s, ast.SetDefaultRoleStmt):
        if any(_norm_user(u2) != _norm_user(user) for u2 in s.users):
            pm.require(user, "create user", roles=roles)
        return
    if isinstance(s, (ast.GrantStmt, ast.RevokeStmt)):
        # MySQL (executor/grant.go): the granter must hold GRANT OPTION at
        # the statement's scope AND every privilege being granted there.
        # CREATE USER alone authorizes user management, not grants —
        # otherwise a user-admin could GRANT ALL to themselves.
        db, table = _parse_level(s.level)
        pm.require(user, "grant option", db, table, roles=roles)
        # ALL expands to the privileges that EXIST at the statement's
        # scope: db/table-level ALL comprises only DML+DDL privileges
        # (MySQL has no db-scoped SUPER/PROCESS/CREATE USER to demand)
        scope_all = (KNOWN_PRIVS - {"grant option", "all"} if db is None
                     else DML_PRIVS | DDL_PRIVS)
        for p in s.privs:
            needed = sorted(scope_all) if p.lower() == "all" else [p]
            for q in needed:
                pm.require(user, q, db, table, roles=roles)
        return
    if isinstance(s, (ast.KillStmt, ast.AdminStmt, ast.SplitRegionStmt,
                      ast.DropStatsStmt, ast.RepairTableStmt)):
        pm.require(user, "super", roles=roles)
        return
    if isinstance(s, ast.ShowStmt) and s.kind == "grants" and s.target:
        from .session import Session  # typing only; avoid cycle at import

        if _norm_user(s.target) != _norm_user(user):
            pm.require(user, "create user", roles=roles)  # enumerate others: admin-only
        return
    # SET / SHOW / USE / txn control / PREPARE-EXECUTE: unrestricted
    # (EXECUTE re-enters check_stmt with the underlying statement)


def handle(session, s):
    """Execute a privilege statement (already authorized by check_stmt)."""
    pm = session.domain.priv
    if isinstance(s, ast.CreateUserStmt):
        pm.create_user(s.user, s.password, s.if_not_exists)
    elif isinstance(s, ast.DropUserStmt):
        pm.drop_user(s.user, s.if_exists)
    elif isinstance(s, ast.SetPasswordStmt):
        pm.set_password(s.user, s.password)
    elif isinstance(s, ast.GrantStmt):
        pm.grant(s.user, s.privs, s.level)
    elif isinstance(s, ast.RevokeStmt):
        pm.revoke(s.user, s.privs, s.level)
    elif isinstance(s, ast.CreateRoleStmt):
        for r in s.roles:
            pm.create_role(r, s.if_not_exists)
    elif isinstance(s, ast.DropRoleStmt):
        for r in s.roles:
            pm.drop_role(r, s.if_exists)
    elif isinstance(s, ast.GrantRoleStmt):
        for u in s.users:
            pm.grant_role(s.roles, u)
    elif isinstance(s, ast.RevokeRoleStmt):
        for u in s.users:
            pm.revoke_role(s.roles, u)
    elif isinstance(s, ast.SetRoleStmt):
        granted = pm.granted_roles(session.user)
        if s.mode == "none":
            session.active_roles = []
        elif s.mode == "all":
            session.active_roles = sorted(granted)
        elif s.mode == "default":
            session.active_roles = sorted(pm.default_roles(session.user))
        else:
            want = [_norm_user(r) for r in s.roles]
            missing = [r for r in want if r not in granted]
            if missing:
                raise KVError(f"role(s) {missing} not granted to "
                              f"{session.user}")
            session.active_roles = sorted(want)
    elif isinstance(s, ast.SetDefaultRoleStmt):
        target = (s.mode if s.mode in ("all", "none") else s.roles)
        for u in s.users:
            pm.set_default_roles(u, target)
    elif isinstance(s, ast.FlushStmt):
        pass
    from .session import ResultSet

    return ResultSet()
