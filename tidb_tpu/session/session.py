"""Session: parse -> plan -> execute loop with txn lifecycle.

Reference: session/session.go — Execute (:1065) / execute (:1078) parse+
compile+run loop, lazy txn state machine (txn.go:41-141), commit with
optimistic retry (:444,:635), and executor/adapter.go ExecStmt.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np

from ..catalog import ColumnInfo, IndexInfo, TableInfo
from ..catalog.schema import STATE_PUBLIC
from ..errors import (
    ExecutorError,
    KVError,
    PlanError,
    SchemaChangedError,
    TiDBTPUError,
    TxnConflictError,
    UnknownDatabaseError,
)
from ..executor import ExecContext, collect_all
from ..parser import ast, parse
from ..planner import (
    PhysicalContext,
    explain_text,
    finish_plan,
    plan_statement,
)
from ..planner.build import PlanBuilder
from ..planner.rules import optimize_logical
from ..types import (
    FieldType,
    TypeKind,
    ty_bit,
    ty_date,
    ty_datetime,
    ty_decimal,
    ty_enum,
    ty_float,
    ty_int,
    ty_json,
    ty_set,
    ty_string,
    ty_time,
    ty_uint,
)
from ..types.values import (
    format_date,
    format_datetime,
    format_decimal,
    format_time,
)
from .domain import Domain
from .vars import SYSVAR_DEFAULTS, SessionVars


@dataclass
class ResultSet:
    headers: List[str] = field(default_factory=list)
    rows: List[tuple] = field(default_factory=list)
    affected_rows: int = 0
    last_insert_id: int = 0
    warnings: List[str] = field(default_factory=list)
    is_query: bool = False
    ftypes: Optional[List[FieldType]] = None  # column types for the wire

    def scalar(self):
        return self.rows[0][0] if self.rows else None


_TYPE_MAP = {
    "bigint": lambda p, s: ty_int(),
    "int": lambda p, s: ty_int(),
    "integer": lambda p, s: ty_int(),
    "smallint": lambda p, s: ty_int(),
    "tinyint": lambda p, s: ty_int(),
    "bool": lambda p, s: ty_int(),
    "boolean": lambda p, s: ty_int(),
    "bigint unsigned": lambda p, s: ty_uint(),
    "double": lambda p, s: ty_float(),
    "float": lambda p, s: ty_float(),
    "real": lambda p, s: ty_float(),
    "decimal": lambda p, s: ty_decimal(p or 10, s),
    "numeric": lambda p, s: ty_decimal(p or 10, s),
    "varchar": lambda p, s: ty_string(),
    "char": lambda p, s: ty_string(),
    "text": lambda p, s: ty_string(),
    "blob": lambda p, s: ty_string(),
    "string": lambda p, s: ty_string(),
    "date": lambda p, s: ty_date(),
    "datetime": lambda p, s: ty_datetime(),
    "timestamp": lambda p, s: ty_datetime(),
    "time": lambda p, s: ty_time(),
    "bit": lambda p, s: ty_bit(p or 1),
    "json": lambda p, s: ty_json(),
}


class Session:
    def __init__(self, domain: Domain, conn_id: int = 0):
        self.domain = domain
        self.conn_id = conn_id
        self.vars = SessionVars(domain.global_vars)
        self.current_db = "test"
        # authenticated identity; in-process sessions are trusted as root,
        # the wire server overwrites this after the auth handshake
        self.user = "root@%"
        self.active_roles: List[str] = []  # SET ROLE state (MySQL roles)
        self._snapshot_ts = None  # SET tidb_snapshot historical-read TSO
        self._snapshot_pin = None  # storage pin token holding GC/compaction
        self._txn = None  # explicit txn (BEGIN..COMMIT)
        self._in_txn = False
        self._killed = False
        self._warnings: List[str] = []
        self._prepared: dict = {}  # name -> sql
        self.last_exec_ctx: Optional[ExecContext] = None
        self.last_plan = None
        self.last_trace = None  # finished QueryTrace of the last execute()
        # lifecycle: the in-flight statement's QueryScope (deadline +
        # cancel event) — KILL, the expensive-query watchdog and server
        # drain all cancel through it; and the last statement's
        # termination reason (ok|killed|timeout|mem_quota|overload|
        # shutdown|error) for the slow log / summary / metrics
        self._scope = None
        self.last_termination = "ok"
        self._pending_wire_read = None  # server-set COM_QUERY payload size
        self._pending_admission_wait_ns = 0  # server-set queue wait
        from collections import OrderedDict

        self._plan_cache: "OrderedDict" = OrderedDict()

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------
    def execute(self, sql: str, params: Optional[list] = None) -> List[ResultSet]:
        from . import bindinfo

        if bindinfo.is_binding_stmt(sql):
            return [bindinfo.handle(self, sql)]
        from ..lifecycle import (
            QueryScope,
            activate_scope,
            classify_termination,
            deactivate_scope,
            scope_active,
        )
        from ..trace import finish_trace, span, start_trace, tracing_active

        # one lifecycle scope per top-level execute(): the statement's
        # deadline (max_execution_time) + cancel event, observed at every
        # blocking host-side seam.  Nested executes (EXECUTE prepared,
        # TRACE targets, subplans) inherit the outer statement's scope.
        sc = sc_token = None
        if not scope_active():
            timeout_ms = self.vars.get_int("max_execution_time")
            sc = QueryScope(timeout_ms / 1000.0 if timeout_ms > 0 else None)
            # per-statement resource group (ISSUE 17): resolved ONCE at
            # scope creation (sysvar wins, then the user's ALTER USER
            # binding, then default); the group OBJECT rides the scope
            # so chunked dispatchers and fan-out workers never need a
            # domain lookup
            sc.resgroup = self.domain.resgroups.resolve(
                self.user, self.vars.get("tidb_tpu_resource_group") or "")
        # one trace per top-level execute() call: slow-log-enabled
        # sessions trace every statement; nested executes record into the
        # outer trace
        tr = token = None
        if not tracing_active() and self.vars.get_bool("tidb_enable_slow_log"):
            tr, token = start_trace(sql, self.conn_id)
            wr = getattr(self, "_pending_wire_read", None)
            if wr:
                # (bytes, socket-wait ns) from the wire layer; the wait
                # becomes an asyncio-level wire.read span so admission
                # wait and network wait are distinguishable in traces
                nb, wait_ns = wr if isinstance(wr, tuple) else (wr, 0)
                tr.root.set(wire_read_bytes=nb)
                if wait_ns:
                    tr.add_span("wire.read", wait_ns, bytes=nb)
                self._pending_wire_read = None
            aw = getattr(self, "_pending_admission_wait_ns", 0)
            if aw:
                tr.add_span("admission.wait", aw)
                self._pending_admission_wait_ns = 0
        exc: Optional[BaseException] = None
        # activation happens IMMEDIATELY before the try whose finally
        # deactivates: an exception in the setup above must not leak the
        # scope contextvar onto this pooled executor thread (a poisoned
        # worker would kill every later statement scheduled on it)
        if sc is not None:
            sc_token = activate_scope(sc)
            self._scope = sc  # KILL / watchdog / drain cancel through this
        try:
            out = []
            with span("parse"):
                stmts = parse(sql)
            if len(stmts) == 1:
                # plan-cache key: single-statement texts cache their plan
                stmts[0]._sql_text = sql
            for stmt in stmts:
                t0 = time.time()
                self.stmt_start, self.stmt_sql = t0, sql  # watchdog
                try:
                    rs = self._execute_stmt(stmt, params)
                finally:
                    self.stmt_start = None
                dur = time.time() - t0
                self.domain.record_stmt(sql, dur, len(rs.rows))
                out.append(rs)
            return out
        except BaseException as e:
            exc = e
            raise
        finally:
            term = None
            if sc is not None:
                term = classify_termination(exc, sc)
                self.last_termination = term
                deactivate_scope(sc_token)
                if term not in ("ok", "error"):
                    from ..metrics import REGISTRY

                    REGISTRY.inc(f"stmt_terminated_{term}_total")
                self.domain.record_termination(sql, term)
            if tr is not None:
                if term is not None:
                    tr.root.set(termination=term)
                self.last_trace = tr
                totals = finish_trace(tr, token)
                self._maybe_slow_log(tr, totals)
                self._observe_slo(sql, tr)

    def query(self, sql: str, params: Optional[list] = None) -> List[tuple]:
        """Convenience: rows of the last result set."""
        return self.execute(sql, params)[-1].rows

    def _maybe_slow_log(self, tr, totals):
        """Account a finished trace: phase aggregates always fold into
        the statement summary; the slow log gets an entry when the
        statement crossed tidb_slow_log_threshold ms (0 logs all)."""
        try:
            dur_ms = tr.duration_ms()
            threshold = self.vars.get_int("tidb_slow_log_threshold", 300)
            self.domain.record_trace(tr, totals, dur_ms,
                                     slow=dur_ms >= threshold)
        except Exception:
            # the slow log is advisory and must never fail the
            # statement — but silent breakage would disable the whole
            # accounting pipeline invisibly, so count it
            from ..metrics import REGISTRY

            REGISTRY.inc("trace_accounting_errors_total")

    def _observe_slo(self, sql: str, tr):
        """Per-statement-class end-to-end latency histogram + SLO
        error-budget burn counters (ISSUE 13): the class threshold rides
        `tidb_tpu_slo_<class>_ms` sysvars (0 disables burn accounting;
        the histogram always records).  The value ``auto`` (ISSUE 20
        satellite) derives the threshold from the rolling-window p99
        (trace.slo) instead of a fixed constant."""
        try:
            from ..metrics import REGISTRY
            from ..trace import stmt_class
            from ..trace.slo import SLO_AUTO, resolve_threshold_ms

            cls = stmt_class(sql)
            dur_ms = tr.duration_ms()
            REGISTRY.observe_hist(f"stmt_latency_{cls}_ms", dur_ms)
            # GLOBAL scope only: the burn counters are fleet-wide and
            # must agree with the threshold /status reports.  Resolve
            # BEFORE feeding the windows: a statement is judged against
            # the baseline of statements that preceded it — an outlier
            # must not dilate its own threshold
            thr = resolve_threshold_ms(
                self.vars.get_global_str(f"tidb_tpu_slo_{cls}_ms", "0"),
                cls)
            # fixed-threshold classes feed the rolling windows too, so
            # flipping a class to 'auto' acts on an already-warm baseline
            SLO_AUTO.observe(cls, dur_ms)
            if thr > 0:
                if dur_ms > thr:
                    REGISTRY.inc(f"slo_{cls}_breach_total")
                else:
                    REGISTRY.inc(f"slo_{cls}_ok_total")
        except Exception:
            from ..metrics import REGISTRY

            REGISTRY.inc("trace_accounting_errors_total")

    def kill(self, query_only: bool = True):
        """KILL QUERY (default): cancel the in-flight statement only.
        KILL CONNECTION (query_only=False): poison the session."""
        if not query_only:
            self._killed = True
        self.cancel_query("killed")

    def cancel_query(self, reason: str):
        """Cancel the in-flight statement's scope (KILL, the watchdog's
        max_execution_time enforcement, server drain).  The statement
        unwinds at its next host-side seam — backoff sleeps, fan-out
        tasks, tile/mesh chunk loops, MPP rungs, 2PC prewrite batches
        and DDL backfill batches all observe the same event."""
        sc = self._scope
        if sc is not None:
            sc.cancel(reason)
        if self.last_exec_ctx is not None:
            self.last_exec_ctx.killed = True

    # ------------------------------------------------------------------
    # txn lifecycle (lazy txn, session/txn.go:41-141)
    # ------------------------------------------------------------------
    def _begin_txn(self):
        if self._txn is None:
            txn = self.domain.storage.begin()
            cat = self.domain.catalog
            start_ver = cat.schema_version

            def schema_check():
                touched = {tid for (tid, _h) in txn.buffer.keys()}
                if any(cat.table_versions.get(tid, 0) > start_ver
                       for tid in touched):
                    raise SchemaChangedError()

            txn.schema_check = schema_check
            try:
                # MySQL clients tune row-lock waits per session; clamp to
                # MySQL's documented range [1, 1073741824] so a bogus SET
                # (get_int -> 0) can't turn every wait into an instant
                # timeout
                txn.lock_wait_timeout_s = float(min(max(
                    self.vars.get_int("innodb_lock_wait_timeout"), 1),
                    1 << 30))
            except Exception:
                pass
            self._txn = txn
        return self._txn

    def _autocommit(self) -> bool:
        return self.vars.get_bool("autocommit") and not self._in_txn

    def commit(self):
        if self._txn is not None:
            txn, self._txn = self._txn, None
            self._in_txn = False
            touched = {tid for (tid, _h) in txn.buffer.keys()}
            # the commit-time schema check runs inside txn.commit() after
            # prewrite (txn.schema_check, wired in _begin_txn)
            txn.commit()
            if touched:
                for tid in touched:
                    self.domain.storage.maybe_compact(tid)
                self.domain.maybe_auto_analyze(touched)
        else:
            self._in_txn = False

    def rollback(self):
        if self._txn is not None:
            txn, self._txn = self._txn, None
            self._in_txn = False
            txn.rollback()
        else:
            self._in_txn = False

    # ------------------------------------------------------------------
    # dispatch
    # ------------------------------------------------------------------
    def _execute_stmt(self, stmt: ast.Stmt, params=None) -> ResultSet:
        self._warnings = []
        s = stmt
        from . import priv as _priv

        _priv.check_stmt(self, s)  # optimize.go:128-131 choke point
        if self._snapshot_ts is not None:
            self._snapshot_write_guard(s)
        if isinstance(s, (ast.SelectStmt, ast.UnionStmt, ast.InsertStmt,
                          ast.UpdateStmt, ast.DeleteStmt,
                          ast.LoadDataStmt)):
            self._check_table_locks(s)
        elif isinstance(s, (ast.DropTableStmt, ast.TruncateTableStmt,
                            ast.AlterTableStmt, ast.RenameTableStmt,
                            ast.CreateIndexStmt, ast.DropIndexStmt)):
            tns = (s.tables if isinstance(s, ast.DropTableStmt)
                   else [s.old] if isinstance(s, ast.RenameTableStmt)
                   else [s.table])
            for tn in tns:
                self._check_ddl_table_lock(tn.db, tn.name)
        from ..errors import DeadlockError

        try:
            return self._dispatch_stmt(s, params)
        except DeadlockError:
            # the victim's whole transaction rolls back so the surviving
            # waiter proceeds immediately (MySQL/TiDB deadlock handling)
            self.rollback()
            raise

    def _dispatch_stmt(self, s, params=None) -> ResultSet:
        if isinstance(s, (ast.SelectStmt, ast.UnionStmt)):
            return self._run_query(s, params)
        if isinstance(s, (ast.InsertStmt, ast.UpdateStmt, ast.DeleteStmt,
                          ast.LoadDataStmt)):
            return self._run_dml(s, params)
        if isinstance(s, ast.ExplainStmt):
            return self._run_explain(s)
        if isinstance(s, ast.TraceStmt):
            return self._run_trace(s)
        if isinstance(s, ast.BeginStmt):
            self._in_txn = True
            self._begin_txn()
            return ResultSet()
        if isinstance(s, ast.CommitStmt):
            self.commit()
            return ResultSet()
        if isinstance(s, ast.RollbackStmt):
            self.rollback()
            return ResultSet()
        if isinstance(s, ast.UseStmt):
            if not self.domain.catalog.info_schema().has_schema(s.db):
                raise UnknownDatabaseError(s.db)
            self.current_db = s.db
            return ResultSet()
        if isinstance(s, ast.SetStmt):
            return self._run_set(s)
        if isinstance(s, ast.ShowStmt):
            return self._run_show(s)
        if isinstance(s, ast.DescTableStmt):
            return self._desc_table(s.table)
        if isinstance(s, ast.PrepareStmt):
            self._prepared[s.name] = s.sql
            return ResultSet()
        if isinstance(s, ast.ExecuteStmt):
            sqltext = self._prepared.get(s.name)
            if sqltext is None:
                raise PlanError(f"unknown prepared statement {s.name!r}")
            vals = [self.vars.user_vars.get(n) for n in s.using]
            rss = self.execute(sqltext, vals)
            return rss[-1]
        if isinstance(s, ast.DeallocateStmt):
            self._prepared.pop(s.name, None)
            return ResultSet()
        if isinstance(s, ast.KillStmt):
            self.domain.kill(s.conn_id, s.query_only)
            return ResultSet()
        if isinstance(s, ast.AnalyzeTableStmt):
            return self._run_analyze(s)
        if isinstance(s, ast.SplitRegionStmt):
            return self._run_split(s)
        if isinstance(s, ast.AdminStmt):
            return self._run_admin(s)
        if isinstance(s, (ast.GrantStmt, ast.RevokeStmt, ast.CreateUserStmt,
                          ast.DropUserStmt, ast.SetPasswordStmt,
                          ast.FlushStmt, ast.CreateRoleStmt,
                          ast.DropRoleStmt, ast.GrantRoleStmt,
                          ast.RevokeRoleStmt, ast.SetRoleStmt,
                          ast.SetDefaultRoleStmt)):
            from . import priv

            return priv.handle(self, s)
        if isinstance(s, ast.ResourceGroupStmt):
            return self._run_resource_group(s)
        if isinstance(s, ast.AlterUserResourceGroupStmt):
            try:
                self.domain.resgroups.bind_user(s.user, s.group)
            except KeyError:
                raise ExecutorError(
                    f"unknown resource group {s.group!r}")
            self.domain.resgroups.publish()  # bindings replicate too
            return ResultSet()
        if isinstance(s, ast.LockTablesStmt):
            return self._run_lock_tables(s)
        if isinstance(s, ast.UnlockTablesStmt):
            self._release_table_locks()
            return ResultSet()
        # ---- DDL ------------------------------------------------------
        return self._run_ddl(s)

    # ------------------------------------------------------------------
    # queries
    # ------------------------------------------------------------------
    def _pctx(self, hints=None) -> PhysicalContext:
        dirty = frozenset(
            tid for (tid, _h) in (self._txn.buffer.keys() if self._txn else ())
        )
        prefer_merge = self.vars.get_bool("tidb_opt_prefer_merge_join")
        enable_ij = self.vars.get_bool("tidb_opt_enable_index_join")
        variant = (self.vars.get("tidb_index_join_variant") or "lookup").lower()
        allow_mpp = self.vars.get_bool("tidb_allow_mpp")
        if hints:
            # per-statement optimizer hints (binding USING /*+ ... */)
            if "merge_join" in hints:
                prefer_merge, enable_ij = True, False
            if "hash_join" in hints:
                # HASH_JOIN pins the root algorithm: no index/mpp reroute
                prefer_merge, enable_ij, allow_mpp = False, False, False
            if "inl_join" in hints or "index_join" in hints:
                enable_ij, prefer_merge = True, False
            if "inl_hash_join" in hints:
                enable_ij, prefer_merge, variant = True, False, "hash"
            if "no_index_join" in hints:
                enable_ij = False
        return PhysicalContext(
            storage=self.domain.storage,
            dirty_tables=dirty,
            pushdown_blacklist=frozenset(),
            enable_pushdown=self.vars.get_bool("tidb_enable_pushdown"),
            stats=self.domain.stats,
            prefer_merge_join=prefer_merge,
            enable_index_join=enable_ij,
            index_join_variant=variant,
            check_plan=self.vars.get_bool("tidb_check_plan"),
            allow_mpp=allow_mpp,
            enforce_mpp=self.vars.get_bool("tidb_enforce_mpp"),
            mpp_threshold=self.vars.get_int(
                "tidb_broadcast_join_threshold_count", 10240),
        )

    def _infoschema(self):
        """Schema for planning/execution: historical when tidb_snapshot is
        pinned (GetSnapshotInfoSchema), else current."""
        if self._snapshot_ts is not None:
            from ..store.oracle import extract_physical

            return self.domain.catalog.info_schema_at(
                extract_physical(self._snapshot_ts))
        return self.domain.catalog.info_schema()

    def _exec_ctx(self, current_read: bool = False) -> ExecContext:
        txn = self._txn if self._in_txn or self._txn is not None else None
        snap = self._snapshot_ts
        if txn is None and snap is not None:
            read_ts = snap  # historical read (tidb_snapshot)
        else:
            read_ts = self.domain.storage.current_ts() if txn is None else 0
        ctx = ExecContext(
            self.domain.storage,
            infoschema=self._infoschema(),
            sess_vars=self.vars,
            txn=txn,
            read_ts=read_ts,
        )
        ctx.current_read = current_read
        ctx.historical = snap is not None  # stats feedback skips stale reads
        ctx.killed = self._killed
        ctx.domain = self.domain  # memtable providers read live state
        self.last_exec_ctx = ctx
        return ctx

    def _exec_subplan(self, logical) -> List[tuple]:
        phys = finish_plan(logical, self._pctx())
        ctx = self._exec_ctx()
        chunks = collect_all(phys.build(ctx))
        rows: List[tuple] = []
        for c in chunks:
            rows.extend(c.to_pylist())
        return rows

    def _plan(self, stmt, params=None):
        from ..trace import span
        from . import bindinfo

        with span("plan") as sp:
            stmt, hints = bindinfo.apply_binding(self, stmt)
            key = self._plan_cache_key(stmt, params)
            if key is not None:
                hit = self._plan_cache.get(key)
                if hit is not None:
                    from ..metrics import REGISTRY

                    REGISTRY.inc("plan_cache_hits_total")
                    self._plan_cache.move_to_end(key)
                    sp.set(plan_cache="hit")
                    return hit
            phys = plan_statement(
                stmt, self._infoschema(), self.current_db,
                self._pctx(hints), exec_subplan=self._exec_subplan,
                param_values=params,
            )
            if key is not None:
                from ..metrics import REGISTRY

                REGISTRY.inc("plan_cache_misses_total")
                self._plan_cache[key] = phys
                cap = max(self.vars.get_int("tidb_plan_cache_size", 128), 1)
                while len(self._plan_cache) > cap:
                    self._plan_cache.popitem(last=False)
                sp.set(plan_cache="miss")
            return phys

    def _plan_cache_key(self, stmt, params):
        """Cache key for repeated statements (planner/core/cache.go analog:
        keyed on text + schema version + PER-TABLE data versions + planner
        vars) — DML against unrelated tables leaves cached plans valid.
        None disables caching: txn writes change pushdown eligibility, and
        parameterized plans bake constant ranges."""
        if params is not None or self._txn is not None \
                or self._snapshot_ts is not None:
            return None  # historical reads: never cache
        if not isinstance(stmt, (ast.SelectStmt, ast.UnionStmt)):
            return None
        sql = getattr(stmt, "_sql_text", None)
        if sql is None:
            return None
        from .priv import _walk_tables

        refs: list = []
        _walk_tables(stmt, refs)
        isc = self.domain.catalog.info_schema()
        # shape-bucketed per-table version (serving): key plans on the
        # table's ROW-COUNT BUCKET + base version instead of the raw
        # committed-write counter — steady-state DML that stays within a
        # table's pow2 size class keeps its cached plans valid (plans
        # read data at execution time; only stats/schema/bindings shifts,
        # all keyed separately, change what the planner would pick)
        use_buckets = self.vars.get_bool("tidb_tpu_shape_buckets")
        from ..serving import shape_bucket

        vers = []
        seen = set()
        for tn in refs:
            db = (tn.db or self.current_db).lower()
            name = tn.name.lower()
            if (db, name) in seen:
                continue
            seen.add((db, name))
            if db in ("information_schema", "performance_schema"):
                return None  # memtables: live state, never cache
            if not isc.has_table(db, name):
                return None
            t = isc.table(db, name)
            if t.is_view:
                # views hide their base tables from the AST walk: fall
                # back to the global version (always-correct, coarser)
                vers.append(("__global__",
                             self.domain.storage.data_version()))
                continue
            for pid in (t.physical_ids() + [t.id]
                        if t.partition_info else [t.id]):
                st = self.domain.stats.get(pid)
                stats_ver = (st.version, st.build_time) if st else None
                if pid == t.id and t.partition_info:
                    vers.append((pid, 0, stats_ver))
                    continue
                try:
                    store = self.domain.storage.table(pid)
                except KVError:
                    return None
                if use_buckets:
                    vers.append((pid, store.base_version,
                                 shape_bucket(store.base_rows
                                              + len(store.delta) + 1),
                                 stats_ver))
                else:
                    vers.append((pid, store.mutations, stats_ver))
        return (
            sql, self.current_db,
            self.domain.catalog.schema_version,
            tuple(vers),
            # learned-selectivity generation: feedback that materially
            # moved an estimate must re-plan cached statements
            self.domain.stats.feedback.epoch,
            # layout-decision generation (tidb_tpu/layout): a re-tuned
            # column layout shifts scan cost (cold decode) and program
            # shapes, so cached plans must not outlive the decision
            _layout_epoch(),
            getattr(self.domain, "bindings_version", 0),
            getattr(self, "_bindings_version", 0),
            self.vars.get_bool("tidb_enable_pushdown"),
            self.vars.get_bool("tidb_opt_prefer_merge_join"),
            self.vars.get_bool("tidb_opt_enable_index_join"),
            self.vars.get("tidb_index_join_variant"),
            self.vars.get_bool("tidb_allow_mpp"),
            self.vars.get_bool("tidb_enforce_mpp"),
            self.vars.get_int("tidb_broadcast_join_threshold_count",
                              10240),
        )

    def _run_query(self, stmt, params=None) -> ResultSet:
        for_update = getattr(stmt, "for_update", False)
        if for_update:
            self._select_for_update_lock(stmt, params)
        phys = self._plan(stmt, params)
        self.last_plan = phys
        sql = getattr(stmt, "_sql_text", None)
        if sql is not None:
            from . import bindinfo

            bindinfo.maybe_capture(self, sql, stmt, phys)
        ctx = self._exec_ctx(current_read=for_update)
        exe = phys.build(ctx)
        chunks = collect_all(exe)
        headers = phys.schema.headers() if len(phys.schema) else []
        rows: List[tuple] = []
        fts = phys.schema.ftypes()
        for c in chunks:
            for r in c.to_pylist():
                rows.append(_format_row(r, fts))
        return ResultSet(headers=headers, rows=rows, is_query=True,
                         warnings=self._warnings + list(ctx.warnings),
                         ftypes=fts)

    def _select_for_update_lock(self, stmt, params=None):
        """SELECT ... FOR UPDATE: pessimistically lock the matching rows
        before the read runs (executor/adapter.go:338-372 SelectLockExec
        path).  Scope: single-table FROM (the reference locks each table's
        handles; joins fall back to snapshot reads with a warning)."""
        if not isinstance(stmt, ast.SelectStmt) or stmt.from_clause is None:
            return
        if not isinstance(stmt.from_clause, ast.TableName):
            self._warnings.append(
                "FOR UPDATE on multi-table queries reads at snapshot "
                "(row locks not taken)")
            return
        t = self.domain.catalog.info_schema().table(
            stmt.from_clause.db or self.current_db, stmt.from_clause.name)
        if t.is_view:
            return
        if self._autocommit():
            # autocommit FOR UPDATE: locks would release at statement end
            # anyway (MySQL semantics) — read at snapshot, take none
            return
        # reuse the DELETE condition builder: conditions over full-row
        # offsets, then the handle scan locates matching (pid, handle)s.
        # Shapes the row-locator cannot express (subqueries in WHERE, ...)
        # degrade to a snapshot read with a warning rather than erroring.
        fake = ast.DeleteStmt(stmt.from_clause, stmt.where)
        pb = PlanBuilder(self.domain.catalog.info_schema(), self.current_db,
                         param_values=params)
        try:
            plan = pb.build_delete(fake)
        except TiDBTPUError as e:
            self._warnings.append(
                f"FOR UPDATE reads at snapshot (row locks not taken: {e})")
            return
        from ..planner.physical import _dml_readers

        txn = self._begin_txn()
        # FOR UPDATE is a current read: take the lock horizon at statement
        # start so rows committed after txn start are seen and locked
        txn.for_update_ts = max(txn.for_update_ts,
                                self.domain.storage.current_ts())
        ctx = self._exec_ctx(current_read=True)
        keys = []
        for pid, reader in _dml_readers(ctx, plan.table, plan.conditions,
                                        -1):
            reader.open()
            try:
                while True:
                    c = reader.next()
                    if c is None:
                        break
                    for h in c.col(0).data:
                        keys.append((pid, int(h)))
            finally:
                reader.close()
        if keys:
            txn.lock_keys(*keys)

    def _run_dml(self, stmt, params=None) -> ResultSet:
        retries = max(self.vars.get_int("tidb_retry_limit", 10), 0)
        attempt = 0
        while True:
            attempt += 1
            auto = self._autocommit() and self._txn is None
            txn = self._begin_txn()
            ctx = self._exec_ctx(current_read=True)
            try:
                phys = self._plan(stmt, params)
                self.last_plan = phys
                collect_all(phys.build(ctx))
                if auto:
                    self.commit()  # compaction/auto-analyze hooks run there
                return ResultSet(affected_rows=ctx.affected_rows,
                                 last_insert_id=ctx.last_insert_id,
                                 warnings=list(ctx.warnings))
            except TxnConflictError:
                # optimistic retry (session.go:635) — autocommit only
                self.rollback()
                if not auto or attempt > retries or \
                        self.vars.get_bool("tidb_disable_txn_auto_retry"):
                    raise
            except Exception:
                if auto:
                    self.rollback()
                raise

    def _run_explain(self, s: ast.ExplainStmt) -> ResultSet:
        if isinstance(s.target, (ast.SelectStmt, ast.UnionStmt,
                                 ast.InsertStmt, ast.UpdateStmt,
                                 ast.DeleteStmt)):
            outer = getattr(s, "_sql_text", None)
            if outer is not None:
                # bindings match on the inner statement's digest
                s.target._sql_text = outer
            phys = self._plan(s.target)
        else:
            raise PlanError("EXPLAIN supports SELECT/DML only")
        if s.analyze:
            ctx = self._exec_ctx()
            auto = self._autocommit() and self._txn is None and isinstance(
                s.target, (ast.InsertStmt, ast.UpdateStmt, ast.DeleteStmt)
            )
            if auto:
                ctx.txn = self._begin_txn()
            collect_all(phys.build(ctx))
            if auto:
                self.commit()
            rows = []
            op_samples = []
            for nm, est, task, info in phys.explain_tree():
                st = ctx.stats.get(_plan_id_of(nm))
                extra = ""
                if st:
                    extra = (f"rows:{st.rows} loops:{st.loops} "
                             f"time:{st.time_ns/1e6:.2f}ms")
                    if st.engine:
                        extra += f" engine:{st.engine}"
                    op_id = nm.lstrip(" ").lstrip("└─")
                    depth = (len(nm) - len(nm.lstrip(" "))) // 2
                    op_samples.append((depth, op_id, st.time_ns))
                rows.append((nm, est, task, info, extra))
            # operator sampling (ISSUE 18): EXPLAIN ANALYZE runs feed
            # their per-operator self-times into the continuous
            # profiler, so flame frames carry plan operator ids
            from ..trace.profiler import PROFILER

            PROFILER.fold_explain(op_samples)
            # per-statement HBM high-water attribution (ISSUE 13): the
            # dispatch sites stamp resident device bytes on the execute
            # spans; surface the peak on the root operator's line
            from ..trace import current_trace

            ltr = current_trace()
            if ltr is not None and rows:
                tot = ltr.phase_totals()
                peak = tot.get("hbm_peak_bytes", 0)
                if peak:
                    nm, est, task, info, extra = rows[0]
                    extra = (extra + " " if extra else "") \
                        + f"hbm_peak:{peak}"
                    rows[0] = (nm, est, task, info, extra)
                # chunked-dispatch visibility (ISSUE 17): how many
                # device launches the statement's fragments split into
                nchunks = tot.get("chunks", 0)
                if nchunks:
                    nm, est, task, info, extra = rows[0]
                    extra = (extra + " " if extra else "") \
                        + f"chunks: {nchunks}"
                    rows[0] = (nm, est, task, info, extra)
            return ResultSet(
                headers=["id", "estRows", "task", "info", "execution info"],
                rows=rows, is_query=True)
        rows = list(phys.explain_tree())
        return ResultSet(headers=["id", "estRows", "task", "info"], rows=rows,
                         is_query=True)

    def _run_trace(self, s: ast.TraceStmt) -> ResultSet:
        """TRACE [FORMAT='row'|'json'] <stmt> (executor/trace.go): run the
        target under the span recorder and return its span tree.  When the
        session already traces (slow log enabled) the target's spans land
        in the active trace; otherwise TRACE forces one of its own."""
        import json as _json

        from ..trace import current_trace, finish_trace, start_trace

        tr = current_trace()
        owned = False
        if tr is None:
            tr, token = start_trace(getattr(self, "stmt_sql", "") or "trace",
                                    self.conn_id)
            owned = True
        try:
            self._execute_stmt(s.target)
        finally:
            if owned:
                finish_trace(tr, token)
        self.last_trace = tr
        fmt = getattr(s, "fmt", "row")
        if fmt == "json":
            return ResultSet(
                headers=["operation"],
                rows=[(_json.dumps(tr.to_dict(), sort_keys=True),)],
                is_query=True)
        return ResultSet(headers=["operation", "startTS", "duration"],
                         rows=tr.rows(), is_query=True)

    # ------------------------------------------------------------------
    # SET / SHOW / DESC
    # ------------------------------------------------------------------
    def _run_set(self, s: ast.SetStmt) -> ResultSet:
        from ..planner.expr_build import ExprBuilder
        from ..planner.columns import Schema

        eb = ExprBuilder(Schema([]), None, None, [], None)
        for name, is_global, vexpr in s.assignments:
            if isinstance(vexpr, ast.Default):
                value = SYSVAR_DEFAULTS.get(name.lower(), ("",))[0]
            else:
                from ..planner.build import _eval_const

                value = _eval_const(eb.build(vexpr))
            if name.lower() == "tidb_snapshot":
                self._set_snapshot(value)
                continue
            if name.lower() == "tidb_profiling":
                self._set_profiling(value)
                continue
            if not is_global and not self.vars.known(name) \
                    and name.lower() not in SYSVAR_DEFAULTS:
                # unknown non-global names are user variables (@x); the
                # lexer strips the @ marker
                self.vars.user_vars[name] = value
            elif is_global:
                self.vars.set_global(name, value)
            else:
                self.vars.set_session(name, value)
            from .. import serving

            if name.lower() in serving._SYSVARS:
                # serving knobs configure a process-wide resource (the
                # batcher / bucket policy), mirroring max_connections
                serving.refresh_from_vars(self.vars)
            if name.lower() == "tidb_tpu_dispatch_chunk_ms":
                # the dispatchers read a process knob (like the serving
                # sysvars): GLOBAL or SESSION set both retarget it —
                # chunking guards a shared device, not a session
                from ..copr.chunking import set_dispatch_chunk_ms

                try:
                    set_dispatch_chunk_ms(float(value))
                except (TypeError, ValueError):
                    pass
        return ResultSet()

    def _snapshot_write_guard(self, s):
        """TiDB rejects EVERY write statement under tidb_snapshot — DML,
        DDL, and EXPLAIN ANALYZE of DML (which executes)."""
        wr = (ast.InsertStmt, ast.UpdateStmt, ast.DeleteStmt,
              ast.LoadDataStmt, ast.CreateTableStmt, ast.DropTableStmt,
              ast.TruncateTableStmt, ast.AlterTableStmt,
              ast.RenameTableStmt, ast.CreateIndexStmt, ast.DropIndexStmt,
              ast.CreateDatabaseStmt, ast.DropDatabaseStmt,
              ast.CreateViewStmt, ast.AnalyzeTableStmt,
              ast.RecoverTableStmt, ast.DropStatsStmt,
              ast.RepairTableStmt)
        target = s.target if isinstance(s, (ast.ExplainStmt,
                                            ast.TraceStmt)) else s
        analyze = getattr(s, "analyze", True)  # plain EXPLAIN is read-only
        if isinstance(target, wr) and (target is s or analyze):
            raise ExecutorError(
                "can not execute write statement when 'tidb_snapshot' "
                "is set")

    def _set_snapshot(self, value):
        """SET tidb_snapshot: pin autocommit reads to a historical TSO
        (session.go setSnapshotTS / GetSnapshotInfoSchema role).  Accepts a
        raw TSO, a unix-seconds number, or 'YYYY-MM-DD HH:MM:SS'; bounded
        below by the GC safepoint.  Empty string clears it.

        Bounds beyond GC: column-layout DDL (ADD/DROP/MODIFY COLUMN)
        rebuilds the store eagerly (catalog._rebuild_storage), so data time
        travel does not cross such a DDL — reads older than the rebuild
        raise 'snapshot is older than the compaction horizon'.  While a
        snapshot is pinned, GC and background compaction hold their floor
        at the pinned TSO (storage.pin_read), so DML-only history
        time-travels exactly."""
        from ..store.oracle import compose_ts

        if value in ("", None, 0):
            self._snapshot_ts = None
            self._unpin_snapshot()
            self.vars.set_session("tidb_snapshot", "")
            return
        if self._txn is not None or self._in_txn:
            raise PlanError(
                "can not set tidb_snapshot during a transaction")
        try:
            if isinstance(value, str):
                from ..types.values import parse_datetime

                ts = compose_ts(parse_datetime(value) // 1000, 0)
            else:
                v = int(value)
                # heuristic matching TiDB: big values are TSOs, small
                # ones unix seconds
                ts = v if v > (1 << 40) else compose_ts(v * 1000, 0)
        except (ValueError, TypeError) as e:
            raise PlanError(f"invalid tidb_snapshot value {value!r}: {e}")
        floor = self.domain.maintenance.last_safepoint
        if floor and ts < floor:
            raise PlanError(
                "snapshot is older than GC safe point")
        self._snapshot_ts = ts
        # hold GC + compaction at this TSO for the life of the pin:
        # without it background compaction advances base_ts and the
        # historical read silently turns empty (ADVICE r4 #1)
        self._unpin_snapshot()
        self._snapshot_pin = self.domain.storage.pin_read(ts)
        self.vars.set_session("tidb_snapshot", str(ts))

    def _set_profiling(self, value):
        """SET tidb_profiling = 1|0: toggle the domain cProfile collector
        surfaced through information_schema.tidb_profile (util/profile's
        pprof table role; covers the session thread's planner/executor
        work — distsql worker threads run outside the collector)."""
        on = str(value).strip().lower() in ("1", "true", "on")
        dom = self.domain
        if on and getattr(dom, "profiler", None) is None:
            import cProfile

            dom.profiler = cProfile.Profile()
            dom.profiler.enable()
        elif not on and getattr(dom, "profiler", None) is not None:
            dom.profiler.disable()
            dom.profiler = None
        # the collector is domain-wide: mirror its ACTUAL state where
        # operators look (SHOW VARIABLES / cluster_config)
        dom.global_vars["tidb_profiling"] = "1" if on else "0"
        self.vars.set_session("tidb_profiling", "1" if on else "0")

    def _unpin_snapshot(self):
        if self._snapshot_pin is not None:
            self.domain.storage.unpin_read(self._snapshot_pin)
            self._snapshot_pin = None

    def close(self):
        """Connection teardown: release snapshot pins and roll back any
        open transaction so GC/compaction are not held forever."""
        self._unpin_snapshot()
        try:
            if self._txn is not None:
                self.rollback()
        except Exception:
            pass

    def _run_show(self, s: ast.ShowStmt) -> ResultSet:
        import fnmatch

        kind = s.kind
        isc = self._infoschema()  # snapshot-aware (tidb_snapshot)

        def like_filter(names):
            if s.like:
                pat = s.like.replace("%", "*").replace("_", "?")
                return [n for n in names if fnmatch.fnmatch(n.lower(),
                                                            pat.lower())]
            return names

        if kind == "databases":
            names = like_filter(isc.schema_names())
            return ResultSet(["Database"], [(n,) for n in names],
                             is_query=True)
        if kind == "tables":
            db = s.db or self.current_db
            names = like_filter([t.name for t in isc.tables(db)])
            return ResultSet([f"Tables_in_{db}"], [(n,) for n in names],
                             is_query=True)
        if kind in ("columns", "full_columns"):
            return self._desc_table(ast.TableName(s.target, s.db))
        if kind == "create_table":
            db = s.db or self.current_db
            t = isc.table(db, s.target)
            return ResultSet(["Table", "Create Table"],
                             [(t.name, _show_create(t))], is_query=True)
        if kind == "index":
            db = s.db or self.current_db
            t = isc.table(db, s.target)
            rows = []
            for ix in t.indexes:
                for seq, col in enumerate(ix.columns):
                    rows.append((t.name, 0 if ix.unique else 1, ix.name,
                                 seq + 1, col))
            return ResultSet(
                ["Table", "Non_unique", "Key_name", "Seq_in_index",
                 "Column_name"], rows, is_query=True)
        if kind == "grants":
            user = s.target or self.user
            rows = [(g,) for g in self.domain.priv.show_grants(user)]
            from .priv import _norm_user

            return ResultSet([f"Grants for {_norm_user(user)}"], rows,
                             is_query=True)
        if kind == "variables":
            allv = self.vars.all_vars()
            names = like_filter(sorted(allv))
            return ResultSet(["Variable_name", "Value"],
                             [(n, allv[n]) for n in names], is_query=True)
        if kind == "warnings":
            return ResultSet(["Level", "Code", "Message"],
                             [("Warning", 0, w) for w in self._warnings],
                             is_query=True)
        if kind == "processlist":
            # single source of truth: the information_schema provider
            from ..infoschema_tables import MEMTABLES

            cols, provider = MEMTABLES["processlist"]
            rows = provider(self.domain, isc)
            return ResultSet([c[0].title() for c in cols], rows,
                             is_query=True)
        if kind in ("stats_meta", "stats_histograms", "stats_buckets"):
            return self._show_stats(kind)
        if kind == "stats_healthy":
            # health = 100 * (1 - modified/count) (handle.go Healthy):
            # modified counts MVCC versions committed AFTER the stats were
            # built (deletes/updates mutate chains in place, so chain
            # lengths alone can't tell old rows from new modifications)
            from ..store.oracle import extract_physical

            rows = []
            for dbn in isc.schema_names():
                for t in isc.tables(dbn):
                    if t.is_view:
                        continue
                    st = self.domain.stats.get(t.id)
                    if st is None:
                        continue
                    build_ms = int((st.build_time or 0) * 1000)
                    modified = 0
                    for pid in t.physical_ids():
                        try:
                            store = self.domain.storage.table(pid)
                        except KVError:
                            continue
                        for chain in store.delta.values():
                            for v in chain:
                                if extract_physical(
                                        v.commit_ts) > build_ms:
                                    modified += 1
                    health = max(0, 100 - int(
                        100 * modified / max(st.row_count, 1)))
                    rows.append((dbn, t.name, "", health))
            return ResultSet(
                ["Db_name", "Table_name", "Partition_name", "Healthy"],
                rows, is_query=True)
        if kind == "analyze_status":
            db_of = {}
            for dbn in isc.schema_names():
                for t in isc.tables(dbn):
                    db_of[t.id] = dbn
            rows = []
            for tid, st in sorted(
                    self.domain.stats.cache_snapshot().items()):
                owner = isc.table_by_id(tid)
                if owner is None:
                    continue
                rows.append((
                    db_of.get(owner.id, ""), owner.name,
                    "" if tid == owner.id else f"pid {tid}",
                    "analyze columns", st.row_count,
                    time.strftime("%Y-%m-%d %H:%M:%S",
                                  time.localtime(st.build_time or 0)),
                    "finished"))
            return ResultSet(
                ["Table_schema", "Table_name", "Partition", "Job_info",
                 "Processed_rows", "Start_time", "State"], rows,
                is_query=True)
        if kind == "regions":
            db = s.db or self.current_db
            t = isc.table(db, s.target)
            rows = []
            for pid in t.physical_ids():
                for r in self.domain.storage.regions.regions_of(pid):
                    rows.append((r.region_id, t.name, r.start,
                                 "inf" if r.end >= (1 << 62) else r.end,
                                 r.epoch, r.leader_store))
            return ResultSet(
                ["Region_id", "Table", "Start", "End", "Epoch", "Leader"],
                rows, is_query=True)
        if kind == "stats":
            rows = []
            for db in isc.schema_names():
                for t in isc.tables(db):
                    if t.is_view:
                        continue
                    base = delta = nbytes = 0
                    for pid in t.physical_ids():
                        store = self.domain.storage.table(pid)
                        base += store.base_rows
                        delta += len(store.delta)
                        nbytes += store.nbytes()
                    rows.append((db, t.name, base, delta, nbytes))
            return ResultSet(
                ["Db_name", "Table_name", "Base_rows", "Delta_rows", "Bytes"],
                rows, is_query=True)
        raise PlanError(f"SHOW {kind} not supported")

    def _show_stats(self, kind: str) -> ResultSet:
        """SHOW STATS_META / STATS_HISTOGRAMS / STATS_BUCKETS over the
        stats cache (statistics/handle + executor/show_stats.go)."""
        import time as _time

        isc = self.domain.catalog.info_schema()
        stats = self.domain.stats
        meta_rows, hist_rows, bucket_rows = [], [], []
        for dbn in isc.schema_names():
            for t in isc.tables(dbn):
                if t.is_view:
                    continue
                targets = [("", t.id)]
                if t.partition_info is not None:
                    targets += [(p.name, p.id)
                                for p in t.partition_info.defs]
                for part_name, tid in targets:
                    st = stats.get(tid)
                    if st is None:
                        continue
                    mtime = _time.strftime(
                        "%Y-%m-%d %H:%M:%S",
                        _time.localtime(st.build_time or 0))
                    meta_rows.append((dbn, t.name, part_name, mtime,
                                      st.modify_count, st.row_count))
                    for ci, cs in sorted(st.columns.items()):
                        if ci >= len(t.columns):
                            continue
                        cname = t.columns[ci].name
                        hist_rows.append((
                            dbn, t.name, part_name, cname, 0,
                            mtime, cs.ndv, cs.null_count,
                            len(cs.hist.buckets)))
                        for bi, b in enumerate(cs.hist.buckets):
                            bucket_rows.append((
                                dbn, t.name, part_name, cname, bi,
                                b.count, b.repeat, b.lower, b.upper))
        if kind == "stats_meta":
            return ResultSet(
                ["Db_name", "Table_name", "Partition_name", "Update_time",
                 "Modify_count", "Row_count"], meta_rows, is_query=True)
        if kind == "stats_histograms":
            return ResultSet(
                ["Db_name", "Table_name", "Partition_name", "Column_name",
                 "Is_index", "Update_time", "Distinct_count", "Null_count",
                 "Buckets"], hist_rows, is_query=True)
        return ResultSet(
            ["Db_name", "Table_name", "Partition_name", "Column_name",
             "Bucket_id", "Count", "Repeats", "Lower_Bound", "Upper_Bound"],
            bucket_rows, is_query=True)

    def _desc_table(self, tn: ast.TableName) -> ResultSet:
        t = self.domain.catalog.info_schema().table(
            tn.db or self.current_db, tn.name
        )
        rows = []
        for c in t.public_columns():
            key = ""
            if c.primary_key:
                key = "PRI"
            elif any(ix.unique and ix.columns == [c.name] for ix in t.indexes):
                key = "UNI"
            elif any(c.name in ix.columns for ix in t.indexes):
                key = "MUL"
            rows.append((
                c.name, c.ftype.sql_name().lower(),
                "YES" if c.ftype.nullable else "NO", key,
                c.default if c.has_default else None,
                "auto_increment" if c.auto_increment else "",
            ))
        return ResultSet(["Field", "Type", "Null", "Key", "Default", "Extra"],
                         rows, is_query=True)

    # ------------------------------------------------------------------
    # ANALYZE / ADMIN / SPLIT
    # ------------------------------------------------------------------
    def _run_analyze(self, s: ast.AnalyzeTableStmt) -> ResultSet:
        for tn in s.tables:
            t = self.domain.catalog.info_schema().table(
                tn.db or self.current_db, tn.name
            )
            for pid in t.physical_ids():
                store = self.domain.storage.table(pid)
                for ci in range(store.n_cols):
                    store.column_stats(ci)  # warm min/max (device engine)
            self.domain.stats.analyze(t)
        return ResultSet()

    def _run_split(self, s: ast.SplitRegionStmt) -> ResultSet:
        t = self.domain.catalog.info_schema().table(
            s.table.db or self.current_db, s.table.name
        )
        n = 0
        for pid in t.physical_ids():
            store = self.domain.storage.table(pid)
            self.domain.storage.regions.split_even(
                pid, s.num, max(store.base_rows, store.next_handle)
            )
            n += len(self.domain.storage.regions.regions_of(pid))
        return ResultSet(["TOTAL_SPLIT_REGION"], [(n,)], is_query=True)

    def _run_admin(self, s: ast.AdminStmt) -> ResultSet:
        if s.kind in ("show_ddl", "show_ddl_jobs"):
            rows = [
                (j.id, j.typ, j.db, j.table, j.state, j.schema_version,
                 ",".join(j.states_walked))
                for j in reversed(self.domain.catalog.jobs[-20:])
            ]
            return ResultSet(
                ["Job_id", "Type", "Db", "Table", "State", "Schema_ver",
                 "States"], rows, is_query=True)
        if s.kind == "check_table":
            for tn in s.tables:
                t = self.domain.catalog.info_schema().table(
                    tn.db or self.current_db, tn.name
                )
                self._admin_check_table(t)
            return ResultSet()
        if s.kind in ("recover_index", "cleanup_index"):
            tn = s.tables[0]
            t = self.domain.catalog.info_schema().table(
                tn.db or self.current_db, tn.name)
            return self._admin_repair_index(t, s.index, s.kind)
        if s.kind == "checksum_table":
            rows = []
            for tn in s.tables:
                db = tn.db or self.current_db
                t = self.domain.catalog.info_schema().table(db, tn.name)
                rows.append((db, tn.name) + self._checksum_table(t))
            return ResultSet(
                ["Db_name", "Table_name", "Checksum_crc64_xor",
                 "Total_kvs", "Total_bytes"], rows, is_query=True)
        if s.kind == "show_next_row_id":
            tn = s.tables[0]
            db = tn.db or self.current_db
            t = self.domain.catalog.info_schema().table(db, tn.name)
            nid = max(self.domain.storage.table(pid).next_handle
                      for pid in t.physical_ids())
            return ResultSet(
                ["DB_NAME", "TABLE_NAME", "COLUMN_NAME", "NEXT_GLOBAL_ROW_ID"],
                [(db, tn.name, "_tidb_rowid", max(nid, t.auto_inc_id))],
                is_query=True)
        raise PlanError(f"ADMIN {s.kind} not supported")

    def _checksum_table(self, t: TableInfo):
        """(crc64_xor, total_kvs, total_bytes) over the VISIBLE rows of
        every physical store (the reference's checksum cop request,
        kv/kv.go:206-211, computed in-process).

        Columnar and streaming: a running crc per column over its visible
        bytes plus validity, fed 64K rows at a time so memory stays
        bounded at bench scale; the committed delta overlay rides along
        as a per-column tail.  Per-store, the (index, data crc, validity
        crc) records are themselves crc'd — crc32 is linear over GF(2),
        so XOR-combining per-column crcs (seeded or not) cancels under
        equal-length column swaps; hashing the record stream binds each
        crc to its column ordinal non-linearly.  Object values are
        length-prefixed (a bare separator would make ['a\\x1f','b'] and
        ['a','\\x1fb'] collide).  No per-row Python loop — the old repr()
        row walk took minutes at bench scale (round-5 ADVICE) and is the
        purity lint's canonical row-loop specimen (tests/test_lint.py)."""
        import struct
        import zlib

        from ..chunk.column import Column

        def col_bytes(col):
            if col.data.dtype == object:
                enc = [str(x).encode() for x in col.data]
                return b"".join(len(s).to_bytes(4, "little") + s
                                for s in enc)
            return np.ascontiguousarray(col.data).tobytes()

        ts = self.domain.storage.current_ts()
        crc = 0
        kvs = 0
        nbytes = 0
        step = 1 << 16
        for pid in t.physical_ids():
            store = self.domain.storage.table(pid)
            deleted, inserted = store.delta_overlay(ts, 0, 1 << 62)
            n = store.base_rows
            if not n and not inserted:
                continue
            keep = np.ones(n, dtype=np.bool_)
            if deleted:
                keep[np.fromiter(deleted, dtype=np.int64,
                                 count=len(deleted))] = False
            ncols = store.n_cols
            col_crcs = [0] * ncols
            val_crcs = [0] * ncols
            store_kvs = 0
            for lo in range(0, n, step):
                hi = min(lo + step, n)
                chunk = store.base_chunk(range(ncols), lo, hi)
                kslice = keep[lo:hi]
                vis = chunk if kslice.all() else chunk.filter(kslice)
                store_kvs += vis.num_rows
                for ci in range(ncols):
                    col = vis.col(ci)
                    raw = col_bytes(col)
                    col_crcs[ci] = zlib.crc32(raw, col_crcs[ci])
                    val_crcs[ci] = zlib.crc32(col.validity().tobytes(),
                                              val_crcs[ci])
                    nbytes += len(raw)
            if inserted:
                rows = [inserted[h] for h in sorted(inserted)]
                store_kvs += len(rows)
                ftypes = store.ftypes()
                for ci in range(ncols):
                    tail = Column.from_values(
                        ftypes[ci], [r[ci] for r in rows])
                    raw = col_bytes(tail)
                    col_crcs[ci] = zlib.crc32(raw, col_crcs[ci])
                    val_crcs[ci] = zlib.crc32(tail.validity().tobytes(),
                                              val_crcs[ci])
                    nbytes += len(raw)
            # XOR across stores keeps the reference's partition/row-order
            # invariance; within a store the record crc is positional.  A
            # store whose VISIBLE row count is zero must contribute 0 (not
            # the crc of all-zero column records), or the checksum of
            # identical visible content would change with compaction state
            # (base rows all deleted vs. physically compacted away).
            kvs += store_kvs
            if store_kvs:
                crc ^= zlib.crc32(b"".join(
                    struct.pack("<III", ci, col_crcs[ci], val_crcs[ci])
                    for ci in range(ncols)))
        return crc, kvs, nbytes

    def _admin_repair_index(self, t: TableInfo, index_name: str,
                            kind: str) -> ResultSet:
        """ADMIN RECOVER INDEX / CLEANUP INDEX (util/admin.go:281-312):
        indexes here are DERIVED sorted artifacts, so both repairs
        re-derive the artifact from the base rows — RECOVER reports how
        many entries the rebuilt index carries (ADDED_COUNT/SCAN_COUNT),
        CLEANUP how many bogus entries the rebuild discarded."""
        ix = next((x for x in t.indexes
                   if x.name.lower() == index_name.lower()), None)
        if ix is None:
            raise PlanError(f"index {index_name!r} does not exist on "
                            f"{t.name}")
        added = scanned = removed = 0
        for pid in t.physical_ids():
            store = self.domain.storage.table(pid)
            offs = tuple(t.col_offsets(ix.columns))
            old = store.indexes.peek(offs)
            old_n = len(old.handles) if old is not None else None
            store.indexes.invalidate(offs)
            rebuilt = store.indexes.get(store, offs)  # re-derive from rows
            added += len(rebuilt.handles)
            scanned += store.base_rows
            if old_n is not None and old_n > len(rebuilt.handles):
                removed += old_n - len(rebuilt.handles)
        if kind == "recover_index":
            return ResultSet(["ADDED_COUNT", "SCAN_COUNT"],
                             [(added, scanned)], is_query=True)
        return ResultSet(["REMOVED_COUNT"], [(removed,)], is_query=True)

    def _admin_check_table(self, t: TableInfo):
        """ADMIN CHECK TABLE (executor/admin.go CheckTable role), adapted
        to derived indexes.  Two real checks per physical store:

        1. Every EXISTING sorted-index artifact (cached or backfilled)
           must agree with the CURRENT base rows — row counts match and a
           sampled handle-gather returns the index's key values.  Freshly
           derivable indexes are skipped: rebuilding one here and comparing
           it against its own source would be tautological.
        2. Unique constraints verify over the FULL visible table — base
           minus deletions plus committed delta — via the catalog's
           unique scanner (the same code the online-DDL recheck trusts).
        """
        from ..errors import ExecutorError

        cat = self.domain.catalog
        for pid in t.physical_ids():
            store = self.domain.storage.table(pid)
            for ix in t.indexes:
                if ix.state != STATE_PUBLIC:
                    continue
                offs = tuple(t.col_offsets(ix.columns))
                idx = store.indexes.peek(offs)
                if idx is not None and idx.base_version ==                         store.base_version:
                    self._check_index_artifact(t, store, ix, offs, idx)
                if ix.unique:
                    try:
                        cat._check_unique(t, list(ix.columns), ix.name,
                                          store_id=pid)
                    except KVError as e:
                        raise ExecutorError(
                            f"admin check table {t.name}: {e}")

    def _check_index_artifact(self, t, store, ix, offs, idx):
        """Sampled artifact-vs-base verification using sparse gathers."""
        from ..errors import ExecutorError

        n = store.base_rows
        expect = n
        if n:
            # non-NULL count per index columns from validity only
            chunk = store.base_chunk(list(offs), 0, n,
                                     decode_strings=False)
            valid = np.ones(n, dtype=np.bool_)
            for i in range(len(offs)):
                valid &= chunk.col(i).validity()
            expect = int(valid.sum())
        else:
            expect = 0
        if len(idx.handles) != expect:
            raise ExecutorError(
                f"admin check table {t.name}: index {ix.name!r} covers "
                f"{len(idx.handles)} rows, table has {expect} indexable "
                f"rows")
        hs = idx.handles
        if not len(hs):
            return
        if len(hs) > 65536:
            pick = np.linspace(0, len(hs) - 1, 4096, dtype=np.int64)
        else:
            pick = np.arange(len(hs), dtype=np.int64)
        got = store.gather_chunk(list(offs), hs[pick],
                                 decode_strings=False)
        for j in range(len(offs)):
            if not np.array_equal(np.asarray(idx.cols[j])[pick],
                                  got.col(j).data):
                raise ExecutorError(
                    f"admin check table {t.name}: index {ix.name!r} "
                    f"column {ix.columns[j]!r} disagrees with table data")

    # ------------------------------------------------------------------
    # DDL
    # ------------------------------------------------------------------
    # ------------------------------------------------------------------
    # LOCK TABLES (server-level table locks; MySQL semantics: a session
    # holding any table locks may only touch locked tables, writes need a
    # WRITE lock, foreign WRITE locks exclude everyone else)
    # ------------------------------------------------------------------
    _LOCK_EXEMPT_DBS = ("information_schema", "performance_schema",
                        "mysql")  # MySQL exempts these from LOCK TABLES

    def _run_resource_group(self, s) -> ResultSet:
        """CREATE/ALTER/DROP RESOURCE GROUP against the domain's
        resource-control plane (lifecycle/resgroup.py)."""
        reg = self.domain.resgroups
        try:
            if s.kind == "create":
                reg.create(s.name, ru_per_sec=s.ru_per_sec or 0,
                           burstable=bool(s.burstable),
                           query_limit_ms=s.query_limit_ms or 0,
                           priority=s.priority or 1,
                           if_not_exists=s.if_not_exists)
            elif s.kind == "alter":
                reg.alter(s.name, ru_per_sec=s.ru_per_sec,
                          burstable=s.burstable,
                          query_limit_ms=s.query_limit_ms,
                          priority=s.priority)
            else:
                reg.drop(s.name, if_exists=s.if_exists)
        except KeyError:
            raise ExecutorError(f"unknown resource group {s.name!r}")
        except ValueError as e:
            raise ExecutorError(str(e))
        # fleet replication (ISSUE 18): a registry attached to the
        # coord plane pushes the new definition set into the shared
        # store so every member's next resolve() adopts it
        reg.publish()
        return ResultSet()

    def _run_lock_tables(self, s) -> ResultSet:
        isc = self.domain.catalog.info_schema()
        wanted = []
        for tn, mode in s.items:
            db = (tn.db or self.current_db).lower()
            isc.table(db, tn.name)  # must exist
            wanted.append(((db, tn.name.lower()), mode))
        with self.domain._mu:
            locks = self.domain.table_locks
            for key, mode in wanted:
                h = locks.get(key)
                if h is None:
                    continue
                others = h["owners"] - {self.conn_id}
                if others and (mode == "write" or h["mode"] == "write"):
                    raise ExecutorError(
                        f"Table '{key[1]}' is locked by another session")
            # LOCK TABLES implicitly releases this session's prior locks
            self._release_table_locks_locked()
            for key, mode in wanted:
                h = locks.get(key)
                if h is None or not h["owners"]:
                    locks[key] = {"mode": mode, "owners": {self.conn_id}}
                else:  # shared read lock gains another owner
                    h["owners"].add(self.conn_id)
        return ResultSet()

    def _release_table_locks(self):
        with self.domain._mu:
            self._release_table_locks_locked()

    def _release_table_locks_locked(self):
        locks = self.domain.table_locks
        for key in list(locks):
            locks[key]["owners"].discard(self.conn_id)
            if not locks[key]["owners"]:
                del locks[key]

    def _check_table_locks(self, stmt):
        """MySQL LOCK TABLES enforcement at dispatch time."""
        if not self.domain.table_locks:
            return
        from .priv import _walk_tables

        refs: list = []
        _walk_tables(stmt, refs)
        if not refs:
            return
        writing = isinstance(stmt, (ast.InsertStmt, ast.UpdateStmt,
                                    ast.DeleteStmt, ast.LoadDataStmt))
        target = getattr(stmt, "table", None) if writing else None
        with self.domain._mu:
            locks = self.domain.table_locks
            mine = any(self.conn_id in v["owners"] for v in locks.values())
            for tn in refs:
                db = (tn.db or self.current_db).lower()
                if db in self._LOCK_EXEMPT_DBS:
                    continue
                key = (db, tn.name.lower())
                h = locks.get(key)
                if h is None:
                    if mine:
                        raise ExecutorError(
                            f"Table '{tn.name}' was not locked with "
                            f"LOCK TABLES")
                    continue
                if self.conn_id in h["owners"]:
                    if writing and tn is target and h["mode"] != "write":
                        raise ExecutorError(
                            f"Table '{tn.name}' was locked with a READ "
                            f"lock and can't be updated")
                    continue
                if h["mode"] == "write" or (writing and tn is target):
                    raise ExecutorError(
                        f"Table '{tn.name}' is locked by another session")

    def _check_ddl_table_lock(self, db: str, name: str):
        """DDL on a table another session holds locked is refused (MySQL:
        even a foreign READ lock blocks DROP/ALTER)."""
        key = ((db or self.current_db).lower(), name.lower())
        with self.domain._mu:
            h = self.domain.table_locks.get(key)
            if h is not None and h["owners"] - {self.conn_id}:
                raise ExecutorError(
                    f"Table '{name}' is locked by another session")

    def _run_ddl(self, s: ast.Stmt) -> ResultSet:
        cat = self.domain.catalog
        if isinstance(s, ast.CreateDatabaseStmt):
            cat.create_database(s.name, s.if_not_exists)
            return ResultSet()
        if isinstance(s, ast.DropDatabaseStmt):
            cat.drop_database(s.name, s.if_exists)
            if self.current_db.lower() == s.name.lower():
                self.current_db = ""
            return ResultSet()
        if isinstance(s, ast.CreateTableStmt):
            info = self._table_info_from_ast(s)
            cat.create_table(s.table.db or self.current_db, info,
                             s.if_not_exists)
            return ResultSet()
        if isinstance(s, ast.DropTableStmt):
            for tn in s.tables:
                cat.drop_table(tn.db or self.current_db, tn.name,
                               s.if_exists, view_only=s.is_view)
            return ResultSet()
        if isinstance(s, ast.TruncateTableStmt):
            cat.truncate_table(s.table.db or self.current_db, s.table.name)
            return ResultSet()
        if isinstance(s, ast.RecoverTableStmt):
            cat.recover_table(s.table.db or self.current_db, s.table.name)
            return ResultSet()
        if isinstance(s, ast.DropStatsStmt):
            t = cat.info_schema().table(s.table.db or self.current_db,
                                        s.table.name)
            for pid in t.physical_ids() + [t.id]:
                self.domain.stats.drop(pid)
            return ResultSet()
        if isinstance(s, ast.RepairTableStmt):
            # re-derive every index artifact from the row data, then run
            # the full integrity check (util/admin.go RepairTable role
            # over derived indexes)
            t = cat.info_schema().table(s.table.db or self.current_db,
                                        s.table.name)
            for ix in t.indexes:
                self._admin_repair_index(t, ix.name, "recover_index")
            self._admin_check_table(t)
            return ResultSet()
        if isinstance(s, ast.RenameTableStmt):
            cat.rename_table(s.old.db or self.current_db, s.old.name,
                             s.new.name)
            return ResultSet()
        if isinstance(s, ast.CreateIndexStmt):
            cat.create_index(s.table.db or self.current_db, s.table.name,
                             s.index_name, s.columns, s.unique)
            return ResultSet()
        if isinstance(s, ast.DropIndexStmt):
            cat.drop_index(s.table.db or self.current_db, s.table.name,
                           s.index_name)
            return ResultSet()
        if isinstance(s, ast.CreateViewStmt):
            db = s.name.db or self.current_db
            if s.or_replace and cat.info_schema().has_table(db, s.name.name):
                cat.drop_table(db, s.name.name, view_only=True)
            info = TableInfo(0, s.name.name, [], is_view=True)
            info.view_select = s.query  # parsed AST (see build_from)
            cat.create_table(db, info)
            return ResultSet()
        if isinstance(s, ast.AlterTableStmt):
            return self._run_alter(s)
        raise PlanError(f"statement {type(s).__name__} not supported")

    def _run_alter(self, s: ast.AlterTableStmt) -> ResultSet:
        cat = self.domain.catalog
        db = s.table.db or self.current_db
        if s.action == "add_column":
            cat.add_column(db, s.table.name, self._column_info(s.column))
            return ResultSet()
        if s.action == "drop_column":
            cat.drop_column(db, s.table.name, s.name)
            return ResultSet()
        if s.action == "modify_column":
            cat.modify_column(db, s.table.name, self._column_info(s.column))
            return ResultSet()
        if s.action == "add_index":
            ix = s.index
            cat.create_index(db, s.table.name, ix.name, ix.columns,
                             ix.unique, ix.primary)
            return ResultSet()
        if s.action == "drop_index":
            cat.drop_index(db, s.table.name, s.name)
            return ResultSet()
        if s.action == "rename":
            cat.rename_table(db, s.table.name, s.name)
            return ResultSet()
        if s.action in ("add_partition", "drop_partition",
                        "truncate_partition", "coalesce_partition"):
            return self._run_partition_ddl(cat, db, s)
        if s.action == "change_column":
            cat.change_column(db, s.table.name, s.name,
                              self._column_info(s.column))
            return ResultSet()
        if s.action == "rename_index":
            cat.rename_index(db, s.table.name, s.names[0], s.names[1])
            return ResultSet()
        if s.action == "auto_increment":
            cat.rebase_auto_increment(db, s.table.name, s.number)
            return ResultSet()
        if s.action == "comment":
            cat.set_table_comment(db, s.table.name, s.name)
            return ResultSet()
        if s.action == "add_fk":
            fk = s.fk
            cat.add_foreign_key(
                db, s.table.name, fk.name, fk.columns,
                fk.ref_table.db or db, fk.ref_table.name, fk.ref_columns)
            return ResultSet()
        if s.action == "drop_fk":
            cat.drop_foreign_key(db, s.table.name, s.name)
            return ResultSet()
        raise PlanError(f"ALTER {s.action} not supported")

    def _run_partition_ddl(self, cat, db: str, s: ast.AlterTableStmt):
        """ALTER TABLE ... ADD/DROP/TRUNCATE/COALESCE PARTITION with
        per-partition stats invalidation (ddl_api.go:2187-2316 analog)."""
        name = s.table.name
        before = {pd.id for pd in
                  (cat.info_schema().table(db, name).partition_info.defs
                   if cat.info_schema().table(db, name).partition_info
                   else [])}
        if s.action == "add_partition":
            cat.add_partition(db, name,
                              [(d.name, d.less_than) for d in s.part_defs],
                              add_buckets=s.number)
        elif s.action == "drop_partition":
            cat.drop_partition(db, name, s.names)
        elif s.action == "truncate_partition":
            cat.truncate_partition(db, name, s.names)
        else:
            cat.coalesce_partition(db, name, s.number)
        # stats: removed/replaced partitions invalidate via the catalog's
        # drop hook; the logical merged row count is stale either way, so
        # drop it and let auto-analyze / the next ANALYZE rebuild
        t = cat.info_schema().table(db, name)
        after = {pd.id for pd in t.partition_info.defs}
        if after != before:
            self.domain.stats.drop(t.id)
        return ResultSet()

    def _column_info(self, cd: ast.ColumnDef) -> ColumnInfo:
        tn = cd.type_name.lower()
        if tn == "enum":
            if not cd.elems:
                raise PlanError("ENUM requires at least one member")
            ft = ty_enum(cd.elems)
        elif tn == "set":
            if len(cd.elems) > 64:
                raise PlanError("SET supports at most 64 members")
            ft = ty_set(cd.elems)
        else:
            mk = _TYPE_MAP.get(tn)
            if mk is None:
                raise PlanError(f"unknown column type {cd.type_name!r}")
            ft = mk(cd.precision, cd.scale)
        from ..types import MAX_DECIMAL_PRECISION

        if ft.kind == TypeKind.DECIMAL and (
                ft.precision > MAX_DECIMAL_PRECISION
                or ft.scale > 30 or ft.scale > ft.precision):
            raise PlanError(
                f"invalid DECIMAL({ft.precision},{ft.scale})")
        if cd.not_null or cd.primary_key:
            ft = ft.not_null()
        default = None
        has_default = False
        if cd.default is not None:
            from ..planner.build import _eval_const
            from ..planner.columns import Schema
            from ..planner.expr_build import ExprBuilder

            eb = ExprBuilder(Schema([]), None, None, [], None)
            default = _eval_const(eb.build(cd.default))
            has_default = True
        return ColumnInfo(cd.name, ft, 0, default, has_default,
                          cd.auto_increment, cd.primary_key)

    def _table_info_from_ast(self, s: ast.CreateTableStmt) -> TableInfo:
        cols = [self._column_info(c) for c in s.columns]
        info = TableInfo(0, s.table.name, cols)
        idx_id = 1
        for c in cols:
            if c.primary_key:
                info.indexes.append(
                    IndexInfo(idx_id, "PRIMARY", [c.name], True, True)
                )
                idx_id += 1
            # UNIQUE column constraint
        for i, cd in enumerate(s.columns):
            if cd.unique and not cd.primary_key:
                info.indexes.append(
                    IndexInfo(idx_id, f"uniq_{cd.name}", [cd.name], True)
                )
                idx_id += 1
        for ix in s.indexes:
            info.indexes.append(
                IndexInfo(idx_id, ix.name or f"idx_{idx_id}",
                          ix.columns, ix.unique, ix.primary)
            )
            idx_id += 1
        if s.partition_by is not None:
            info.partition_info = self._partition_info(s.partition_by, info)
        seen_fk = set()
        for fk in s.foreign_keys:
            # same validation as ALTER ... ADD FOREIGN KEY
            # (catalog.add_foreign_key): referenced table + columns must
            # exist, names unique, column counts equal
            ref_db = (fk.ref_table.db or self.current_db).lower()
            for c in fk.columns:
                if info.find_column(c) is None:
                    raise PlanError(f"FK column {c!r} does not exist")
            rt = self.domain.catalog.info_schema().table(
                ref_db, fk.ref_table.name)
            for c in fk.ref_columns:
                if rt.find_column(c) is None:
                    raise PlanError(
                        f"FK referenced column {c!r} does not exist in "
                        f"{fk.ref_table.name}")
            if len(fk.columns) != len(fk.ref_columns):
                raise PlanError("FK column count mismatch")
            if fk.name.lower() in seen_fk:
                raise PlanError(f"duplicate foreign key name {fk.name!r}")
            seen_fk.add(fk.name.lower())
            info.foreign_keys.append({
                "name": fk.name, "columns": list(fk.columns),
                "ref_db": ref_db,
                "ref_table": fk.ref_table.name.lower(),
                "ref_columns": list(fk.ref_columns),
            })
        return info

    def _partition_info(self, pb, info: TableInfo):
        """Validate + build PartitionInfo (ddl_api.go buildTablePartitionInfo
        + checkPartitionKeysConstraint analogs)."""
        from ..catalog.schema import PartitionDef, PartitionInfo

        col = info.find_column(pb.column)
        if col is None:
            raise PlanError(f"unknown partition column {pb.column!r}")
        if col.ftype.kind not in (TypeKind.INT, TypeKind.UINT, TypeKind.BOOL,
                                  TypeKind.DATE, TypeKind.DATETIME):
            raise PlanError(
                f"partition column {pb.column!r} must be integer-valued")
        # MySQL 1503: every unique key must use the partitioning column,
        # so uniqueness stays partition-local (no cross-shard checks)
        for ix in info.indexes:
            if (ix.unique or ix.primary) and \
                    pb.column.lower() not in [c.lower() for c in ix.columns]:
                raise PlanError(
                    f"a {'PRIMARY KEY' if ix.primary else 'UNIQUE INDEX'} "
                    f"must include all columns in the table's partitioning "
                    f"function")
        if pb.kind == "hash":
            defs = [PartitionDef(0, f"p{i}") for i in range(pb.num)]
            return PartitionInfo("hash", col.name, defs)
        # RANGE: bounds must be strictly increasing; MAXVALUE only last
        defs, prev = [], None
        seen = set()
        for i, pd in enumerate(pb.defs):
            if pd.name.lower() in seen:
                raise PlanError(f"duplicate partition name {pd.name!r}")
            seen.add(pd.name.lower())
            if pd.less_than is None:
                if i != len(pb.defs) - 1:
                    raise PlanError(
                        "MAXVALUE can only be used in the last partition")
            else:
                if prev is not None and pd.less_than <= prev:
                    raise PlanError(
                        "VALUES LESS THAN must be strictly increasing")
                prev = pd.less_than
            defs.append(PartitionDef(0, pd.name, pd.less_than))
        return PartitionInfo("range", col.name, defs)


# ---------------------------------------------------------------------------


def _format_row(row: tuple, fts: List[FieldType]) -> tuple:
    out = []
    for v, ft in zip(row, fts):
        if v is None:
            out.append(None)
        elif ft.kind == TypeKind.DECIMAL:
            iv = int(v)
            if abs(iv) <= (1 << 53):
                # exactly float-representable: keep the numeric result shape
                out.append(iv / (10 ** ft.scale) if ft.scale else iv)
            else:
                # past 2^53 a float silently drops digits — exact string
                out.append(format_decimal(iv, ft.scale))
        elif ft.kind == TypeKind.DATE:
            out.append(format_date(v))
        elif ft.kind == TypeKind.DATETIME:
            out.append(format_datetime(v))
        elif ft.kind == TypeKind.TIME:
            out.append(format_time(int(v)))
        elif ft.kind == TypeKind.ENUM:
            i = int(v)
            out.append(ft.elems[i - 1] if 1 <= i <= len(ft.elems) else "")
        elif ft.kind == TypeKind.SET:
            i = int(v)
            out.append(",".join(e for j, e in enumerate(ft.elems)
                                if i >> j & 1))
        elif ft.kind == TypeKind.JSON:
            out.append(str(v))
        elif isinstance(v, np.generic):
            out.append(v.item())
        else:
            out.append(v)
    return tuple(out)


def _plan_id_of(name: str) -> int:
    try:
        return int(name.rsplit("_", 1)[1])
    except (IndexError, ValueError):
        return -1


def _show_create(t: TableInfo) -> str:
    lines = []
    for c in t.public_columns():
        s = f"  `{c.name}` {c.ftype.sql_name().lower()}"
        if not c.ftype.nullable:
            s += " NOT NULL"
        if c.has_default:
            s += f" DEFAULT {c.default!r}"
        if c.auto_increment:
            s += " AUTO_INCREMENT"
        lines.append(s)
    for ix in t.indexes:
        if ix.primary:
            lines.append(f"  PRIMARY KEY (`{'`,`'.join(ix.columns)}`)")
        elif ix.unique:
            lines.append(
                f"  UNIQUE KEY `{ix.name}` (`{'`,`'.join(ix.columns)}`)"
            )
        else:
            lines.append(f"  KEY `{ix.name}` (`{'`,`'.join(ix.columns)}`)")
    for fk in t.foreign_keys:
        lines.append(
            f"  CONSTRAINT `{fk['name']}` FOREIGN KEY "
            f"(`{'`,`'.join(fk['columns'])}`) REFERENCES "
            f"`{fk['ref_table']}` (`{'`,`'.join(fk['ref_columns'])}`)")
    body = ",\n".join(lines)
    out = f"CREATE TABLE `{t.name}` (\n{body}\n)"
    pi = t.partition_info
    if pi is not None:
        if pi.kind == "hash":
            out += (f"\nPARTITION BY HASH (`{pi.column}`) "
                    f"PARTITIONS {len(pi.defs)}")
        else:
            parts = ", ".join(
                f"PARTITION `{p.name}` VALUES LESS THAN "
                + ("MAXVALUE" if p.less_than is None else f"({p.less_than})")
                for p in pi.defs)
            out += f"\nPARTITION BY RANGE (`{pi.column}`) ({parts})"
    return out


def _layout_epoch() -> int:
    """Layout-decision generation for plan-cache keys (import kept out of
    the module prologue: sessions exist in jax-free embedders)."""
    try:
        from ..layout import layout_epoch

        return layout_epoch()
    except Exception:
        return 0
