"""System variables.

Reference: sessionctx/variable — SessionVars with ~607 MySQL-style sysvars
(sysvar.go:118), TiDB-specific tuning knobs incl. all parallelism degrees
(tidb_vars.go:367-423).  A registry of defaults; sessions overlay their own
values over the domain's globals, exactly like MySQL SESSION vs GLOBAL scope.
"""

from __future__ import annotations

from typing import Dict, Optional

# name -> (default, kind)  kind in {int, bool, str, float}
SYSVAR_DEFAULTS = {
    "autocommit": ("1", "bool"),
    # MySQL row-lock wait budget (seconds; MySQL default 50)
    "innodb_lock_wait_timeout": ("50", "int"),
    "sql_mode": ("ONLY_FULL_GROUP_BY,STRICT_TRANS_TABLES", "str"),
    "max_execution_time": ("0", "int"),
    # GC retention (seconds; gc_worker.go gcDefaultLifeTime is 10m) and
    # the expensive-query log threshold (seconds, expensivequery.go)
    "tidb_gc_life_time": ("600", "str"),
    "tidb_expensive_query_time_threshold": ("60", "str"),
    "tx_isolation": ("REPEATABLE-READ", "str"),
    "transaction_isolation": ("REPEATABLE-READ", "str"),
    "time_zone": ("SYSTEM", "str"),
    "wait_timeout": ("28800", "int"),
    "interactive_timeout": ("28800", "int"),
    "max_allowed_packet": ("67108864", "int"),
    "version_comment": ("tidb-tpu", "str"),
    "character_set_client": ("utf8mb4", "str"),
    "character_set_results": ("utf8mb4", "str"),
    "character_set_connection": ("utf8mb4", "str"),
    "collation_connection": ("utf8mb4_bin", "str"),
    "lower_case_table_names": ("2", "int"),
    # --- TiDB-style knobs (tidb_vars.go) ------------------------------
    "tidb_max_chunk_size": ("1024", "int"),
    "tidb_init_chunk_size": ("32", "int"),
    "tidb_distsql_scan_concurrency": ("8", "int"),
    "tidb_executor_concurrency": ("5", "int"),
    "tidb_hash_join_concurrency": ("-1", "int"),
    "tidb_hashagg_partial_concurrency": ("-1", "int"),
    "tidb_hashagg_final_concurrency": ("-1", "int"),
    "tidb_projection_concurrency": ("-1", "int"),
    "tidb_index_lookup_concurrency": ("4", "int"),
    "tidb_index_lookup_join_concurrency": ("4", "int"),
    "tidb_opt_prefer_merge_join": ("0", "bool"),
    "tidb_opt_enable_index_join": ("1", "bool"),
    # index join scheduling variant: lookup (ordered, sequential batches) |
    # hash (concurrent batch workers) | merge (key-ordered probes) —
    # INL_JOIN / INL_HASH_JOIN / INL_MERGE_JOIN hint analog
    "tidb_index_join_variant": ("lookup", "str"),
    # cost-based TPU-vs-host scan routing (optimizer.go:162-184 cost split
    # analog).  Measured on the axon-tunneled v5e: one dispatch+readback
    # round trip ~70ms; host numpy runs Q1-shaped scans ~1.3 rows/us; the
    # warm device sustains ~50 rows/us.  dispatch_us=0 disables routing
    # (always device) — set ~70000 on tunneled hardware.
    "tidb_opt_device_dispatch_us": ("0", "int"),
    "tidb_opt_host_rows_per_us": ("1", "int"),
    "tidb_opt_device_rows_per_us": ("50", "int"),
    "tidb_mem_quota_query": (str(32 << 30), "int"),
    "tidb_oom_action": ("cancel", "str"),
    "tidb_retry_limit": ("10", "int"),
    # total per-cop-task retry sleep budget (ms) — backoff.go's maxSleep,
    # configurable instead of the old hard-coded 10s (distsql/backoff.py)
    "tidb_backoff_budget_ms": ("10000", "int"),
    "tidb_disable_txn_auto_retry": ("0", "bool"),
    "tidb_snapshot": ("", "str"),
    # domain-wide cProfile collector -> information_schema.tidb_profile
    "tidb_profiling": ("0", "bool"),
    # --- query tracing / slow log (tidb_tpu/trace) --------------------
    # enable: every statement records a span tree (wire -> parse -> plan
    # -> executor -> distsql -> copr compile/transfer/execute/readback);
    # threshold: statements at or above this many ms land in
    # INFORMATION_SCHEMA.SLOW_QUERY with per-phase columns (0 logs all).
    # Disabled, span hooks are a single contextvar read (zero-cost).
    "tidb_enable_slow_log": ("1", "bool"),
    "tidb_slow_log_threshold": ("300", "int"),
    # size-capped slow-log rotation (ISSUE 13): when the active file
    # exceeds this many bytes it rotates (atomic rename) into
    # slow_query.log.1..N (N = TIDB_TPU_SLOW_LOG_KEEP env, default 3);
    # 0 disables rotation.  GLOBAL scope — the log file is a domain
    # resource.  Torn-tail recovery applies to the active file only.
    "tidb_tpu_slow_log_max_bytes": (str(64 << 20), "int"),
    # --- per-statement-class SLO thresholds (ISSUE 13) ----------------
    # end-to-end latency SLO per statement class (point/agg/join/DML);
    # every finished traced statement observes a log2-bucket histogram
    # `stmt_latency_<class>_ms` and, when its class threshold is > 0,
    # bumps `slo_<class>_{ok,breach}_total` — the error-budget burn
    # counters the /status "slo" section reports.  0 disables burn
    # accounting for a class (the histogram still records).  The string
    # 'auto' (GLOBAL scope) derives the threshold from the observed
    # rolling p99 instead (trace.slo: headroom x merged-window p99,
    # inert until the windows hold enough samples).
    "tidb_tpu_slo_point_ms": ("100", "int"),
    "tidb_tpu_slo_agg_ms": ("1000", "int"),
    "tidb_tpu_slo_join_ms": ("5000", "int"),
    "tidb_tpu_slo_dml_ms": ("500", "int"),
    "tidb_tpu_slo_other_ms": ("0", "int"),
    # auto-capture plan baselines for repeated statements
    # (bindinfo/handle.go:545 CaptureBaselines)
    "tidb_capture_plan_baselines": ("0", "bool"),
    "tidb_opt_agg_push_down": ("1", "bool"),
    "tidb_opt_distinct_agg_push_down": ("0", "bool"),
    # --- MPP exchange engine (tidb_vars.go TiDBAllowMPP/TiDBEnforceMPP,
    # TiDBBroadcastJoinThresholdCount) -------------------------------
    # allow: planner may pick the device shuffle join; enforce: pick it
    # whenever structurally eligible regardless of the cost threshold;
    # threshold: build sides at or below this row estimate stay on the
    # broadcast-lookup / host lanes (no exchange)
    "tidb_allow_mpp": ("1", "bool"),
    "tidb_enforce_mpp": ("0", "bool"),
    "tidb_broadcast_join_threshold_count": ("10240", "int"),
    # plan-cache capacity per session (planner/core/cache.go's
    # plan-cache-size; used to be a hard-coded 128)
    "tidb_plan_cache_size": ("128", "int"),
    # periodic server-side eager session checkpointing (lifecycle
    # follow-up (d)): every N seconds the server parks all prepared
    # sessions' handoff state on the coordination plane, so even a
    # SIGKILLed process loses at most one interval of session churn.
    # 0 disables (drain-time handoff still runs).  GLOBAL scope — the
    # checkpoint loop is a server resource.
    "tidb_tpu_handoff_checkpoint_s": ("0", "int"),
    # --- shape-bucketed serving & micro-batching (tidb_tpu/serving) ---
    # shape buckets: compiled programs and plan-cache entries key on
    # pow2 shape CLASSES (row-count buckets, hoisted predicate params,
    # bucketed TopN budgets) instead of literal shapes/constants
    "tidb_tpu_shape_buckets": ("1", "bool"),
    # micro-batching window (ms; 0 disables): identical-fingerprint
    # point/agg statements arriving within the window coalesce into one
    # vmapped device dispatch.  Process-wide knobs (the batcher is a
    # server-level resource, like max_connections).
    "tidb_tpu_microbatch_window_ms": ("0", "int"),
    "tidb_tpu_microbatch_max": ("32", "int"),
    # interruptible chunked dispatch (ISSUE 17): target device ms per
    # chunk; oversized fragments split into range-slot re-launches of
    # the same compiled program, with KILL/quota checks and
    # resource-group admission between chunks.  0 disables (one
    # dispatch per fragment, the pre-chunking behavior).
    "tidb_tpu_dispatch_chunk_ms": ("100", "int"),
    # the session's resource group; empty = the user's binding
    # (ALTER USER ... RESOURCE GROUP) or "default"
    "tidb_tpu_resource_group": ("", "str"),
    # --- TPU-native knobs ---------------------------------------------
    "tidb_use_tpu": ("1", "bool"),  # per-session engine routing (cpu|tpu)
    # background device-cache warming after bulk loads (LOAD DATA):
    # the first analytic query finds columns resident on the mesh
    "tidb_tpu_prefetch": ("1", "bool"),
    "tidb_tpu_block_rows": (str(1 << 20), "int"),
    "tidb_allow_batch_cop": ("1", "bool"),
    "tidb_enable_pushdown": ("1", "bool"),
    # schema/dtype-verify every finished physical plan (lint.plancheck) —
    # the vet-for-plans gate over planner rewrites; cheap host-side walk,
    # runs only on plan-cache misses, so it stays on by default
    "tidb_check_plan": ("1", "bool"),
}


class SessionVars:
    def __init__(self, globals_map: Optional[Dict[str, str]] = None):
        self._globals = globals_map if globals_map is not None else {}
        self._session: Dict[str, str] = {}
        # user-defined @vars
        self.user_vars: Dict[str, object] = {}

    # ---- typed getters -------------------------------------------------
    def get(self, name: str) -> Optional[str]:
        name = name.lower()
        if name in self._session:
            return self._session[name]
        if name in self._globals:
            return self._globals[name]
        d = SYSVAR_DEFAULTS.get(name)
        return d[0] if d else None

    def get_int(self, name: str, default: int = 0) -> int:
        v = self.get(name)
        try:
            return int(v)
        except (TypeError, ValueError):
            return default

    def get_global_int(self, name: str, default: int = 0) -> int:
        """GLOBAL-scope read (skips any session override): for shared
        resources — SLO burn counters, the slow log — where every
        session must act on the same value /status reports."""
        name = name.lower()
        v = self._globals.get(name)
        if v is None:
            d = SYSVAR_DEFAULTS.get(name)
            v = d[0] if d else None
        try:
            return int(v)
        except (TypeError, ValueError):
            return default

    def get_global_str(self, name: str, default: str = "") -> str:
        """GLOBAL-scope raw read (skips session overrides, no type
        coercion): for sysvars carrying sentinel strings on an int-kind
        knob — `tidb_tpu_slo_<class>_ms = 'auto'` selects the derived
        rolling-p99 threshold (trace.slo) and must read the same on
        every session and on /status."""
        name = name.lower()
        v = self._globals.get(name)
        if v is None:
            d = SYSVAR_DEFAULTS.get(name)
            v = d[0] if d else None
        return v if v is not None else default

    def get_bool(self, name: str) -> bool:
        v = self.get(name)
        return str(v).lower() in ("1", "on", "true", "yes")

    # ---- setters -------------------------------------------------------
    def set_session(self, name: str, value):
        self._session[name.lower()] = _norm(value)

    def set_global(self, name: str, value):
        self._globals[name.lower()] = _norm(value)

    def known(self, name: str) -> bool:
        name = name.lower()
        return (name in SYSVAR_DEFAULTS or name in self._globals
                or name in self._session)

    def all_vars(self) -> Dict[str, str]:
        out = {k: v[0] for k, v in SYSVAR_DEFAULTS.items()}
        out.update(self._globals)
        out.update(self._session)
        return out


def _norm(value) -> str:
    if isinstance(value, bool):
        return "1" if value else "0"
    if value is None:
        return ""
    return str(value)
