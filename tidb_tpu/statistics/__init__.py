from .handle import ColumnStats, StatsHandle, TableStats
from .histogram import Bucket, CMSketch, FMSketch, Histogram

__all__ = [
    "StatsHandle", "TableStats", "ColumnStats",
    "Histogram", "Bucket", "CMSketch", "FMSketch",
]
