"""Query feedback: learn real selectivities from executed scans.

Reference: statistics/feedback.go:51 (QueryFeedback collected per scan
range) applied back into stats in statistics/handle/update.go:411-489.
TPU-native simplification: the coprocessor DAG evaluates whole conjunction
sets per scan, so feedback is keyed on the (table, normalized-conds)
digest and learned as an EWMA of observed selectivity.  The planner
consults learned entries BEFORE histogram math, so estimates converge to
actuals after a few executions even when histograms are stale or the
conjunction is correlated (the two classic drift sources)."""

from __future__ import annotations

import json
import threading
from typing import Dict, Optional, Tuple
from ..util_concurrency import make_lock


def conds_digest(conds) -> Optional[str]:
    """Stable digest of a conjunction (exprs remapped to STORE offsets).
    None when any conjunct fails to serialize (no learning for it)."""
    from ..copr.ir import serialize_expr

    try:
        parts = sorted(
            json.dumps(serialize_expr(c), sort_keys=True, default=str)
            for c in conds
        )
    except Exception:
        return None
    return "&".join(parts)


class QueryFeedback:
    """(table_id, conds digest) -> EWMA of observed selectivity."""

    ALPHA = 0.5  # fast convergence; observations are whole-scan truths
    MAX_ENTRIES = 4096

    def __init__(self):
        self._fb: Dict[Tuple[int, str], Tuple[float, int]] = {}
        self._mu = make_lock("statistics.feedback:QueryFeedback._mu")
        # bumped only when a learned value MATERIALLY moves (new entry or
        # >1.5x shift): cached plans consult this generation, so stable
        # entries keep the plan cache hot while fresh learning re-plans
        self.epoch = 0

    def record(self, table_id: int, digest: str, actual_sel: float,
               baseline_sel: float = None):
        """Update the learned EWMA.  The plan-cache generation bumps only
        when learning MATERIALLY disagrees with what the planner would
        estimate anyway (baseline = histogram math) or with the previous
        learned value — accurate histograms keep the plan cache hot."""
        actual_sel = min(max(actual_sel, 0.0), 1.0)

        def far(a, b):
            lo, hi = sorted((max(a, 1e-9), max(b, 1e-9)))
            return hi / lo > 1.5

        with self._mu:
            cur = self._fb.get((table_id, digest))
            if cur is None:
                if len(self._fb) >= self.MAX_ENTRIES:
                    # bounded memory: drop the least-observed entry
                    victim = min(self._fb, key=lambda k: self._fb[k][1])
                    del self._fb[victim]
                self._fb[(table_id, digest)] = (actual_sel, 1)
                if baseline_sel is None or far(actual_sel, baseline_sel):
                    self.epoch += 1
            else:
                sel, n = cur
                new = sel * (1 - self.ALPHA) + actual_sel * self.ALPHA
                self._fb[(table_id, digest)] = (new, n + 1)
                if far(sel, new):
                    self.epoch += 1

    def lookup(self, table_id: int, digest: str) -> Optional[float]:
        with self._mu:
            cur = self._fb.get((table_id, digest))
        return cur[0] if cur is not None else None

    def invalidate_table(self, table_id: int):
        """ANALYZE rebuilt the histograms: fresh stats supersede learned
        corrections (update.go resets feedback the same way)."""
        with self._mu:
            for k in [k for k in self._fb if k[0] == table_id]:
                del self._fb[k]

    def snapshot(self):
        with self._mu:
            return dict(self._fb)
