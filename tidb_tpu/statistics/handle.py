"""Statistics lifecycle: build on ANALYZE, cache per table version, feed the
planner's row estimates.

Reference: statistics/handle (load/update cache handle.go:148, auto-analyze
NeedAnalyzeTable update.go:621-639), statistics/selectivity.go.

The build path is columnar: ANALYZE pulls each column's base blocks (plus the
delta overlay) and builds Histogram + CMSketch + null/NDV counts with numpy —
the pushdown-ANALYZE shape of executor/analyze.go, minus the RPC hop.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ..types import TypeKind
from .histogram import CMSketch, FMSketch, Histogram
from ..util_concurrency import make_rlock


@dataclass
class ColumnStats:
    hist: Histogram
    cms: Optional[CMSketch]
    null_count: int
    ndv: int


@dataclass
class TableStats:
    table_id: int
    version: int  # storage base_version + delta size at build time
    row_count: int
    columns: Dict[int, ColumnStats] = field(default_factory=dict)
    build_time: float = 0.0
    modify_count: int = 0
    # ANALYZE-built NDV per index (keyed by the tuple of store column
    # offsets, in index order): correlated multi-column selectivity
    # (statistics/index.go histogram NDV role)
    index_ndv: Dict[tuple, int] = field(default_factory=dict)


class StatsHandle:
    def __init__(self, storage):
        from .feedback import QueryFeedback

        self.storage = storage
        self._cache: Dict[int, TableStats] = {}
        self._mu = make_rlock("statistics.handle:StatsHandle._mu")
        self.auto_analyze_ratio = 0.5
        # learned whole-conjunction selectivities (statistics/feedback.go
        # role): consulted before histogram math in estimate_selectivity
        self.feedback = QueryFeedback()

    # ------------------------------------------------------------------
    epoch = 0  # bumped per analyze: plan-cache invalidation

    def analyze_table(self, table_id: int, n_buckets: int = 64) -> TableStats:
        self.epoch += 1
        self.feedback.invalidate_table(table_id)
        return self._analyze_table(table_id, n_buckets)

    def analyze(self, table_info, n_buckets: int = 64) -> TableStats:
        """ANALYZE entry taking schema metadata: partitioned tables analyze
        every partition store (stats cached per physical id) plus a merged
        row-count entry under the logical id for planner cardinality
        (statistics/handle.go's partition-table GlobalStats, row-count
        level)."""
        index_offsets = [
            tuple(table_info.col_offsets(ix.columns))
            for ix in table_info.indexes
        ]
        if table_info.partition_info is None:
            self.epoch += 1
            self.feedback.invalidate_table(table_info.id)
            return self._analyze_table(table_info.id, n_buckets,
                                       index_offsets)
        self.epoch += 1
        for pid in table_info.physical_ids():
            self.feedback.invalidate_table(pid)
        total, version = 0, 0
        for pd in table_info.partition_info.defs:
            st = self._analyze_table(pd.id, n_buckets, index_offsets)
            total += st.row_count
            version = version * 1_000_003 + st.version
        merged = TableStats(table_info.id, version, total,
                            build_time=time.time())
        with self._mu:
            self._cache[table_info.id] = merged
        return merged

    def _analyze_table(self, table_id: int, n_buckets: int = 64,
                       index_offsets=None) -> TableStats:
        store = self.storage.table(table_id)
        ts = self.storage.current_ts()
        deleted, inserted = store.delta_overlay(ts, 0, 1 << 62)
        dele = set(deleted)
        n_base = store.base_rows
        stats = TableStats(
            table_id,
            version=store.base_version * 1_000_003 + len(store.delta),
            row_count=n_base - len(dele) + len(inserted),
            build_time=time.time(),
        )
        for ci in range(store.n_cols):
            meta = store.cols[ci]
            chunk = store.base_chunk([ci], 0, n_base, decode_strings=False)
            col = chunk.col(0)
            data = col.data
            valid = col.validity()
            if dele:
                keep = np.ones(n_base, dtype=np.bool_)
                keep[list(dele)] = False
                data, valid = data[keep], valid[keep]
            vals = data[valid]
            nulls = int((~valid).sum())
            if inserted:
                # fold committed delta rows in (strings -> dict codes)
                dvals = []
                for row in inserted.values():
                    x = row[ci]
                    if x is None:
                        nulls += 1
                        continue
                    if meta.ftype.kind == TypeKind.STRING:
                        code = store.encode_dict_const(ci, str(x)) \
                            if meta.dictionary is not None else \
                            hash(str(x)) & 0x7FFFFFFF
                        dvals.append(code)
                    else:
                        dvals.append(x)
                if dvals:
                    vals = np.concatenate([
                        vals.astype(np.float64, copy=False),
                        np.asarray(dvals, dtype=np.float64),
                    ])
            if meta.ftype.kind == TypeKind.STRING and vals.dtype == object:
                # shouldn't happen (dict-encoded), but guard
                vals = np.array([hash(x) & 0x7FFFFFFF for x in vals],
                                dtype=np.int64)
            vals64 = vals.astype(np.float64, copy=False)
            hist = Histogram.build(vals64, nulls, n_buckets)
            cms = CMSketch()
            if len(vals):
                cms.insert_batch(vals.astype(np.int64, copy=False)
                                 if vals.dtype != np.float64
                                 else vals.view(np.int64))
            stats.columns[ci] = ColumnStats(hist, cms, nulls, hist.ndv)
        for offs in (index_offsets or ()):
            offs = tuple(offs)
            if not offs or any(o >= store.n_cols for o in offs):
                continue
            stats.index_ndv[offs] = self._combined_ndv(store, offs, dele,
                                                       inserted)
        with self._mu:
            self._cache[table_id] = stats
        return stats

    @staticmethod
    def _combined_ndv(store, offs, dele, inserted) -> int:
        """Distinct count of the column tuple (index key NDV).  NULL-bearing
        keys are excluded (MySQL index cardinality convention); delta rows'
        raw string values encode to the same dictionary codes the base
        chunk carries so both sides compare in one domain."""
        from ..types import TypeKind

        chunk = store.base_chunk(list(offs), 0, store.base_rows,
                                 decode_strings=False)
        cols = [chunk.col(i).data for i in range(len(offs))]
        valids = [chunk.col(i).validity() for i in range(len(offs))]
        seen = set()
        for h in range(chunk.num_rows):
            if h in dele or not all(v[h] for v in valids):
                continue
            seen.add(tuple(c[h] for c in cols))
        dict_cols = store.dict_encoded_cols()
        for row in inserted.values():
            key = []
            for o in offs:
                x = row[o]
                if x is None:
                    key = None
                    break
                if o in dict_cols:
                    code = store.encode_dict_const(o, str(x))
                    x = code if code >= 0 else ("\x00new", str(x))
                key.append(x)
            if key is not None:
                seen.add(tuple(key))
        return max(len(seen), 1)

    def drop(self, table_id: int):
        with self._mu:
            self._cache.pop(table_id, None)
        try:
            # the layout autotuner forgets the dropped table's columns
            # (its store may outlive the drop for MVCC, so the drop
            # notification — not store GC — is the liveness signal)
            from ..layout import LAYOUT

            LAYOUT.forget_table(table_id)
        except Exception:
            pass  # layout upkeep must never fail a DDL

    def get(self, table_id: int) -> Optional[TableStats]:
        with self._mu:
            return self._cache.get(table_id)

    def cache_snapshot(self):
        """Point-in-time copy of the stats cache for introspection (SHOW
        ANALYZE STATUS / mysql.stats_meta) — iteration outside the lock
        would race concurrent ANALYZE inserts."""
        with self._mu:
            return dict(self._cache)

    # ------------------------------------------------------------------
    def need_auto_analyze(self, table_id: int) -> bool:
        """update.go:621-639 NeedAnalyzeTable: analyze when modified rows
        exceed ratio * row_count or no stats exist for a non-empty table."""
        store = self.storage.table(table_id)
        st = self.get(table_id)
        cur_rows = store.base_rows + len(store.delta)
        if st is None:
            return cur_rows > 0
        cur_version = store.base_version * 1_000_003 + len(store.delta)
        if cur_version == st.version:
            return False
        modified = abs(cur_rows - st.row_count) + len(store.delta)
        return modified > max(st.row_count, 1) * self.auto_analyze_ratio

    # ------------------------------------------------------------------
    # selectivity (statistics/selectivity.go, simplified to per-conjunct
    # independence like the reference's fallback path)
    # ------------------------------------------------------------------
    def record_feedback(self, table_id: int, conds, actual_sel: float):
        """Executor-side entry: learn the observed selectivity of a fully
        drained scan's conjunction (statistics/feedback.go role)."""
        from .feedback import conds_digest

        dg = conds_digest(conds)
        if dg is None:
            return
        baseline = self.estimate_selectivity(table_id, conds,
                                             use_feedback=False)
        self.feedback.record(table_id, dg, actual_sel, baseline)
        self._feed_layout(table_id, conds, actual_sel)

    def _feed_layout(self, table_id: int, conds, actual_sel: float):
        """Forward the learned per-scan selectivity to the layout
        autotuner (tidb_tpu/layout) for every store column the
        conjunction touches — one of the tuner's observation planes."""
        try:
            from ..layout import LAYOUT, layout_enabled

            if not layout_enabled():
                return
            store = self.storage.table(table_id)
            refs: set = set()
            for c in conds:
                c.collect_columns(refs)
            for ci in refs:
                if 0 <= ci < store.n_cols:
                    LAYOUT.observe(store, ci, "filter", sel=actual_sel)
        except Exception:
            pass  # observation is advisory, never a query failure

    def estimate_selectivity(self, table_id: int, conds,
                             use_feedback: bool = True) -> float:
        """Per-conjunct selectivity with two sharpenings over naive
        independence (statistics/selectivity.go):

        - range conds on ONE column intersect into a single histogram
          range estimate (a > 5 AND a < 10 is one interval, not 0.25^2)
        - an eq-conjunction covering an ANALYZEd index's columns uses the
          index's combined NDV (correlated columns stop multiplying)
        """
        from ..expr.expression import ColumnExpr, Constant, ScalarFunc

        st = self.get(table_id)
        if st is None or st.row_count == 0:
            return 0.25 ** min(len(conds), 2) if conds else 1.0
        if conds and use_feedback:
            # learned truth from prior executions beats histogram math
            from .feedback import conds_digest

            dg = conds_digest(conds)
            if dg is not None:
                learned = self.feedback.lookup(table_id, dg)
                if learned is not None:
                    return max(min(learned, 1.0), 1e-6)
        try:
            store = self.storage.table(table_id)
        except Exception:
            store = None
        ranges: Dict[int, list] = {}
        eq_cols: Dict[int, object] = {}
        rest = []
        for c in conds:
            trip = _col_const(c) if isinstance(c, ScalarFunc) else (
                None, None, False)
            col, const, flipped = trip
            name = getattr(c, "name", "")
            if col is not None and name in ("<", "<=", ">", ">=", "="):
                op = name if not flipped else _FLIP.get(name, name)
                if op == "=":
                    eq_cols[col.index] = (c, const)
                else:
                    ranges.setdefault(col.index, []).append((c, op, const))
                continue
            rest.append(c)
        sel = 1.0
        # one interval estimate per ranged column
        for ci, items in ranges.items():
            if len(items) == 1 or ci in eq_cols:
                for c, _op, _k in items:
                    sel *= self._cond_selectivity(st, c, store)
            else:
                sel *= self._interval_selectivity(st, ci, items, store)
        # eq conds: covered-index NDV beats independence when available
        eq_left = dict(eq_cols)
        for offs, ndv in sorted(st.index_ndv.items(),
                                key=lambda kv: -len(kv[0])):
            if offs and all(o in eq_left for o in offs):
                sel *= 1.0 / max(ndv, 1)
                for o in offs:
                    del eq_left[o]
        for ci, (c, _const) in eq_left.items():
            sel *= self._cond_selectivity(st, c, store)
        for c in rest:
            sel *= self._cond_selectivity(st, c, store)
        return max(min(sel, 1.0), 1e-6)

    def _interval_selectivity(self, st: "TableStats", ci: int, items,
                              store) -> float:
        """Intersect all range conds on one column into [lo, hi] and read
        the histogram once."""
        cs = st.columns.get(ci)
        if cs is None or cs.hist.row_count() == 0:
            return 0.25
        lo = hi = None
        for c, op, const in items:
            v = const.value
            if isinstance(v, str):
                if store is None:
                    return 0.25
                meta = store.cols[ci] if ci < store.n_cols else None
                if meta is None or meta.dictionary is None:
                    return 0.25
                v = store.dict_bound(
                    ci, v, "left" if op in ("<", ">=") else "right")
            if not isinstance(v, (int, float)):
                return 0.25
            x = float(v)
            if op in (">", ">="):
                lo = x if lo is None else max(lo, x)
            else:
                hi = x if hi is None else min(hi, x)
        h = cs.hist
        total = float(h.row_count())
        hi_cnt = total if hi is None else (
            h.less_row_count(hi) + h.equal_row_count(hi))
        lo_cnt = 0.0 if lo is None else h.less_row_count(lo)
        return max(min((hi_cnt - lo_cnt) / total, 1.0), 0.0)

    def _cond_selectivity(self, st: TableStats, cond, store=None) -> float:
        from ..expr.expression import ColumnExpr, Constant, ScalarFunc

        default = 0.8  # unknown predicate shapes barely filter
        if not isinstance(cond, ScalarFunc):
            return default
        name = cond.name
        if name in ("and",):
            a, b = cond.args
            return self._cond_selectivity(st, a, store) * \
                self._cond_selectivity(st, b, store)
        if name in ("or",):
            a, b = cond.args
            sa = self._cond_selectivity(st, a, store)
            sb = self._cond_selectivity(st, b, store)
            return min(sa + sb, 1.0)
        col, const, flipped = _col_const(cond)
        if col is None:
            return 0.25 if name in ("=", "<", "<=", ">", ">=", "in",
                                    "like") else default
        # callers remap ColumnExpr.index to the STORE column offset before
        # asking for selectivity (see planner/physical._selectivity)
        cs = st.columns.get(col.index)
        if cs is None or cs.hist.row_count() == 0:
            return 0.25
        total = float(cs.hist.row_count())
        op = name if not flipped else _FLIP.get(name, name)
        v = const.value
        if isinstance(v, str) and store is not None:
            # stats are over dictionary codes; encode the literal using the
            # EFFECTIVE (flip-adjusted) operator's bound side
            meta = store.cols[col.index] if col.index < store.n_cols else None
            if meta is None or meta.dictionary is None:
                return 0.25
            if op == "=":
                v = store.encode_dict_const(col.index, v)
                if v < 0:
                    return 0.0
            else:
                v = store.dict_bound(
                    col.index, v,
                    "left" if op in ("<", ">=") else "right",
                )
            const = type(const)(v, const.ftype)
        x = _const_as_float(const)
        if x is None:
            return 0.25
        h = cs.hist
        if op == "=":
            # point predicates: Count-Min beats the histogram's in-bucket
            # average when the value is an integer representation
            v = const.value
            if cs.cms is not None and cs.cms.count > 0 and \
                    isinstance(v, int):
                return min(cs.cms.query(v) / total, 1.0)
            return min(h.equal_row_count(x) / total, 1.0)
        if op == "!=":
            return max(1.0 - h.equal_row_count(x) / total, 0.0)
        if op == "<":
            return min(h.less_row_count(x) / total, 1.0)
        if op == "<=":
            return min((h.less_row_count(x) + h.equal_row_count(x)) / total, 1.0)
        if op == ">":
            return max(1.0 - (h.less_row_count(x) + h.equal_row_count(x))
                       / total, 0.0)
        if op == ">=":
            return max(1.0 - h.less_row_count(x) / total, 0.0)
        if op == "isnull":
            return cs.null_count / total
        if op == "isnotnull":
            return 1.0 - cs.null_count / total
        return default


_FLIP = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}


def _col_const(cond):
    from ..expr.expression import ColumnExpr, Constant

    if cond.name in ("isnull", "isnotnull") and len(cond.args) == 1 and \
            isinstance(cond.args[0], ColumnExpr):
        return cond.args[0], Constant(0, None), False
    if len(getattr(cond, "args", ())) != 2:
        return None, None, False
    a, b = cond.args
    if isinstance(a, ColumnExpr) and isinstance(b, Constant):
        return a, b, False
    if isinstance(b, ColumnExpr) and isinstance(a, Constant):
        return b, a, True
    return None, None, False


def _const_as_float(c) -> Optional[float]:
    v = getattr(c, "value", None)
    if v is None:
        return None
    if isinstance(v, (int, float)):
        ft = getattr(c, "ftype", None)
        if ft is not None and getattr(ft, "kind", None) == TypeKind.DECIMAL:
            return float(v)  # scaled-int repr matches stored values
        return float(v)
    return None
