"""Equi-depth histograms + Count-Min sketch + FM sketch.

Reference: statistics/histogram.go:42 (equi-depth Histogram with per-bucket
count/repeat), statistics/cmsketch.go:40, statistics/fmsketch.go.  Vectorized
builds: one np.sort per column instead of the reference's per-row insertion.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np


@dataclass
class Bucket:
    upper: float  # inclusive upper bound
    lower: float
    count: int  # rows in this bucket
    repeat: int  # rows equal to upper


class Histogram:
    """Equi-depth histogram over numeric representations (strings hash to
    dictionary codes before reaching here)."""

    def __init__(self, buckets: List[Bucket], null_count: int, ndv: int,
                 total: int):
        self.buckets = buckets
        self.null_count = null_count
        self.ndv = ndv
        self.total = total  # non-null rows

    @staticmethod
    def build(values: np.ndarray, null_count: int = 0,
              n_buckets: int = 64) -> "Histogram":
        n = len(values)
        if n == 0:
            return Histogram([], null_count, 0, 0)
        v = np.sort(values.astype(np.float64, copy=False))
        ndv = int((np.diff(v) != 0).sum()) + 1
        per = max(n // n_buckets, 1)
        buckets: List[Bucket] = []
        i = 0
        while i < n:
            j = min(i + per, n)
            upper = v[j - 1]
            # extend to include all duplicates of upper (repeat semantics)
            while j < n and v[j] == upper:
                j += 1
            repeat = int(np.searchsorted(v, upper, "right")
                         - np.searchsorted(v, upper, "left"))
            buckets.append(Bucket(float(upper), float(v[i]), j - i, repeat))
            i = j
        return Histogram(buckets, null_count, ndv, n)

    # ------------------------------------------------------------------
    def row_count(self) -> int:
        return self.total + self.null_count

    def less_row_count(self, x: float) -> float:
        """Estimated rows with value < x."""
        acc = 0.0
        for b in self.buckets:
            if x > b.upper:
                acc += b.count
            elif x <= b.lower:
                break
            else:
                width = b.upper - b.lower
                frac = (x - b.lower) / width if width > 0 else 0.0
                acc += (b.count - b.repeat) * frac
                break
        return acc

    def equal_row_count(self, x: float) -> float:
        for b in self.buckets:
            if b.lower <= x <= b.upper:
                if x == b.upper:
                    return float(b.repeat)
                return max(b.count / max(self.ndv_in_bucket(), 1), 1.0)
        return 0.0

    def ndv_in_bucket(self) -> int:
        return max(self.ndv // max(len(self.buckets), 1), 1)

    def between_row_count(self, lo: Optional[float], hi: Optional[float],
                          lo_open: bool = False,
                          hi_open: bool = True) -> float:
        """rows in [lo, hi) by default; None = unbounded."""
        if self.total == 0:
            return 0.0
        a = self.less_row_count(lo) + (self.equal_row_count(lo) if lo_open else 0.0) \
            if lo is not None else 0.0
        b = self.less_row_count(hi) + (0.0 if hi_open else self.equal_row_count(hi)) \
            if hi is not None else float(self.total)
        return max(b - a, 0.0)


class CMSketch:
    """Count-Min sketch for point-equality estimates (cmsketch.go:40)."""

    def __init__(self, depth: int = 4, width: int = 2048):
        self.depth = depth
        self.width = width
        self.table = np.zeros((depth, width), dtype=np.int64)
        self.count = 0

    _SEEDS = (0x9E3779B97F4A7C15, 0xC2B2AE3D27D4EB4F,
              0x165667B19E3779F9, 0x27D4EB2F165667C5)

    def _hash(self, vals: np.ndarray) -> np.ndarray:
        """[depth, n] bucket indices (splitmix-style avalanche)."""
        x = vals.astype(np.uint64)
        out = np.empty((self.depth, len(vals)), dtype=np.int64)
        for d in range(self.depth):
            h = x + np.uint64(self._SEEDS[d])
            h = (h ^ (h >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
            h = (h ^ (h >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
            h = h ^ (h >> np.uint64(31))
            out[d] = (h % np.uint64(self.width)).astype(np.int64)
        return out

    def insert_batch(self, vals: np.ndarray):
        idx = self._hash(vals)
        for d in range(self.depth):
            np.add.at(self.table[d], idx[d], 1)
        self.count += len(vals)

    def query(self, val: int) -> int:
        idx = self._hash(np.array([val], dtype=np.int64))
        est = min(int(self.table[d][idx[d][0]]) for d in range(self.depth))
        # noise correction (classic CM bias adjustment)
        noise = self.count / self.width
        return max(int(est - noise), 0)


class FMSketch:
    """Flajolet-Martin distinct-count sketch (statistics/fmsketch.go)."""

    def __init__(self, max_size: int = 10000):
        self.max_size = max_size
        self.mask = np.uint64(0)
        self.hashset: set = set()

    def insert_batch(self, vals: np.ndarray):
        x = vals.astype(np.uint64)
        h = x * np.uint64(0x9E3779B97F4A7C15)
        h = h ^ (h >> np.uint64(29))
        for v in h:
            v = np.uint64(v)
            if (v & self.mask) == 0:
                self.hashset.add(int(v))
                if len(self.hashset) > self.max_size:
                    self.mask = (self.mask << np.uint64(1)) | np.uint64(1)
                    self.hashset = {
                        s for s in self.hashset
                        if (np.uint64(s) & self.mask) == 0
                    }

    def ndv(self) -> int:
        return (int(self.mask) + 1) * len(self.hashset)
