from .kv import (
    CopRequest,
    CopResponse,
    KeyRange,
    Storage,
    StoreClient,
)
from .oracle import Oracle, compose_ts, extract_physical
from .blockstore import TableStore, BLOCK_SIZE
from .regions import Region, RegionManager
from .storage import BlockStorage

__all__ = [
    "CopRequest",
    "CopResponse",
    "KeyRange",
    "Storage",
    "StoreClient",
    "Oracle",
    "compose_ts",
    "extract_physical",
    "TableStore",
    "BLOCK_SIZE",
    "Region",
    "RegionManager",
    "BlockStorage",
]
