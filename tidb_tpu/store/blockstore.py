"""Columnar block store: the TPU-native storage engine for one table.

This is the component the reference does NOT contain (TiKV's storage engine,
in Rust, outside the repo) and which we must build natively (SURVEY.md header
note).  Design:

- **Base**: immutable fixed-capacity column blocks (numpy; BLOCK_SIZE rows)
  with implicit handles [0..base_rows).  Fixed shapes are what XLA wants:
  a scan stacks blocks into [n_blocks, BLOCK_SIZE] device arrays with
  row-validity masks, so every block compiles to the same program.
- **Strings** are dictionary-encoded at load with a *sorted* dictionary
  (order-preserving: code comparisons = string comparisons), codes int32.
- **Delta**: an MVCC row store (handle -> version chain) for DML after load,
  with Percolator locks — the moral equivalent of TiDB's membuffer+TiKV MVCC
  (kv/memdb + mocktikv/mvcc_leveldb.go).  Scans overlay delta on base like
  UnionScan (executor/union_scan.go) merges txn buffer over snapshot.
- **compact()** merges committed delta into new base blocks (delta-merge,
  the TiFlash idea) and rebuilds dictionaries sorted.
"""

from __future__ import annotations

import bisect
import itertools
import threading
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..chunk import Chunk, Column
from ..errors import KVError, LockedError, TxnConflictError
from ..types import FieldType, TypeKind
from ..util_concurrency import make_rlock

BLOCK_SIZE = 1 << 16  # 65536 rows per block

_STORE_SEQ = itertools.count(1)  # process-unique store tokens (cache keys)


@dataclass
class ColumnMeta:
    name: str
    ftype: FieldType
    # sorted dictionary for string columns (base blocks store int32 codes)
    dictionary: Optional[List[str]] = None


@dataclass
class Lock:
    start_ts: int
    primary: Tuple[int, int]  # (table_id, handle)
    op: str  # 'put' | 'del' | 'lock'
    values: Optional[tuple]
    ttl_ms: int = 3000


@dataclass
class Version:
    commit_ts: int
    start_ts: int
    op: str  # 'put' | 'del'
    values: Optional[tuple]  # full row tuple for 'put'


class TableStore:
    def __init__(self, table_id: int, columns: List[Tuple[str, FieldType]]):
        self.table_id = table_id
        # process-unique token: table ids repeat across Domains (each catalog
        # numbers from 100), so shared caches MUST key on this, not table_id
        self.store_uid = next(_STORE_SEQ)
        self.cols: List[ColumnMeta] = [ColumnMeta(n, t) for n, t in columns]
        self.base_rows = 0
        # per column: list of numpy blocks + validity blocks
        self._blocks: List[List[np.ndarray]] = [[] for _ in self.cols]
        self._valids: List[List[Optional[np.ndarray]]] = [[] for _ in self.cols]
        self.base_ts = 0  # commit_ts of the base snapshot
        # delta: handle -> ascending-commit_ts version chain
        self.delta: Dict[int, List[Version]] = {}
        self.locks: Dict[int, Lock] = {}
        self.next_handle = 0
        self._mu = make_rlock("store.blockstore:TableStore._mu")
        # bumped on bulk load / compact: device caches key on this
        self.base_version = 0
        self._col_stats: Dict[int, Tuple[int, int, bool]] = {}
        # durability hook (store/persist.TablePersister); None = RAM-only
        self.persister = None
        self.on_mutate = None  # storage-level data-version bump (plan cache)
        self.mutations = 0  # per-store committed-write counter (plan cache)
        from .index import IndexManager

        self.indexes = IndexManager()

    # ------------------------------------------------------------------
    # schema helpers
    # ------------------------------------------------------------------
    @property
    def n_cols(self) -> int:
        return len(self.cols)

    def col_index(self, name: str) -> int:
        for i, c in enumerate(self.cols):
            if c.name == name:
                return i
        raise KVError(f"no column {name!r} in table {self.table_id}")

    def ftypes(self) -> List[FieldType]:
        return [c.ftype for c in self.cols]

    def dict_encoded_cols(self) -> set:
        return {
            i for i, c in enumerate(self.cols) if c.dictionary is not None
        }

    def encode_dict_const(self, col_idx: int, s: str) -> int:
        """String constant -> dictionary code; -1 if absent (matches nothing,
        but keeps comparisons well-defined because codes are >= 0)."""
        d = self.cols[col_idx].dictionary
        if d is None:
            raise KVError("column not dict-encoded")
        j = bisect.bisect_left(d, s)
        if j < len(d) and d[j] == s:
            return j
        return -1
    def dict_bound(self, col_idx: int, s: str, side: str) -> int:
        """Code bound for range predicates on sorted dictionaries:
        side='left' -> first code with value >= s; 'right' -> first > s."""
        d = self.cols[col_idx].dictionary
        return (bisect.bisect_left if side == "left" else bisect.bisect_right)(d, s)

    # ------------------------------------------------------------------
    # bulk load (build base blocks)
    # ------------------------------------------------------------------
    def bulk_load_arrays(self, arrays: Sequence[np.ndarray],
                         valids: Optional[Sequence[Optional[np.ndarray]]] = None,
                         ts: int = 0,
                         dictionaries: Optional[dict] = None):
        """Append columnar data to base.  String columns take object arrays
        and are dictionary-encoded here — OR, Arrow-dictionary style, the
        caller passes `dictionaries[ci] = sorted unique values` and
        `arrays[ci]` as int codes into it (bulk generators/loaders skip
        the per-row encode entirely)."""
        with self._mu:
            n = len(arrays[0])
            assert all(len(a) == n for a in arrays), "ragged load"
            # New base rows take handles [base_rows, base_rows+n).  Delta
            # inserts committed before this load may already own handles in
            # that range (alloc_handle starts at next_handle); left alone,
            # their versions would shadow the loaded rows as phantom updates.
            # Fold the committed delta into base first so every existing row
            # gets a fresh sub-base_rows handle and the append region is free.
            if self.delta and (self.next_handle > self.base_rows
                               or any(h >= self.base_rows for h in self.delta)):
                if self.locks:
                    raise KVError(
                        "bulk load would collide with uncommitted rows")
                fold_ts = max(
                    [ts] + [c[-1].commit_ts for c in self.delta.values() if c])
                self.compact(fold_ts)
            # validate EVERY coded column before any block is appended: a
            # failure mid-loop would leave ragged columns (torn store)
            if dictionaries:
                for ci, new_dict in dictionaries.items():
                    self._validate_coded_locked(ci, arrays[ci], new_dict)
            for ci, (meta, arr) in enumerate(zip(self.cols, arrays)):
                valid = valids[ci] if valids else None
                if meta.ftype.kind == TypeKind.STRING:
                    if dictionaries is not None and ci in dictionaries:
                        arr = self._ingest_coded_locked(ci, meta, arr,
                                                 dictionaries[ci])
                    else:
                        codes, dictionary = _dict_encode_merge(
                            arr, meta.dictionary, self._blocks[ci]
                        )
                        meta.dictionary = dictionary
                        arr = codes
                else:
                    arr = np.ascontiguousarray(arr, dtype=meta.ftype.np_dtype)
                self._append_blocks_locked(ci, arr, valid)
            self.base_rows += n
            self.next_handle = max(self.next_handle, self.base_rows)
            self.base_ts = max(self.base_ts, ts)
            self.base_version += 1
            self._col_stats.clear()
            self.mutations += 1
            if self.on_mutate is not None:
                self.on_mutate()
            if self.persister is not None:
                self.persister.save_base(self)

    def _validate_coded_locked(self, ci: int, codes: np.ndarray, new_dict):
        """Pure validation for Arrow-style coded ingest (no mutation)."""
        if ci >= len(self.cols) or \
                self.cols[ci].ftype.kind != TypeKind.STRING:
            raise KVError(f"column {ci} is not a string column")
        new_dict = [str(x) for x in new_dict]
        if sorted(set(new_dict)) != new_dict:
            raise KVError("dictionary must be sorted unique strings")
        codes = np.asarray(codes)
        if len(codes) and (int(codes.min()) < 0
                           or int(codes.max()) >= len(new_dict)):
            raise KVError("dictionary codes out of range")
        if self.cols[ci].dictionary is None and self._blocks[ci]:
            raise KVError(
                "existing un-coded blocks: cannot attach a dictionary")

    def _ingest_coded_locked(self, ci: int, meta, codes: np.ndarray,
                      new_dict) -> np.ndarray:
        """Pre-encoded string ingest (validated up front by
        _validate_coded_locked): merge with the existing dictionary, remapping
        old blocks when code order shifts — same contract as
        _dict_encode_merge, minus the per-row encode."""
        new_dict = [str(x) for x in new_dict]
        codes = np.ascontiguousarray(codes, dtype=np.int32)
        if meta.dictionary is None or meta.dictionary == new_dict:
            meta.dictionary = new_dict
            return codes
        to_merged, merged = _merge_dictionary(meta.dictionary, new_dict,
                                              self._blocks[ci])
        meta.dictionary = merged
        return to_merged[codes]

    def _append_blocks_locked(self, ci: int, arr: np.ndarray, valid: Optional[np.ndarray]):
        blocks, valids = self._blocks[ci], self._valids[ci]
        off = 0
        n = len(arr)
        # fill the last partial block first
        if blocks and len(blocks[-1]) < BLOCK_SIZE:
            space = BLOCK_SIZE - len(blocks[-1])
            take = min(space, n)
            blocks[-1] = np.concatenate([blocks[-1], arr[:take]])
            if valids[-1] is not None or (valid is not None and not valid[:take].all()):
                old_v = (
                    valids[-1]
                    if valids[-1] is not None
                    else np.ones(len(blocks[-1]) - take, dtype=np.bool_)
                )
                new_v = (
                    valid[:take]
                    if valid is not None
                    else np.ones(take, dtype=np.bool_)
                )
                valids[-1] = np.concatenate([old_v, new_v])
            off = take
        while off < n:
            take = min(BLOCK_SIZE, n - off)
            blocks.append(np.ascontiguousarray(arr[off : off + take]))
            v = None
            if valid is not None and not valid[off : off + take].all():
                v = valid[off : off + take].copy()
            valids.append(v)
            off += take

    # ------------------------------------------------------------------
    # base block access (device scan path)
    # ------------------------------------------------------------------
    def iter_base_blocks(
        self, col_idx: Sequence[int], start: int, end: int
    ) -> Iterator[Tuple[int, List[np.ndarray], List[Optional[np.ndarray]]]]:
        """Yield (handle_offset, [col arrays], [col valids]) for each base
        block slice intersecting [start, end)."""
        # snapshot the block lists under the lock, then iterate the
        # locals: base blocks are append-only (compaction replaces the
        # whole lists), so the slices stay valid without holding the
        # mutex across yields
        with self._mu:
            end = min(end, self.base_rows)
            if start >= end:
                return
            blocks = {ci: list(self._blocks[ci]) for ci in col_idx}
            valids = {ci: list(self._valids[ci]) for ci in col_idx}
        b0, b1 = start // BLOCK_SIZE, (end - 1) // BLOCK_SIZE
        for b in range(b0, b1 + 1):
            lo = max(start - b * BLOCK_SIZE, 0)
            hi = min(end - b * BLOCK_SIZE, BLOCK_SIZE)
            arrs, vals = [], []
            for ci in col_idx:
                blk = blocks[ci][b]
                arrs.append(blk[lo:hi])
                v = valids[ci][b]
                vals.append(v[lo:hi] if v is not None else None)
            yield b * BLOCK_SIZE + lo, arrs, vals

    def base_chunk(self, col_idx: Sequence[int], start: int, end: int,
                   decode_strings: bool = True) -> Chunk:
        """Materialize base rows [start, end) as a host Chunk."""
        cols: List[Column] = []
        parts: List[List[np.ndarray]] = [[] for _ in col_idx]
        vparts: List[List[np.ndarray]] = [[] for _ in col_idx]
        any_rows = False
        for off, arrs, vals in self.iter_base_blocks(col_idx, start, end):
            any_rows = True
            for i, (a, v) in enumerate(zip(arrs, vals)):
                parts[i].append(a)
                vparts[i].append(
                    v if v is not None else np.ones(len(a), dtype=np.bool_)
                )
        for i, ci in enumerate(col_idx):
            meta = self.cols[ci]
            if not any_rows:
                cols.append(Column.from_values(meta.ftype, []))
                continue
            data = np.concatenate(parts[i])
            valid = np.concatenate(vparts[i])
            if meta.ftype.kind == TypeKind.STRING and decode_strings:
                data = _decode_dict(data, meta.dictionary)
            cols.append(Column(meta.ftype, data, None if valid.all() else valid))
        return Chunk(cols)

    def gather_chunk(self, col_idx: Sequence[int], handles: np.ndarray,
                     decode_strings: bool = True) -> Chunk:
        """Gather specific base rows by handle (vectorized per block) —
        the cheap path for sparse device-selected rows (TopN/filter)."""
        handles = np.asarray(handles, dtype=np.int64)
        n = len(handles)
        blk_ids = handles // BLOCK_SIZE
        offs = handles % BLOCK_SIZE
        uniq_blocks = np.unique(blk_ids)
        with self._mu:
            snap = {ci: (list(self._blocks[ci]), list(self._valids[ci]))
                    for ci in col_idx}
        cols: List[Column] = []
        for ci in col_idx:
            meta = self.cols[ci]
            blocks, valids = snap[ci]
            dt = blocks[0].dtype if blocks else meta.ftype.np_dtype
            data = np.zeros(n, dtype=dt)
            valid = np.ones(n, dtype=np.bool_)
            for b in uniq_blocks:
                sel = blk_ids == b
                data[sel] = blocks[b][offs[sel]]
                v = valids[b]
                if v is not None:
                    valid[sel] = v[offs[sel]]
            if meta.ftype.kind == TypeKind.STRING and decode_strings:
                data = _decode_dict(data, meta.dictionary)
            cols.append(Column(meta.ftype, data,
                               None if valid.all() else valid))
        return Chunk(cols)

    # ------------------------------------------------------------------
    # MVCC delta (Percolator)
    # ------------------------------------------------------------------
    def prewrite(self, handle: int, op: str, values: Optional[tuple],
                 primary: Tuple[int, int], start_ts: int, ttl_ms: int = 3000,
                 check_ts: Optional[int] = None):
        """check_ts: conflict horizon — defaults to start_ts (optimistic);
        pessimistic lock acquisition and lock-upgrade pass for_update_ts so
        a commit between txn start and lock time is not a conflict
        (2pc.go pessimistic for_update_ts semantics)."""
        with self._mu:
            lk = self.locks.get(handle)
            if lk is not None and lk.start_ts != start_ts:
                raise LockedError((self.table_id, handle), lk.start_ts)
            chain = self.delta.get(handle)
            horizon = check_ts if check_ts is not None else start_ts
            if chain and chain[-1].commit_ts > horizon:
                raise TxnConflictError((self.table_id, handle))
            self.locks[handle] = Lock(start_ts, primary, op, values, ttl_ms)

    def commit(self, handle: int, start_ts: int, commit_ts: int):
        with self._mu:
            lk = self.locks.get(handle)
            if lk is None or lk.start_ts != start_ts:
                # already committed (idempotent) or rolled back
                chain = self.delta.get(handle, [])
                for v in reversed(chain):
                    if v.start_ts == start_ts:
                        return
                raise TxnConflictError((self.table_id, handle))
            del self.locks[handle]
            if lk.op == "lock":
                return
            ver = Version(commit_ts, start_ts, lk.op, lk.values)
            self.delta.setdefault(handle, []).append(ver)
            self.mutations += 1
            if self.on_mutate is not None:
                self.on_mutate()
            if self.persister is not None:
                self.persister.append_delta(handle, ver)

    def rollback(self, handle: int, start_ts: int):
        with self._mu:
            lk = self.locks.get(handle)
            if lk is not None and lk.start_ts == start_ts:
                del self.locks[handle]

    def check_lock(self, handle: int, read_ts: int) -> Optional[Lock]:
        lk = self.locks.get(handle)
        if lk is not None and lk.start_ts <= read_ts and lk.op != "lock":
            return lk
        return None

    def visible_version(self, handle: int, ts: int) -> Optional[Version]:
        chain = self.delta.get(handle)
        if not chain:
            return None
        for v in reversed(chain):
            if v.commit_ts <= ts:
                return v
        return None

    def check_read_horizon(self, ts: int):
        """Fail loudly when a read's TSO predates the base rebuild
        (compaction / bulk load / DDL rebuild): the data the reader should
        see no longer exists, and every read path — copr scan, point get,
        index-side overlay — must surface that rather than returning
        empty/future rows (TiDB's 'snapshot is older than GC safe point')."""
        with self._mu:
            base_ts = self.base_ts
        if 0 < ts < base_ts:
            raise KVError(
                "snapshot is older than the compaction horizon "
                f"(read ts {ts} < base ts {base_ts})")

    def read_row(self, handle: int, ts: int,
                 resolve_locks: bool = True) -> Optional[tuple]:
        """Point read at snapshot ts (None = not found)."""
        with self._mu:
            self.check_read_horizon(ts)
            lk = self.check_lock(handle, ts)
            if lk is not None:
                raise LockedError((self.table_id, handle), lk.start_ts)
            v = self.visible_version(handle, ts)
            if v is not None:
                return v.values if v.op == "put" else None
            if handle < self.base_rows and self.base_ts <= ts:
                return tuple(
                    self.base_chunk(range(self.n_cols), handle, handle + 1).row(0)
                )
            return None

    def delta_overlay(self, ts: int, start: int, end: int):
        """(deleted_base_handles, inserted_rows{handle: values}) visible at ts.

        A 'put' on a base handle counts as delete+insert (update)."""
        deleted: List[int] = []
        inserted: Dict[int, tuple] = {}
        with self._mu:
            for h, chain in self.delta.items():
                if not (start <= h < end):
                    continue
                lk = self.check_lock(h, ts)
                if lk is not None:
                    raise LockedError((self.table_id, h), lk.start_ts)
                v = None
                for ver in reversed(chain):
                    if ver.commit_ts <= ts:
                        v = ver
                        break
                if v is None:
                    continue
                if h < self.base_rows:
                    deleted.append(h)
                if v.op == "put":
                    inserted[h] = v.values
        return deleted, inserted

    def alloc_handle(self) -> int:
        with self._mu:
            h = self.next_handle
            self.next_handle += 1
            return h

    # ------------------------------------------------------------------
    # delta-merge compaction
    # ------------------------------------------------------------------
    def compact(self, ts: int):
        """Fold delta (committed, visible at ts) into fresh base blocks."""
        with self._mu:
            if self.locks:
                raise KVError("cannot compact with live locks")
            deleted, inserted = self.delta_overlay(ts, 0, 1 << 62)
            del_set = set(deleted)
            chunk = self.base_chunk(range(self.n_cols), 0, self.base_rows)
            keep = np.ones(self.base_rows, dtype=np.bool_)
            for h in del_set:
                keep[h] = False
            base = chunk.filter(keep) if self.base_rows else chunk
            extra_rows = [inserted[h] for h in sorted(inserted)]
            arrays, valids = [], []
            for ci, meta in enumerate(self.cols):
                col = base.col(ci)
                data = col.data
                valid = col.validity()
                if extra_rows:
                    ev = [r[ci] for r in extra_rows]
                    evalid = np.array([x is not None for x in ev], dtype=np.bool_)
                    if meta.ftype.kind == TypeKind.STRING:
                        earr = np.empty(len(ev), dtype=object)
                        for j, x in enumerate(ev):
                            earr[j] = x if x is not None else ""
                    else:
                        earr = np.zeros(len(ev), dtype=meta.ftype.np_dtype)
                        for j, x in enumerate(ev):
                            if x is not None:
                                earr[j] = x
                    data = np.concatenate([data, earr])
                    valid = np.concatenate([valid, evalid])
                arrays.append(data)
                valids.append(valid)
            # rebuild
            self._blocks = [[] for _ in self.cols]
            self._valids = [[] for _ in self.cols]
            for meta in self.cols:
                meta.dictionary = None
            self.base_rows = 0
            self.delta.clear()
            self.bulk_load_arrays(arrays, valids, ts)
            self.next_handle = self.base_rows

    def gc(self, safepoint: int) -> int:
        """Drop versions no reader at ts >= safepoint can see; returns the
        number of versions pruned (counted under the store lock).

        Reference: store/tikv/gcworker (gc_worker.go:213-289)."""
        pruned = 0
        with self._mu:
            for h in list(self.delta):
                chain = self.delta[h]
                # keep the newest version <= safepoint plus all > safepoint
                keep_from = 0
                for i, v in enumerate(chain):
                    if v.commit_ts <= safepoint:
                        keep_from = i
                pruned += keep_from
                self.delta[h] = chain[keep_from:]
        return pruned

    def column_stats(self, ci: int) -> Tuple[int, int, bool]:
        """(min, max, has_null) over base blocks for numeric/dict columns.
        Used by the device engine to bound group-code spaces and by the
        planner for range estimation.  Cached per base_version."""
        with self._mu:
            cached = self._col_stats.get(ci)
            if cached is not None:
                return cached
            meta = self.cols[ci]
            lo, hi, has_null = 0, -1, False
            if meta.ftype.kind == TypeKind.STRING:
                lo, hi = 0, len(meta.dictionary or []) - 1
                for v in self._valids[ci]:
                    if v is not None and not v.all():
                        has_null = True
                        break
            else:
                first = True
                for blk, v in zip(self._blocks[ci], self._valids[ci]):
                    if v is None:
                        vals = blk
                    else:
                        if not v.all():
                            has_null = True
                        vals = blk[v]
                    if len(vals) == 0:
                        continue
                    bmin = int(np.floor(float(vals.min())))
                    bmax = int(np.ceil(float(vals.max())))
                    if first:
                        lo, hi, first = bmin, bmax, False
                    else:
                        lo, hi = min(lo, bmin), max(hi, bmax)
            out = (lo, hi, has_null)
            self._col_stats[ci] = out
            return out

    def nbytes(self) -> int:
        with self._mu:
            total = 0
            for blocks in self._blocks:
                for b in blocks:
                    total += b.nbytes if b.dtype != object else len(b) * 8
            return total


def _decode_dict(codes: np.ndarray, dictionary: Optional[List[str]]) -> np.ndarray:
    """int32 codes -> object array of strings (vectorized; out-of-range -> "")."""
    d = np.asarray(dictionary or [], dtype=object)
    if len(d) == 0:
        out = np.empty(len(codes), dtype=object)
        out[:] = ""
        return out
    safe = np.clip(codes, 0, len(d) - 1)
    out = d[safe]
    bad = (codes < 0) | (codes >= len(d))
    if bad.any():
        out = out.copy()
        out[bad] = ""
    return out


def _merge_dictionary(old_dict, new_values, existing_blocks):
    """Merge sorted dictionaries, remapping existing coded blocks in place
    when code order shifts; returns (to_merged codes map, merged dict).
    The single authority for the sorted-merge invariant (three callers)."""
    merged = sorted(set(old_dict) | set(new_values))
    if merged != old_dict and old_dict:
        remap_old = np.array([merged.index(s) for s in old_dict],
                             dtype=np.int32)
        for i, blk in enumerate(existing_blocks):
            existing_blocks[i] = remap_old[blk]
    to_merged = np.array([merged.index(s) for s in new_values],
                         dtype=np.int32)
    return to_merged, merged


def _categorical_encode_fast(arr: np.ndarray):
    """Low-cardinality object-array encode: one vectorized C-level
    equality pass per distinct value instead of a per-element Python
    loop (~20x on TPC-H flag columns).  Returns (codes_by_discovery,
    values) or None when the fast path doesn't apply (cardinality > 256
    or non-str elements whose str() collides with another element)."""
    n = len(arr)
    # cheap cardinality/type probe: a non-categorical column must not pay
    # up to 256 full passes before bailing (compact() re-encodes every
    # string column through here)
    probe = arr[:2048]
    if len({str(x) for x in probe}) > 64:
        return None
    codes = np.full(n, -1, dtype=np.int32)
    values: List[str] = []
    seen = set()
    while n:
        rem = codes < 0
        idx = int(np.argmax(rem))
        if not rem[idx]:
            break
        x = arr[idx]
        if type(x) is not str:
            # non-str elements: object equality would collapse
            # cross-type-equal values (5 vs 5.0) into one entry — the
            # slow path's str() encoding is the semantic authority
            return None
        if x in seen or len(values) >= 256:
            return None  # high cardinality beyond the probe window
        m = rem & (arr == x)
        seen.add(x)
        codes[m] = len(values)
        values.append(x)
    return codes, values


def _dict_encode_merge(arr: np.ndarray, old_dict: Optional[List[str]],
                       existing_blocks: List[np.ndarray]):
    """Encode object-array strings; if a dictionary already exists and new
    values appear, rebuild the dictionary sorted and remap existing blocks
    in place (keeps code order == string order)."""
    fast = _categorical_encode_fast(arr)
    if fast is not None:
        raw_codes, raw_values = fast
        order = sorted(range(len(raw_values)),
                       key=lambda i: raw_values[i])
        values = [raw_values[i] for i in order]
        recode = np.empty(len(raw_values), dtype=np.int32)
        for new_i, old_i in enumerate(order):
            recode[old_i] = new_i
        sorted_codes = recode[raw_codes]
        if old_dict is None:
            return sorted_codes, values
        to_merged, merged = _merge_dictionary(old_dict, values,
                                              existing_blocks)
        return to_merged[sorted_codes], merged
    values = sorted(set(str(x) for x in arr))
    if old_dict is None:
        dictionary = values
        lookup = {s: i for i, s in enumerate(dictionary)}
        codes = np.fromiter(
            (lookup[str(x)] for x in arr), dtype=np.int32, count=len(arr)
        )
        return codes, dictionary
    merged = sorted(set(old_dict) | set(values))
    if merged != old_dict:
        remap = np.array(
            [merged.index(s) for s in old_dict], dtype=np.int32
        ) if old_dict else np.zeros(0, np.int32)
        for i, blk in enumerate(existing_blocks):
            existing_blocks[i] = remap[blk]
    lookup = {s: i for i, s in enumerate(merged)}
    codes = np.fromiter(
        (lookup[str(x)] for x in arr), dtype=np.int32, count=len(arr)
    )
    return codes, merged
