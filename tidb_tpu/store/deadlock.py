"""Wait-for-graph deadlock detection for pessimistic lock waits.

Reference: util/deadlock/deadlock.go:22-130 — a Detector keyed by
transaction start_ts; Detect(txn, waitFor) walks the existing edges and
reports a cycle before the edge is inserted, so the REQUESTING transaction
is the victim (ErrDeadlock), matching the reference's first-detected-aborts
policy.
"""

from __future__ import annotations

import threading
from typing import Dict, Set
from ..util_concurrency import make_lock


class DeadlockDetector:
    def __init__(self):
        self._mu = make_lock("store.deadlock:DeadlockDetector._mu")
        # waiter start_ts -> set of holder start_ts it waits for
        self._edges: Dict[int, Set[int]] = {}

    def detect(self, waiter: int, holder: int) -> bool:
        """Register waiter->holder; True (and no edge) if that would close
        a cycle — the caller must abort as the deadlock victim."""
        if waiter == holder:
            return False
        with self._mu:
            # DFS from holder through existing edges looking for waiter
            stack, seen = [holder], set()
            while stack:
                t = stack.pop()
                if t == waiter:
                    return True
                if t in seen:
                    continue
                seen.add(t)
                stack.extend(self._edges.get(t, ()))
            self._edges.setdefault(waiter, set()).add(holder)
            return False

    def clean_up_wait_for(self, waiter: int, holder: int):
        """Drop one edge after the wait ends (lock acquired or aborted)."""
        with self._mu:
            s = self._edges.get(waiter)
            if s is not None:
                s.discard(holder)
                if not s:
                    del self._edges[waiter]

    def clean_up(self, txn: int):
        """Txn finished: drop every edge it owns (detector CleanUp)."""
        with self._mu:
            self._edges.pop(txn, None)
