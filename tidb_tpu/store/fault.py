"""Fault-injection hooks for the storage layer.

Reference: three mechanisms in the reference (SURVEY.md §5): failpoint
injections, kv.InjectedStore error wrappers (kv/fault_injection.go:22-80),
and mocktikv cluster manipulation / WithHijackClient.  Here a single hook
registry the fake backend consults; tests arm/disarm named failpoints.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional


class FailpointRegistry:
    def __init__(self):
        self._mu = threading.Lock()
        self._points: Dict[str, Callable] = {}

    def enable(self, name: str, action: Callable):
        """action() is invoked at the site; raise inside it to inject an
        error, return to no-op.  It may count calls to fire once, etc."""
        with self._mu:
            self._points[name] = action

    def disable(self, name: str):
        with self._mu:
            self._points.pop(name, None)

    def clear(self):
        with self._mu:
            self._points.clear()

    def hit(self, name: str, **ctx):
        with self._mu:
            action = self._points.get(name)
        if action is not None:
            action(**ctx)


# process-global registry (tests reset via clear())
FAILPOINTS = FailpointRegistry()


def once(exc: Exception) -> Callable:
    """Helper: raise `exc` on first hit only (stale-epoch style transients)."""
    state = {"fired": False}

    def action(**ctx):
        if not state["fired"]:
            state["fired"] = True
            raise exc

    return action


def always(exc: Exception) -> Callable:
    def action(**ctx):
        raise exc

    return action
