"""Fault-injection hooks for the storage layer.

Reference: three mechanisms in the reference (SURVEY.md §5): failpoint
injections, kv.InjectedStore error wrappers (kv/fault_injection.go:22-80),
and mocktikv cluster manipulation / WithHijackClient.  Here a single hook
registry the fake backend consults; tests arm/disarm named failpoints.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional
from ..util_concurrency import make_lock


class FailpointRegistry:
    def __init__(self):
        self._mu = make_lock("store.fault:FailpointRegistry._mu")
        self._points: Dict[str, Callable] = {}

    def enable(self, name: str, action: Callable):
        """action() is invoked at the site; raise inside it to inject an
        error, return to no-op.  It may count calls to fire once, etc."""
        with self._mu:
            self._points[name] = action

    def disable(self, name: str):
        with self._mu:
            self._points.pop(name, None)

    def clear(self):
        with self._mu:
            self._points.clear()

    def armed(self) -> List[str]:
        """Names currently armed (leak detection: the autouse conftest
        fixture fails any test that leaves a failpoint enabled)."""
        with self._mu:
            return sorted(self._points)

    def hit(self, name: str, **ctx):
        with self._mu:
            action = self._points.get(name)
        if action is not None:
            action(**ctx)


# process-global registry (tests reset via clear())
FAILPOINTS = FailpointRegistry()


@contextmanager
def failpoint(name: str, action: Callable):
    """Scoped arming: `with failpoint("2pc/prewrite", once(exc)): ...`
    guarantees disarm on every exit path — replaces the hand-rolled
    try/finally enable/disable pairs tests used to carry."""
    FAILPOINTS.enable(name, action)
    try:
        yield FAILPOINTS
    finally:
        FAILPOINTS.disable(name)


def once(exc: Exception) -> Callable:
    """Helper: raise `exc` on first hit only (stale-epoch style transients)."""
    state = {"fired": False}

    def action(**ctx):
        if not state["fired"]:
            state["fired"] = True
            raise exc

    return action


def always(exc: Exception) -> Callable:
    def action(**ctx):
        raise exc

    return action
