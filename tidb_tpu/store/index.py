"""Secondary index structures over base blocks.

Reference: table/index.go + tablecodec index-key layout (t{tid}_i{iid}...)
— TiDB materializes indexes as KV entries maintained on every write.  The
columnar TPU-native design instead builds a **sorted key matrix per index
lazily from base blocks** (one np.lexsort, cached per base_version) and
overlays the MVCC delta at query time, the same base+delta overlay the scan
path uses.  Writes stay O(1); the first index read after a bulk load pays
one sort — the analytical trade.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..types import TypeKind
from ..util_concurrency import make_lock


@dataclass
class SortedIndex:
    """cols: per-index-column arrays in NATIVE dtype (int64/float64/int32),
    sorted lexicographically; handles aligned.  Rows with NULL in any key
    column are excluded (lookups implement WHERE semantics, where NULL
    never matches)."""

    col_offsets: Tuple[int, ...]
    cols: List[np.ndarray]
    handles: np.ndarray
    base_version: int

    def search_range(self, low: Optional[tuple], high: Optional[tuple],
                     low_open: bool = False,
                     high_open: bool = False) -> np.ndarray:
        """Handles of rows with low <(=) key <(=) high; bounds are value
        tuples over a PREFIX of the index columns (None = unbounded)."""
        lo_i, hi_i = self.search_slice(low, high, low_open, high_open)
        return self.handles[lo_i:hi_i]

    def search_slice(self, low: Optional[tuple], high: Optional[tuple],
                     low_open: bool = False,
                     high_open: bool = False) -> Tuple[int, int]:
        """(lo, hi) positions of the matching run — the covering
        IndexReader serves key columns straight from cols[j][lo:hi]."""
        n = len(self.handles)
        if n == 0:
            return 0, 0
        lo_i = self._bound(low, "right" if low_open else "left") \
            if low is not None else 0
        hi_i = self._bound(high, "left" if high_open else "right") \
            if high is not None else n
        return (0, 0) if lo_i >= hi_i else (lo_i, hi_i)

    def _bound(self, key: tuple, side: str) -> int:
        lo, hi = 0, len(self.handles)
        for ci, v in enumerate(key):
            col = self.cols[ci]
            if ci == len(key) - 1:
                return int(lo + np.searchsorted(col[lo:hi], v, side))
            eq_l = int(lo + np.searchsorted(col[lo:hi], v, "left"))
            eq_r = int(lo + np.searchsorted(col[lo:hi], v, "right"))
            lo, hi = eq_l, eq_r
            if lo >= hi:
                return lo
        return lo


class IndexManager:
    """Per-table cache of SortedIndex keyed by column tuple + base_version."""

    def __init__(self):
        self._cache: Dict[tuple, SortedIndex] = {}
        self._mu = make_lock("store.index:IndexManager._mu")

    def get(self, store, col_offsets: Sequence[int]) -> SortedIndex:
        key = tuple(col_offsets)
        with self._mu:
            idx = self._cache.get(key)
            if idx is not None and idx.base_version == store.base_version:
                return idx
        idx = self._build(store, key)
        with self._mu:
            self._cache[key] = idx
        return idx

    def peek(self, col_offsets) -> "SortedIndex | None":
        """Cached index artifact or None — NEVER builds (ADMIN CHECK uses
        this: verifying a freshly derived index against its own source
        would be tautological)."""
        with self._mu:
            return self._cache.get(tuple(col_offsets))

    def put(self, col_offsets: tuple, idx: "SortedIndex"):
        """Register a prebuilt index (online add-index backfill artifact)."""
        with self._mu:
            self._cache[tuple(col_offsets)] = idx

    def invalidate(self, col_offsets) -> bool:
        """Drop a cached artifact so the next get() rebuilds from base
        rows — ADMIN RECOVER/CLEANUP INDEX (util/admin.go:281-312 role:
        re-derive the index from the row data)."""
        with self._mu:
            return self._cache.pop(tuple(col_offsets), None) is not None

    def _build(self, store, col_offsets: tuple) -> SortedIndex:
        n = store.base_rows
        cols: List[np.ndarray] = []
        valid = np.ones(n, dtype=np.bool_)
        if n:
            chunk = store.base_chunk(list(col_offsets), 0, n,
                                     decode_strings=False)
            for i in range(len(col_offsets)):
                c = chunk.col(i)
                valid &= c.validity()
                cols.append(c.data)
        if n and cols:
            handles = np.arange(n, dtype=np.int64)[valid]
            kept = [c[valid] for c in cols]
        else:
            kept = [np.zeros(0) for _ in col_offsets]
            handles = np.zeros(0, dtype=np.int64)
        return finalize_sorted_index(col_offsets, kept, handles,
                                     store.base_version)


def finalize_sorted_index(col_offsets, key_cols, handles,
                          base_version: int) -> SortedIndex:
    """Sort collected (key, handle) arrays into a SortedIndex — shared by
    the lazy builder above and the online add-index backfill so ordering/
    empty-case semantics cannot diverge."""
    if len(handles):
        order = np.lexsort(tuple(reversed(key_cols)))
        key_cols = [c[order] for c in key_cols]
        handles = handles[order]
    else:
        key_cols = [np.asarray(c) for c in key_cols]
        handles = np.asarray(handles, dtype=np.int64)
    return SortedIndex(tuple(col_offsets), list(key_cols), handles,
                       base_version)
