"""KV / coprocessor abstraction layer.

Reference: kv/kv.go — Storage (:324), Snapshot (:304), Client (:197),
Request (:245), Response (:295).  The seams kept verbatim (they are
transport-agnostic and proven); the *content* differs: a "key" is a
(table_id, handle) pair, a scan range is a handle range, and the request
payload is our DAG IR instead of tipb protobufs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterator, List, Optional, Sequence, Tuple

# A row key addresses (table_id, handle).  Index keys address
# (table_id, index_id, encoded_value, handle).
RowKey = Tuple[int, int]

# canonical per-task retry sleep budget (backoff.go maxSleep default);
# distsql.Backoffer and the tidb_backoff_budget_ms sysvar both anchor here
DEFAULT_BACKOFF_BUDGET_MS = 10_000


@dataclass(frozen=True)
class KeyRange:
    """Half-open handle range [start, end) within one table."""

    table_id: int
    start: int
    end: int

    def intersect(self, other: "KeyRange") -> Optional["KeyRange"]:
        if self.table_id != other.table_id:
            return None
        s, e = max(self.start, other.start), min(self.end, other.end)
        if s >= e:
            return None
        return KeyRange(self.table_id, s, e)


@dataclass
class CopRequest:
    """A coprocessor request: run `dag` over `ranges` at snapshot `ts`.

    Reference: kv.Request (kv/kv.go:245) + tipb.DAGRequest.  Fields kept:
    concurrency, keep_order, streaming, target engine routing.
    """

    dag: dict  # serialized DAG IR (copr/ir.py)
    ranges: List[KeyRange]
    ts: int
    concurrency: int = 8
    keep_order: bool = False
    streaming: bool = False
    # "tpu" | "cpu" — per-request engine routing, the analog of
    # kv.StoreType TiKV/TiFlash (kv/kv.go:222-232)
    engine: str = "tpu"
    # total per-task retry sleep budget (backoff.go maxSleep analog);
    # sessions override via the tidb_backoff_budget_ms sysvar
    backoff_budget_ms: int = DEFAULT_BACKOFF_BUDGET_MS
    # runtime payloads resolved at execution time (numpy arrays), e.g.
    # probe_keys_{n} for JoinProbeIR — the analog of IndexLookUpJoin
    # building inner requests from outer rows
    aux: Optional[dict] = None
    # filled by the mesh engine when it declines the request: surfaced in
    # EXPLAIN ANALYZE so a flagship query quietly leaving the device is
    # visible, not just a metrics counter (VERDICT r2 weak #5)
    mesh_reject_reason: Optional[str] = None


@dataclass
class CopResponse:
    """One region's (or one batch's) worth of results."""

    chunks: List = field(default_factory=list)  # list[Chunk]
    exec_summary: dict = field(default_factory=dict)


class StoreClient:
    """Narrow pushdown boundary: Send(CopRequest) -> iterator of CopResponse.

    Reference: kv.Client (kv/kv.go:197-203).
    """

    def send(self, req: CopRequest) -> Iterator[CopResponse]:
        raise NotImplementedError

    def is_request_supported(self, req: CopRequest) -> bool:
        return True


class Storage:
    """Storage = catalog of table stores + txn entry points + cop client.

    Reference: kv.Storage (kv/kv.go:324).
    """

    def begin(self, start_ts: Optional[int] = None):
        raise NotImplementedError

    def snapshot(self, ts: int):
        raise NotImplementedError

    def get_client(self) -> StoreClient:
        raise NotImplementedError

    def current_ts(self) -> int:
        raise NotImplementedError
