"""Timestamp oracle.

Reference: store/tikv/oracle/oracle.go:22-40 — TSO as physical_ms<<18 |
logical, with futures from PD.  Here a process-local monotonic oracle; the
multi-host story replaces this with a host-0-owned service over DCN.
"""

from __future__ import annotations

import threading
import time
from ..util_concurrency import make_lock

_LOGICAL_BITS = 18


def compose_ts(physical_ms: int, logical: int) -> int:
    return (physical_ms << _LOGICAL_BITS) | logical


def extract_physical(ts: int) -> int:
    return ts >> _LOGICAL_BITS


class Oracle:
    def __init__(self):
        self._lock = make_lock("store.oracle:Oracle._lock")
        self._last_physical = 0
        self._logical = 0

    def get_timestamp(self) -> int:
        with self._lock:
            phys = int(time.time() * 1000)
            if phys <= self._last_physical:
                phys = self._last_physical
                self._logical += 1
                if self._logical >= (1 << _LOGICAL_BITS):
                    phys += 1
                    self._logical = 0
            else:
                self._logical = 0
            self._last_physical = phys
            return compose_ts(phys, self._logical)

    def advance_to(self, ts: int):
        """Never hand out a timestamp <= ts again (recovery: the TSO must
        move past every persisted commit, like PD restarting from etcd)."""
        with self._lock:
            phys = extract_physical(ts)
            if phys > self._last_physical:
                self._last_physical = phys
                self._logical = ts & ((1 << _LOGICAL_BITS) - 1)
            elif phys == self._last_physical:
                self._logical = max(
                    self._logical, ts & ((1 << _LOGICAL_BITS) - 1)
                )

    def is_expired(self, lock_ts: int, ttl_ms: int) -> bool:
        return int(time.time() * 1000) >= extract_physical(lock_ts) + ttl_ms
