"""Table data durability: base-block snapshots + committed-delta log.

Recovery model mirrors the reference (SURVEY.md §3.4): all durable state is
reconstructible from the store — a restarting node reloads and serves; device
memory is purely a cache.  Layout per table under <data_dir>/tables/:

    t<id>.base.npz   immutable base blocks (string cols as dict codes +
                     dictionary), written atomically on bulk load / compact
    t<id>.delta.log  append-only JSON lines of committed MVCC versions
                     (prewrite locks are volatile BY DESIGN: a crash aborts
                     in-flight transactions exactly like Percolator's lock
                     resolution path, mvcc_leveldb.go's lock column family)

The delta log truncates whenever the base snapshot is rewritten (compaction
folds the log in, the reference's delta-merge).
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Optional

import numpy as np

from ..types import TypeKind
from .blockstore import TableStore, Version


class CorruptDeltaLogError(RuntimeError):
    """A delta-log record BEFORE the final line failed to parse: not a
    torn tail (crash-truncation only ever clips the end) but real
    corruption — surfaced instead of silently dropping committed data."""


class TablePersister:
    def __init__(self, data_dir: str, table_id: int):
        self.dir = os.path.join(data_dir, "tables")
        os.makedirs(self.dir, exist_ok=True)
        self.base_path = os.path.join(self.dir, f"t{table_id}.base.npz")
        self.delta_path = os.path.join(self.dir, f"t{table_id}.delta.log")
        self._delta_f = None

    # ---- write side ----------------------------------------------------
    def save_base(self, store: TableStore):
        """Atomic snapshot of the base blocks; truncates the delta log
        (callers hold the store lock or are single-threaded loaders)."""
        arrays = {}
        meta = {
            "base_rows": store.base_rows,
            "base_ts": store.base_ts,
            "next_handle": store.next_handle,
            "dicts": [c.dictionary for c in store.cols],
        }
        for ci, colmeta in enumerate(store.cols):
            blocks = store._blocks[ci]
            valids = store._valids[ci]
            if blocks:
                cat = np.concatenate(blocks)
                if cat.dtype == object:
                    # JSON / wide-decimal columns: pickle-free persistence
                    # as unicode (wide decimals as digit strings)
                    cat = np.array([str(x) for x in cat])
                arrays[f"d{ci}"] = cat
            else:
                arrays[f"d{ci}"] = np.zeros(0, dtype=np.int64)
            vparts = [
                v if v is not None else np.ones(len(b), dtype=np.bool_)
                for b, v in zip(blocks, valids)
            ]
            arrays[f"v{ci}"] = (
                np.concatenate(vparts) if vparts
                else np.zeros(0, dtype=np.bool_)
            )
        fd, tmp = tempfile.mkstemp(dir=self.dir, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez(f, meta=json.dumps(meta), **arrays)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.base_path)
            self._fsync_dir()
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        # the delta log is NOT simply truncated: committed versions may
        # still live only in memory (e.g. INSERTs followed by a bulk load).
        # Rewrite it from the in-memory delta so base+log always equal the
        # full committed state.
        self._close_delta()
        if store.delta:
            fd, tmp = tempfile.mkstemp(dir=self.dir, suffix=".tmp")
            try:
                with os.fdopen(fd, "w") as f:
                    for h in sorted(store.delta):
                        for ver in store.delta[h]:
                            rec = [h, ver.commit_ts, ver.start_ts, ver.op,
                                   ver.values]
                            f.write(json.dumps(rec, default=_np_scalar) + "\n")
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self.delta_path)
                self._fsync_dir()
            except BaseException:
                if os.path.exists(tmp):
                    os.unlink(tmp)
                raise
        elif os.path.exists(self.delta_path):
            os.unlink(self.delta_path)
            self._fsync_dir()

    def append_delta(self, handle: int, ver: Version):
        """Durable-on-commit: the record hits the platters before commit()
        returns, the reference's model (mvcc_leveldb.go:39 — leveldb WAL
        syncs per write batch)."""
        if self._delta_f is None:
            self._delta_f = open(self.delta_path, "a")
        rec = [handle, ver.commit_ts, ver.start_ts, ver.op, ver.values]
        self._delta_f.write(json.dumps(rec, default=_np_scalar) + "\n")
        self._delta_f.flush()
        os.fsync(self._delta_f.fileno())

    def _fsync_dir(self):
        """Make a rename/unlink durable: fsync the containing directory."""
        fd = os.open(self.dir, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def _close_delta(self):
        if self._delta_f is not None:
            self._delta_f.close()
            self._delta_f = None

    def remove(self):
        self._close_delta()
        for p in (self.base_path, self.delta_path):
            if os.path.exists(p):
                os.unlink(p)

    # ---- read side -----------------------------------------------------
    def load(self, store: TableStore) -> bool:
        """Restore base + delta into a freshly created store; False if
        nothing exists on disk.  A table written only through DML has a
        delta log but no base snapshot — both parts are independent."""
        found = False
        if os.path.exists(self.base_path):
            found = True
            self._load_base(store)
        if os.path.exists(self.delta_path):
            found = True
            # two STREAMED passes (a post-write-burst log can be large;
            # never materialize it): first find the final record's line
            # index — the only one torn-tail tolerance may drop
            last_payload = None
            with open(self.delta_path, "rb") as f:
                for i, bline in enumerate(f):
                    if bline.strip():
                        last_payload = i
            torn_offset = None
            unterminated = False
            with open(self.delta_path, "rb") as f:
                offset = 0
                for i, bline in enumerate(f):
                    line_start = offset
                    offset += len(bline)
                    payload = bline.decode("utf-8", "replace").strip()
                    if not payload:
                        continue
                    if i == last_payload and not bline.endswith(b"\n"):
                        unterminated = True
                    try:
                        h, cts, sts, op, values = json.loads(payload)
                    except (ValueError, TypeError) as e:
                        if i == last_payload:
                            # torn tail: the writer died mid-append — the
                            # record never committed (commit() returns only
                            # after fsync of the FULL line), so dropping it
                            # IS the correct recovery (leveldb WAL
                            # semantics: a truncated final record drops)
                            import logging

                            from ..metrics import REGISTRY

                            REGISTRY.inc("delta_log_torn_tail_total")
                            logging.getLogger("tidb_tpu.store").warning(
                                "dropping torn final delta-log record in "
                                "%s (%d bytes): %s",
                                self.delta_path, len(payload), e)
                            torn_offset = line_start
                            break
                        raise CorruptDeltaLogError(
                            f"{self.delta_path}: corrupt record at line "
                            f"{i + 1} (not the final line): {e}") from e
                    store.delta.setdefault(h, []).append(
                        Version(cts, sts, op,
                                tuple(values) if values is not None else None)
                    )
                    store.next_handle = max(store.next_handle, h + 1)
            if torn_offset is not None or unterminated:
                # REPAIR the log before accepting new appends: the next
                # append_delta opens in 'a' mode, and a record written
                # after torn bytes (or after a complete-but-unterminated
                # final line) would merge into one unparseable line —
                # silently losing committed rows on the following reopen
                with open(self.delta_path, "r+b") as f:
                    if torn_offset is not None:
                        f.truncate(torn_offset)
                    else:
                        f.seek(0, os.SEEK_END)
                        f.write(b"\n")
                    f.flush()
                    os.fsync(f.fileno())
                self._fsync_dir()
        return found

    def _load_base(self, store: TableStore):
        with np.load(self.base_path, allow_pickle=False) as z:
            meta = json.loads(str(z["meta"]))
            for ci, colmeta in enumerate(store.cols):
                data = z[f"d{ci}"]
                valid = z[f"v{ci}"]
                if (colmeta.ftype.np_dtype == object
                        and data.dtype.kind == "U"):
                    wide_dec = colmeta.ftype.kind == TypeKind.DECIMAL
                    obj = np.empty(len(data), dtype=object)
                    for i, txt in enumerate(data):
                        obj[i] = int(txt) if wide_dec else str(txt)
                    data = obj
                store._blocks[ci] = []
                store._valids[ci] = []
                if len(data):
                    # re-block without re-encoding: dictionaries restore
                    # verbatim, so codes stay valid
                    from .blockstore import BLOCK_SIZE

                    for off in range(0, len(data), BLOCK_SIZE):
                        blk = data[off: off + BLOCK_SIZE]
                        vb = valid[off: off + BLOCK_SIZE]
                        store._blocks[ci].append(np.ascontiguousarray(blk))
                        store._valids[ci].append(
                            None if vb.all() else vb.copy()
                        )
                colmeta.dictionary = meta["dicts"][ci]
        store.base_rows = meta["base_rows"]
        store.base_ts = meta["base_ts"]
        store.next_handle = meta["next_handle"]
        # secondary indexes rebuild lazily: IndexManager caches are keyed on
        # base_version, which is bumped here
        store.base_version += 1
        store._col_stats.clear()


def _np_scalar(o):
    if hasattr(o, "item"):
        return o.item()
    raise TypeError(f"not JSON serializable: {type(o)}")


class JsonStatePersister:
    """Small durable JSON document with the same crash contract as the
    table persister: atomic tmp-write + rename + dir fsync, torn/corrupt
    files load as `None` instead of crashing the owner.  Backs the
    coordination plane's membership/handoff state (ISSUE 12: a
    coordinator restart replays the epoch instead of starting at 0)."""

    def __init__(self, path: str):
        self.path = path
        self.dir = os.path.dirname(os.path.abspath(path)) or "."
        os.makedirs(self.dir, exist_ok=True)

    def save(self, doc: dict):
        fd, tmp = tempfile.mkstemp(dir=self.dir, suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f, default=_np_scalar)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            dfd = os.open(self.dir, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def load(self) -> Optional[dict]:
        if not os.path.exists(self.path):
            return None
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                doc = json.load(f)
            return doc if isinstance(doc, dict) else None
        except (ValueError, OSError):
            return None  # torn write: the owner starts fresh

    def remove(self):
        if os.path.exists(self.path):
            os.unlink(self.path)
