"""Range-sharded regions with epochs.

Reference: store/tikv/region_cache.go (region->leader map, invalidation),
mocktikv/cluster.go:70-412 (simulated multi-region topology with splits,
SplitTable used by tests to create genuine multi-region scans).

A Region covers a half-open handle range of one table.  Regions are the
fan-out unit for coprocessor requests; on TPU they map to shard groups of
the device mesh.  Epochs let fault-injection tests exercise the stale-routing
retry loop exactly like the reference (region_request.go:281 onRegionError).
"""

from __future__ import annotations

import bisect
import threading
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Tuple

from ..errors import RegionError
from .kv import KeyRange
from ..util_concurrency import make_rlock


@dataclass
class Region:
    region_id: int
    table_id: int
    start: int  # inclusive handle
    end: int  # exclusive handle (1<<62 = +inf)
    epoch: int = 1
    leader_store: int = 0

    def range(self) -> KeyRange:
        return KeyRange(self.table_id, self.start, self.end)


INF = 1 << 62


class RegionManager:
    def __init__(self, n_stores: int = 1):
        self.n_stores = n_stores
        self._next_id = 1
        self._mu = make_rlock("store.regions:RegionManager._mu")
        # table_id -> list[Region] sorted by start, covering [0, INF)
        self._by_table: Dict[int, List[Region]] = {}

    def _new_region(self, table_id: int, start: int, end: int) -> Region:
        r = Region(self._next_id, table_id, start, end,
                   leader_store=self._next_id % self.n_stores)
        self._next_id += 1
        return r

    def bootstrap_table(self, table_id: int):
        with self._mu:
            if table_id not in self._by_table:
                self._by_table[table_id] = [self._new_region(table_id, 0, INF)]

    def drop_table(self, table_id: int):
        with self._mu:
            self._by_table.pop(table_id, None)

    def regions_of(self, table_id: int) -> List[Region]:
        """Snapshot of routing info (copies — a caller's view can go stale,
        which is exactly what the epoch-check/retry path exercises)."""
        with self._mu:
            self.bootstrap_table(table_id)
            return [replace(r) for r in self._by_table[table_id]]

    def split_at(self, table_id: int, handles: List[int]):
        """Split so that each handle in `handles` starts a new region."""
        with self._mu:
            self.bootstrap_table(table_id)
            regions = self._by_table[table_id]
            for h in sorted(set(handles)):
                idx = self._locate_idx(regions, h)
                r = regions[idx]
                if r.start == h:
                    continue
                left = self._new_region(table_id, r.start, h)
                r.start = h
                r.epoch += 1
                regions.insert(idx, left)

    def split_even(self, table_id: int, n: int, total_rows: int):
        """Split [0,total_rows) into n regions (mocktikv SplitTable analog,
        cluster.go:394-412)."""
        if n <= 1 or total_rows <= 0:
            return
        step = max(total_rows // n, 1)
        self.split_at(table_id, [i * step for i in range(1, n)])

    def merge_all(self, table_id: int):
        with self._mu:
            if table_id in self._by_table:
                self._by_table[table_id] = [self._new_region(table_id, 0, INF)]

    @staticmethod
    def _locate_idx(regions: List[Region], handle: int) -> int:
        starts = [r.start for r in regions]
        return max(bisect.bisect_right(starts, handle) - 1, 0)

    def locate(self, krange: KeyRange) -> List[Tuple[Region, KeyRange]]:
        """Split one key range across the regions covering it."""
        out = []
        with self._mu:
            self.bootstrap_table(krange.table_id)
            for r in self._by_table[krange.table_id]:
                clipped = r.range().intersect(krange)
                if clipped is not None:
                    out.append((replace(r), clipped))
        return out

    def check_epoch(self, region_id: int, epoch: int, table_id: int):
        """Raise RegionError if the caller's routing info is stale
        (the reference's ErrRegionEpochNotMatch path)."""
        with self._mu:
            for r in self._by_table.get(table_id, []):
                if r.region_id == region_id:
                    if r.epoch != epoch:
                        raise RegionError(
                            f"region {region_id} epoch {epoch} != {r.epoch}"
                        )
                    return
            raise RegionError(f"region {region_id} not found")
