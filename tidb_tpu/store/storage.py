"""BlockStorage: the in-process storage service (catalog of table stores +
regions + oracle + coprocessor client).

Reference: the kv.Storage implementations — tikvStore (store/tikv/kv.go:130)
and the test-critical NewMockTikvStore (store/mockstore/tikv.go:100).  One
class serves both roles here: it IS the real storage engine (blocks live in
host RAM, compute on TPU) and it IS the deterministic test backend (regions,
epochs, failpoints).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from ..errors import KVError, RegionError
from ..types import FieldType
from .blockstore import TableStore
from .fault import FAILPOINTS
from .kv import CopRequest, CopResponse, KeyRange, Storage, StoreClient
from .oracle import Oracle
from .regions import RegionManager
from .txn import Transaction
from ..util_concurrency import make_rlock


class BlockStorage(Storage):
    def __init__(self, n_stores: int = 1, data_dir: Optional[str] = None):
        self.oracle = Oracle()
        self.regions = RegionManager(n_stores=n_stores)
        from .deadlock import DeadlockDetector

        self.deadlock = DeadlockDetector()
        # live in-process txns: a LIVE holder's locks are never resolved by
        # waiters (the TTL path only covers txns this process no longer
        # tracks — crashed processes start with an empty registry)
        self._live_txns: set = set()
        # pinned historical read TSOs (SET tidb_snapshot): compaction and
        # GC must not advance past the oldest pin, or historical reads
        # would silently lose their base blocks (ADVICE r4 #1)
        self._pinned_reads: Dict[int, int] = {}
        self._pin_seq = 0
        self._tables: Dict[int, TableStore] = {}
        self._mu = make_rlock("store.storage:BlockStorage._mu")
        self._client = CoprClient(self)
        self.data_dir = data_dir
        self._data_version = 0

    # ---- catalog -------------------------------------------------------
    def create_table(self, table_id: int, columns: List[Tuple[str, FieldType]]) -> TableStore:
        with self._mu:
            if table_id in self._tables:
                raise KVError(f"table {table_id} exists in storage")
            ts = TableStore(table_id, columns)
            if self.data_dir is not None:
                from .persist import TablePersister

                ts.persister = TablePersister(self.data_dir, table_id)
            ts.on_mutate = self._bump_data_version
            self._tables[table_id] = ts
            self.regions.bootstrap_table(table_id)
            return ts

    def load_persisted(self):
        """Recovery: restore every table's base+delta from data_dir.

        Reference model (SURVEY.md §3.4): recovery = reload; in-flight
        prewrite locks are volatile so crashed txns abort naturally."""
        with self._mu:
            max_ts = 0
            for ts_store in self._tables.values():
                if ts_store.persister is not None:
                    ts_store.persister.load(ts_store)
                max_ts = max(max_ts, ts_store.base_ts)
                for chain in ts_store.delta.values():
                    if chain:
                        max_ts = max(max_ts, chain[-1].commit_ts)
            # the TSO must move past every persisted commit
            self.oracle.advance_to(max_ts + 1)

    def detach_table(self, table_id: int):
        """Remove a table from the live catalog WITHOUT destroying its
        data or files — the store object moves to the caller (catalog
        recycle bin for RECOVER TABLE).  The reference's analog: dropped
        data stays in TiKV until the GC worker passes the drop TSO."""
        with self._mu:
            t = self._tables.pop(table_id, None)
            if t is not None and t.persister is not None:
                t.persister._close_delta()
            self.regions.drop_table(table_id)
            return t

    def attach_table(self, table_id: int, store: TableStore):
        """Re-register a detached store (RECOVER TABLE flashback)."""
        with self._mu:
            if table_id in self._tables:
                raise KVError(f"table {table_id} exists in storage")
            self._tables[table_id] = store
            store.on_mutate = self._bump_data_version
            if self.data_dir is not None and store.persister is None:
                from .persist import TablePersister

                store.persister = TablePersister(self.data_dir, table_id)
            self.regions.bootstrap_table(table_id)
            self._bump_data_version()

    def drop_table(self, table_id: int, keep_files: bool = False):
        with self._mu:
            t = self._tables.pop(table_id, None)
            if t is not None and t.persister is not None:
                if keep_files:
                    # ALTER rebuild: the replacement store atomically
                    # overwrites the same paths; just release the handle
                    t.persister._close_delta()
                else:
                    t.persister.remove()
            self.regions.drop_table(table_id)

    def table(self, table_id: int) -> TableStore:
        t = self._tables.get(table_id)
        if t is None:
            raise KVError(f"no storage for table {table_id}")
        return t

    def has_table(self, table_id: int) -> bool:
        return table_id in self._tables

    def table_ids(self):
        with self._mu:
            return list(self._tables.keys())

    # ---- kv.Storage interface ------------------------------------------
    def begin(self, start_ts: Optional[int] = None, pessimistic: bool = False) -> Transaction:
        txn = Transaction(
            self, start_ts or self.oracle.get_timestamp(), pessimistic
        )
        with self._mu:
            self._live_txns.add(txn.start_ts)
        return txn

    def txn_alive(self, start_ts: int) -> bool:
        return start_ts in self._live_txns

    def txn_finished(self, start_ts: int):
        with self._mu:
            self._live_txns.discard(start_ts)

    def live_txn_floor(self):
        """Oldest live txn start_ts, or None (snapshot under the lock)."""
        with self._mu:
            return min(self._live_txns) if self._live_txns else None

    # ---- pinned historical reads (tidb_snapshot) ----------------------
    def pin_read(self, ts: int) -> int:
        """Register a long-lived historical read TSO; returns an unpin
        token.  GC/compaction treat pinned TSOs like live-txn snapshots."""
        with self._mu:
            self._pin_seq += 1
            self._pinned_reads[self._pin_seq] = ts
            return self._pin_seq

    def unpin_read(self, token: int):
        with self._mu:
            self._pinned_reads.pop(token, None)

    def pinned_read_floor(self):
        with self._mu:
            return (min(self._pinned_reads.values())
                    if self._pinned_reads else None)

    def data_version(self) -> int:
        """Monotonic counter bumped on bulk load, compaction, and committed
        DML (via TableStore.on_mutate) — O(1) plan-cache invalidation with
        no cross-lock iteration of live delta dicts."""
        return self._data_version

    def _bump_data_version(self):
        self._data_version += 1

    def current_ts(self) -> int:
        return self.oracle.get_timestamp()

    def get_client(self) -> "CoprClient":
        return self._client

    def maybe_compact(self, table_id: int, threshold: int = 4096):
        """Delta-merge when the row store outgrows the threshold (TiFlash's
        delta-merge policy): folds committed delta into fresh base blocks so
        scans stay columnar (and strings dictionary-encoded).  Skipped when
        live locks exist.  NOTE: compaction advances base_ts, so snapshots
        older than the merge no longer see the table — in-process sessions
        take fresh timestamps per statement, and long-lived historical reads
        are bounded by the GC safepoint exactly as in the reference.
        """
        t = self._tables.get(table_id)
        if t is None or t.locks:
            return
        if self.live_txn_floor() is not None \
                or self.pinned_read_floor() is not None:
            # compaction advances base_ts and folds the delta: an open
            # snapshot reader (live txn OR pinned tidb_snapshot) would see
            # an empty table mid-read.  Defer until no snapshot is pinned
            # (same rule as GC).
            return
        if len(t.delta) > max(threshold, t.base_rows // 10):
            try:
                t.compact(self.current_ts())
            except KVError:
                pass  # raced with a new lock; next DML retriggers


class CoprClient(StoreClient):
    """The pushdown boundary implementation: fan a CopRequest out per region
    and run the DAG on the chosen engine.

    Reference: store/tikv/coprocessor.go CopClient.Send (:57) +
    buildCopTasks (:220) + the worker loop (:391-560).  The retry-on-
    region-error loop lives here (region_request.go:74-161 analog).
    """

    def __init__(self, storage: BlockStorage):
        self.storage = storage

    def send(self, req: CopRequest):
        # late imports: copr depends on chunk/expr only
        from ..copr.engine import run_dag_on_region

        tasks = []  # (region, clipped ranges)
        for kr in req.ranges:
            for region, clipped in self.storage.regions.locate(kr):
                tasks.append((region, clipped))
        # order by handle range start for keep_order
        tasks.sort(key=lambda t: (t[1].table_id, t[1].start))
        for region, clipped in tasks:
            attempts = 0
            while True:
                attempts += 1
                try:
                    FAILPOINTS.hit(
                        "copr/region_error",
                        region_id=region.region_id,
                        attempt=attempts,
                    )
                    self.storage.regions.check_epoch(
                        region.region_id, region.epoch, clipped.table_id
                    )
                    resp = run_dag_on_region(
                        self.storage, req, region, clipped
                    )
                    yield resp
                    break
                except RegionError:
                    if attempts > 10:
                        raise
                    # refresh routing: re-locate the clipped range
                    sub = self.storage.regions.locate(clipped)
                    if len(sub) == 1:
                        region, clipped = sub[0]
                        continue
                    # range now spans several regions: recurse via fresh send
                    subreq = CopRequest(
                        dag=req.dag,
                        ranges=[c for _, c in sub],
                        ts=req.ts,
                        concurrency=req.concurrency,
                        keep_order=req.keep_order,
                        streaming=req.streaming,
                        engine=req.engine,
                        aux=req.aux,
                    )
                    yield from self.send(subreq)
                    break
