"""Transactions: optimistic 2PC over the block stores.

Reference: store/tikv/2pc.go — Percolator prewrite/commit with keys grouped
per region (appendBatchBySize :1226), primary-first commit (:999,:866),
TTL'd locks; optimistic conflict surfaces as retryable error
(session retry loop lives in the session layer, session.go:635).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from ..errors import (
    DeadlockError,
    KVError,
    LockedError,
    LockWaitTimeoutError,
    TxnConflictError,
)
from .fault import FAILPOINTS

RowKey = Tuple[int, int]  # (table_id, handle)


@dataclass
class Mutation:
    op: str  # 'put' | 'del' | 'lock'
    values: Optional[tuple]


class Transaction:
    def __init__(self, storage, start_ts: int, pessimistic: bool = False):
        self.storage = storage
        self.start_ts = start_ts
        self.pessimistic = pessimistic
        self.buffer: Dict[RowKey, Mutation] = {}
        self._locked: set = set()
        self.committed = False
        self.rolled_back = False
        # pessimistic conflict horizon: advanced past newer commits when a
        # FOR UPDATE lock is taken (2pc.go for_update_ts); locked keys
        # prewrite against this at commit instead of start_ts
        self.for_update_ts = start_ts
        # optional hook run AFTER prewrite, before the decision point: the
        # session wires the commit-time schema check here (SchemaChecker,
        # session.go checkSchemaValidity).  Running it with prewrite locks
        # held closes the check-then-act race against an online DDL: the
        # DDL's unique recheck either blocks on our locks (and then sees
        # our committed rows) or bumped the version first (and we abort).
        self.schema_check = None

    # ---- buffered writes (membuffer analog, kv/memdb) ------------------
    def put(self, table_id: int, handle: int, values: tuple):
        self.buffer[(table_id, handle)] = Mutation("put", values)

    def delete(self, table_id: int, handle: int):
        self.buffer[(table_id, handle)] = Mutation("del", None)

    def get(self, table_id: int, handle: int) -> Optional[tuple]:
        m = self.buffer.get((table_id, handle))
        if m is not None:
            return m.values if m.op == "put" else None
        return self.storage.table(table_id).read_row(handle, self.start_ts)

    # pessimistic lock-wait knobs; the per-session innodb_lock_wait_timeout
    # overrides the default via `lock_wait_timeout_s` (session._begin_txn)
    LOCK_WAIT_TIMEOUT_S = 5.0
    LOCK_WAIT_POLL_S = 0.005
    lock_wait_timeout_s: float = LOCK_WAIT_TIMEOUT_S

    def lock_keys(self, *keys: RowKey, ttl_ms: int = 3000):
        """Pessimistic locks taken during execution (2pc.go:668).

        A held lock blocks (MySQL row-lock wait) instead of erroring:
        the wait registers an edge in the storage-wide wait-for graph and
        the REQUESTER aborts as victim if the edge closes a cycle
        (util/deadlock/deadlock.go Detect)."""
        if not keys:
            return
        primary = keys[0]
        for tid, h in keys:
            if (tid, h) in self._locked:
                continue
            # rows already in our write buffer still need the KV lock:
            # without it a second session's FOR UPDATE would succeed
            # concurrently and both would "hold" the row
            self._prewrite_waiting(tid, h, "lock", None, primary, ttl_ms,
                                   pessimistic=True)
            self._locked.add((tid, h))

    def _prewrite_waiting(self, tid: int, h: int, op: str, values,
                          primary: RowKey, ttl_ms: int = 3000,
                          pessimistic: bool = False, check_ts=None):
        """Prewrite that WAITS on a foreign lock (MySQL row-lock wait)
        with deadlock detection, instead of failing fast.

        pessimistic=True additionally refreshes for_update_ts past a newer
        committed version instead of failing: a FOR UPDATE lock targets the
        CURRENT row, not the txn snapshot (pessimistic for_update_ts)."""
        import time as _time

        from ..lifecycle import current_scope

        scope = current_scope()
        detector = self.storage.deadlock
        deadline = _time.monotonic() + self.lock_wait_timeout_s
        waiting_on = None
        try:
            while True:
                try:
                    self.storage.table(tid).prewrite(
                        h, op, values, primary, self.start_ts, ttl_ms,
                        check_ts=(self.for_update_ts if pessimistic
                                  else check_ts),
                    )
                    return
                except TxnConflictError:
                    if not pessimistic:
                        raise
                    # a commit landed after for_update_ts: lock the newer
                    # version (advance the horizon) and retry
                    self.for_update_ts = self.storage.oracle.get_timestamp()
                except LockedError as e:
                    holder = e.owner_ts
                    if waiting_on != holder:
                        if waiting_on is not None:
                            detector.clean_up_wait_for(
                                self.start_ts, waiting_on)
                        if detector.detect(self.start_ts, holder):
                            raise DeadlockError()
                        waiting_on = holder
                    # resolvable only when the holder is BOTH untracked by
                    # this process (crashed/foreign) and TTL-expired: a
                    # live txn never loses its locks to a waiter
                    if not self.storage.txn_alive(holder) and                             self.storage.oracle.is_expired(holder, ttl_ms):
                        try:
                            resolve_lock(self.storage, tid, h)
                            continue
                        except LockedError:
                            pass
                    if _time.monotonic() >= deadline:
                        raise LockWaitTimeoutError()
                    # interruptible row-lock wait: KILL/deadline/drain
                    # wakes the waiter instead of letting it poll out
                    # the full innodb_lock_wait_timeout
                    if scope.wait(self.LOCK_WAIT_POLL_S):
                        scope.check()
        finally:
            if waiting_on is not None:
                detector.clean_up_wait_for(self.start_ts, waiting_on)

    # ---- 2PC -----------------------------------------------------------
    def commit(self) -> int:
        if self.committed or self.rolled_back:
            raise KVError("txn already finished")
        if not self.buffer and not self._locked:
            self.committed = True
            self.storage.txn_finished(self.start_ts)
            return self.start_ts
        keys = sorted(self.buffer.keys())
        if not keys:  # lock-only txn
            for tid, h in self._locked:
                self.storage.table(tid).rollback(h, self.start_ts)
            self.committed = True
            self.storage.txn_finished(self.start_ts)
            return self.start_ts
        primary = keys[0]
        # release pessimistic-only locks that have no mutation (they are
        # upgraded in place when a mutation exists)
        for tid, h in self._locked - set(keys):
            self.storage.table(tid).rollback(h, self.start_ts)
        from ..trace import span

        from ..lifecycle import current_scope

        scope = current_scope()
        # phase 1: prewrite all keys (primary first), grouped per region
        prewritten = []
        try:
            with span("txn.prewrite", keys=len(keys)):
                for tid, h in keys:
                    # cancellation seam per prewrite batch unit: before
                    # the decision point a kill aborts cleanly (all
                    # prewritten locks roll back below).  Phase 2 never
                    # checks — once the primary commits, the txn is
                    # decided and must run to completion.
                    scope.check()
                    FAILPOINTS.hit("2pc/prewrite", table_id=tid, handle=h)
                    m = self.buffer[(tid, h)]
                    store = self.storage.table(tid)
                    pess = (tid, h) in self._locked
                    # upgrade IN PLACE: prewrite overwrites our own lock
                    # atomically (blockstore allows same-start_ts
                    # rewrite), so no waiter can steal the row between
                    # release and rewrite.  Keys we hold pessimistic
                    # locks on conflict-check at for_update_ts (the lock
                    # horizon), not start_ts.
                    self._prewrite_waiting(
                        tid, h, m.op, m.values, primary,
                        check_ts=(self.for_update_ts if pess else None))
                    prewritten.append((tid, h))
        except Exception:
            # conflicts/deadlocks/lock-timeouts AND lifecycle
            # cancellations (kill/timeout/drain) all abort the same way:
            # every prewritten lock rolls back so no orphan locks leak
            for tid, h in prewritten:
                self.storage.table(tid).rollback(h, self.start_ts)
            self.rolled_back = True
            self.storage.deadlock.clean_up(self.start_ts)
            self.storage.txn_finished(self.start_ts)
            raise
        if self.schema_check is not None:
            try:
                self.schema_check()
            except Exception:
                for tid, h in prewritten:
                    self.storage.table(tid).rollback(h, self.start_ts)
                self.rolled_back = True
                self.storage.deadlock.clean_up(self.start_ts)
                self.storage.txn_finished(self.start_ts)
                raise
        commit_ts = self.storage.oracle.get_timestamp()
        FAILPOINTS.hit("2pc/before_commit_primary", start_ts=self.start_ts)
        # phase 2: commit primary; after that the txn is decided
        with span("txn.commit", keys=len(keys)):
            self.storage.table(primary[0]).commit(
                primary[1], self.start_ts, commit_ts)
            for tid, h in keys:
                if (tid, h) == primary:
                    continue
                FAILPOINTS.hit("2pc/commit_secondary", table_id=tid,
                               handle=h)
                self.storage.table(tid).commit(h, self.start_ts, commit_ts)
        self.committed = True
        self.storage.deadlock.clean_up(self.start_ts)
        self.storage.txn_finished(self.start_ts)
        return commit_ts

    def rollback(self):
        if self.committed:
            raise KVError("txn already committed")
        for tid, h in set(self.buffer.keys()) | self._locked:
            self.storage.table(tid).rollback(h, self.start_ts)
        self.buffer.clear()
        self.rolled_back = True
        self.storage.deadlock.clean_up(self.start_ts)
        self.storage.txn_finished(self.start_ts)


def resolve_lock(storage, table_id: int, handle: int, ttl_expired_only: bool = True):
    """Resolve an orphan lock by consulting its primary (lock_resolver.go).

    If the primary committed, roll the secondary forward; if the primary
    lock is gone (rolled back), roll the secondary back."""
    store = storage.table(table_id)
    lk = store.locks.get(handle)
    if lk is None:
        return
    if storage.txn_alive(lk.start_ts):
        # live owner: not an orphan, never resolvable
        raise LockedError((table_id, handle), lk.start_ts)
    if ttl_expired_only and not storage.oracle.is_expired(lk.start_ts, lk.ttl_ms):
        raise LockedError((table_id, handle), lk.start_ts)
    ptid, ph = lk.primary
    pstore = storage.table(ptid)
    plk = pstore.locks.get(ph)
    if plk is not None and plk.start_ts == lk.start_ts:
        # primary still locked and expired -> roll back the whole txn
        pstore.rollback(ph, lk.start_ts)
        store.rollback(handle, lk.start_ts)
        return
    # primary decided: find its commit_ts
    for v in reversed(pstore.delta.get(ph, [])):
        if v.start_ts == lk.start_ts:
            store.commit(handle, lk.start_ts, v.commit_ts)
            return
    store.rollback(handle, lk.start_ts)
