"""Transactions: optimistic 2PC over the block stores.

Reference: store/tikv/2pc.go — Percolator prewrite/commit with keys grouped
per region (appendBatchBySize :1226), primary-first commit (:999,:866),
TTL'd locks; optimistic conflict surfaces as retryable error
(session retry loop lives in the session layer, session.go:635).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from ..errors import KVError, LockedError, TxnConflictError
from .fault import FAILPOINTS

RowKey = Tuple[int, int]  # (table_id, handle)


@dataclass
class Mutation:
    op: str  # 'put' | 'del' | 'lock'
    values: Optional[tuple]


class Transaction:
    def __init__(self, storage, start_ts: int, pessimistic: bool = False):
        self.storage = storage
        self.start_ts = start_ts
        self.pessimistic = pessimistic
        self.buffer: Dict[RowKey, Mutation] = {}
        self._locked: set = set()
        self.committed = False
        self.rolled_back = False
        # optional hook run AFTER prewrite, before the decision point: the
        # session wires the commit-time schema check here (SchemaChecker,
        # session.go checkSchemaValidity).  Running it with prewrite locks
        # held closes the check-then-act race against an online DDL: the
        # DDL's unique recheck either blocks on our locks (and then sees
        # our committed rows) or bumped the version first (and we abort).
        self.schema_check = None

    # ---- buffered writes (membuffer analog, kv/memdb) ------------------
    def put(self, table_id: int, handle: int, values: tuple):
        self.buffer[(table_id, handle)] = Mutation("put", values)

    def delete(self, table_id: int, handle: int):
        self.buffer[(table_id, handle)] = Mutation("del", None)

    def get(self, table_id: int, handle: int) -> Optional[tuple]:
        m = self.buffer.get((table_id, handle))
        if m is not None:
            return m.values if m.op == "put" else None
        return self.storage.table(table_id).read_row(handle, self.start_ts)

    def lock_keys(self, *keys: RowKey, ttl_ms: int = 3000):
        """Pessimistic locks taken during execution (2pc.go:668)."""
        if not keys:
            return
        primary = keys[0]
        for tid, h in keys:
            self.storage.table(tid).prewrite(
                h, "lock", None, primary, self.start_ts, ttl_ms
            )
            self._locked.add((tid, h))

    # ---- 2PC -----------------------------------------------------------
    def commit(self) -> int:
        if self.committed or self.rolled_back:
            raise KVError("txn already finished")
        if not self.buffer and not self._locked:
            self.committed = True
            return self.start_ts
        keys = sorted(self.buffer.keys())
        if not keys:  # lock-only txn
            for tid, h in self._locked:
                self.storage.table(tid).rollback(h, self.start_ts)
            self.committed = True
            return self.start_ts
        primary = keys[0]
        # release pessimistic-only locks that have no mutation (they are
        # upgraded in place when a mutation exists)
        for tid, h in self._locked - set(keys):
            self.storage.table(tid).rollback(h, self.start_ts)
        # phase 1: prewrite all keys (primary first), grouped per region
        prewritten = []
        try:
            for tid, h in keys:
                FAILPOINTS.hit("2pc/prewrite", table_id=tid, handle=h)
                m = self.buffer[(tid, h)]
                store = self.storage.table(tid)
                if (tid, h) in self._locked:
                    store.rollback(h, self.start_ts)  # upgrade pessimistic lock
                store.prewrite(h, m.op, m.values, primary, self.start_ts)
                prewritten.append((tid, h))
        except (LockedError, TxnConflictError):
            for tid, h in prewritten:
                self.storage.table(tid).rollback(h, self.start_ts)
            self.rolled_back = True
            raise
        if self.schema_check is not None:
            try:
                self.schema_check()
            except Exception:
                for tid, h in prewritten:
                    self.storage.table(tid).rollback(h, self.start_ts)
                self.rolled_back = True
                raise
        commit_ts = self.storage.oracle.get_timestamp()
        FAILPOINTS.hit("2pc/before_commit_primary", start_ts=self.start_ts)
        # phase 2: commit primary; after that the txn is decided
        self.storage.table(primary[0]).commit(primary[1], self.start_ts, commit_ts)
        for tid, h in keys:
            if (tid, h) == primary:
                continue
            FAILPOINTS.hit("2pc/commit_secondary", table_id=tid, handle=h)
            self.storage.table(tid).commit(h, self.start_ts, commit_ts)
        self.committed = True
        return commit_ts

    def rollback(self):
        if self.committed:
            raise KVError("txn already committed")
        for tid, h in set(self.buffer.keys()) | self._locked:
            self.storage.table(tid).rollback(h, self.start_ts)
        self.buffer.clear()
        self.rolled_back = True


def resolve_lock(storage, table_id: int, handle: int, ttl_expired_only: bool = True):
    """Resolve an orphan lock by consulting its primary (lock_resolver.go).

    If the primary committed, roll the secondary forward; if the primary
    lock is gone (rolled back), roll the secondary back."""
    store = storage.table(table_id)
    lk = store.locks.get(handle)
    if lk is None:
        return
    if ttl_expired_only and not storage.oracle.is_expired(lk.start_ts, lk.ttl_ms):
        raise LockedError((table_id, handle), lk.start_ts)
    ptid, ph = lk.primary
    pstore = storage.table(ptid)
    plk = pstore.locks.get(ph)
    if plk is not None and plk.start_ts == lk.start_ts:
        # primary still locked and expired -> roll back the whole txn
        pstore.rollback(ph, lk.start_ts)
        store.rollback(handle, lk.start_ts)
        return
    # primary decided: find its commit_ts
    for v in reversed(pstore.delta.get(ph, [])):
        if v.start_ts == lk.start_ts:
            store.commit(handle, lk.start_ts, v.commit_ts)
            return
    store.rollback(handle, lk.start_ts)
