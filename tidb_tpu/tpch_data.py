"""Shared TPC-H-shaped data generation (bench.py + driver dryrun).

One lineitem recipe so the benchmark and the multichip dryrun can never
drift apart on schema or data distribution.
"""

from __future__ import annotations

import numpy as np

# authentic TPC-H column types (the reference's lineitem DDL uses
# DECIMAL(15,2) for quantity/extendedprice/discount/tax): decimals store
# as scaled int64, so every money column narrows on the wire
# (store/blockstore.py scaled-int decimal + parallel._wire_dtype)
LINEITEM_DDL = (
    "create table lineitem ("
    " l_orderkey bigint, l_quantity decimal(15,2),"
    " l_extendedprice decimal(15,2), l_discount decimal(15,2),"
    " l_tax decimal(15,2),"
    " l_returnflag varchar(1), l_linestatus varchar(1),"
    " l_shipdate date)"
)


def build_lineitem(n: int, regions: int = 8, seed: int = 7):
    """Fresh Domain with `n` synthetic lineitem rows split over `regions`
    regions; returns the session."""
    from .session import Domain
    from .types.values import parse_date

    domain = Domain()
    s = domain.new_session()
    s.execute(LINEITEM_DDL)
    t = domain.catalog.info_schema().table("test", "lineitem")
    store = domain.storage.table(t.id)
    rng = np.random.default_rng(seed)
    base = parse_date("1992-01-01")
    span = parse_date("1998-12-01") - base
    # string columns ship as Arrow-style dictionary codes: the generator
    # KNOWS its categories, so no per-row encode on the load path
    dicts = {5: ["A", "N", "R"], 6: ["F", "O"]}
    CHUNK = 1 << 21
    for s0 in range(0, n, CHUNK):
        m = min(CHUNK, n - s0)
        arrays = [
            rng.integers(1, n // 4 + 2, m, dtype=np.int64),     # orderkey
            rng.integers(100, 5100, m, dtype=np.int64),          # qty (scaled .2)
            rng.integers(90_000, 10_500_001, m, dtype=np.int64),  # price (.2)
            rng.integers(0, 11, m, dtype=np.int64),              # discount (.2)
            rng.integers(0, 9, m, dtype=np.int64),               # tax (.2)
            rng.integers(0, 3, m, dtype=np.int32),               # returnflag
            rng.integers(0, 2, m, dtype=np.int32),               # linestatus
            (base + rng.integers(0, span, m)).astype(np.int32),  # shipdate
        ]
        store.bulk_load_arrays(arrays, ts=domain.storage.current_ts(),
                               dictionaries=dicts)
    domain.storage.regions.split_even(t.id, regions, store.base_rows)
    from .copr.parallel import prefetch_table

    prefetch_table(domain.storage, t.id)
    return s


def build_tpch_domain(scale: float = 1.0, seed: int = 1234,
                      regions: int = 4):
    """Full 8-table synthetic TPC-H-shaped domain (the golden-suite
    recipe, shared by tests/test_tpch.py and bench.py's `tpch_matrix`
    receipt so the parity suite and the fused-fraction receipt can never
    drift apart on schema or distribution).  Returns the session."""
    from .session import Domain
    from .types.values import parse_date

    n_line = int(8000 * scale)
    n_orders = int(2000 * scale)
    n_cust = int(300 * scale)
    n_part = int(200 * scale)
    n_supp = max(int(40 * scale), 10)
    n_nation = 25

    d = Domain()
    s = d.new_session()
    rng = np.random.default_rng(seed)
    base = parse_date("1992-01-01")
    span = parse_date("1998-12-01") - base

    def load(name, ddl, arrays):
        s.execute(ddl)
        t = d.catalog.info_schema().table("test", name)
        store = d.storage.table(t.id)
        store.bulk_load_arrays(arrays, ts=d.storage.current_ts())
        d.storage.regions.split_even(t.id, regions, store.base_rows)
        return t

    load("nation", "create table nation (n_nationkey bigint, n_name "
         "varchar(25), n_regionkey bigint)", [
        np.arange(n_nation, dtype=np.int64),
        np.array([f"NATION{i:02d}" for i in range(n_nation)],
                 dtype=object),
        rng.integers(0, 5, n_nation, dtype=np.int64),
    ])
    load("region", "create table region (r_regionkey bigint, r_name "
         "varchar(25))", [
        np.arange(5, dtype=np.int64),
        np.array(["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"],
                 dtype=object),
    ])
    scomments = np.array(["quick brown fox", "Customer stuff Complaints",
                          "regular deposits", "silent Customer noise"],
                         dtype=object)
    load("supplier", "create table supplier (s_suppkey bigint, s_name "
         "varchar(25), s_nationkey bigint, s_acctbal decimal(12,2), "
         "s_comment varchar(40))", [
        np.arange(n_supp, dtype=np.int64),
        np.array([f"SUPP{i:04d}" for i in range(n_supp)], dtype=object),
        rng.integers(0, n_nation, n_supp, dtype=np.int64),
        np.round(rng.uniform(-999, 9999, n_supp) * 100).astype(np.int64),
        scomments[rng.integers(0, 4, n_supp)],
    ])
    load("partsupp", "create table partsupp (ps_partkey bigint, "
         "ps_suppkey bigint, ps_availqty bigint, "
         "ps_supplycost decimal(12,2))", [
        np.repeat(np.arange(n_part, dtype=np.int64), 4),
        rng.integers(0, n_supp, n_part * 4, dtype=np.int64),
        rng.integers(1, 10000, n_part * 4, dtype=np.int64),
        np.round(rng.uniform(1, 1000, n_part * 4) * 100).astype(np.int64),
    ])
    phones = np.array([f"{cc}-555-{i:04d}" for i, cc in zip(
        range(n_cust),
        np.array(["13", "31", "23", "29", "30", "18", "17", "44", "99"])[
            rng.integers(0, 9, n_cust)])], dtype=object)
    load("customer", "create table customer (c_custkey bigint, c_name "
         "varchar(25), c_nationkey bigint, c_mktsegment varchar(10), "
         "c_acctbal decimal(12,2), c_phone varchar(15))", [
        np.arange(n_cust, dtype=np.int64),
        np.array([f"CUST{i:05d}" for i in range(n_cust)], dtype=object),
        rng.integers(0, n_nation, n_cust, dtype=np.int64),
        np.array(["BUILDING", "MACHINERY", "AUTOMOBILE", "HOUSEHOLD",
                  "FURNITURE"], dtype=object)[rng.integers(0, 5, n_cust)],
        np.round(rng.uniform(-999, 9999, n_cust) * 100).astype(np.int64),
        phones,
    ])
    load("part", "create table part (p_partkey bigint, p_name "
         "varchar(30), p_type varchar(25), p_size bigint, "
         "p_brand varchar(10))", [
        np.arange(n_part, dtype=np.int64),
        np.array([f"PART{i:05d}" for i in range(n_part)], dtype=object),
        np.array(["PROMO BRUSHED", "STANDARD POLISHED", "SMALL PLATED",
                  "MEDIUM BURNISHED"], dtype=object)[
            rng.integers(0, 4, n_part)],
        rng.integers(1, 50, n_part, dtype=np.int64),
        np.array([f"Brand#{i}" for i in range(1, 6)], dtype=object)[
            rng.integers(0, 5, n_part)],
    ])
    odate = (base + rng.integers(0, span, n_orders)).astype(np.int32)
    ocomments = np.array(["ordinary request", "special packed requests",
                          "pending special asks",
                          "normal special requests",
                          "quiet commentary"], dtype=object)
    load("orders", "create table orders (o_orderkey bigint, o_custkey "
         "bigint, o_orderstatus varchar(1), o_totalprice decimal(15,2), "
         "o_orderdate date, o_orderpriority varchar(15), "
         "o_comment varchar(40))", [
        np.arange(n_orders, dtype=np.int64),
        # leave the top 60 custkeys order-less so NOT IN subqueries hit
        rng.integers(0, max(n_cust - 60, 1), n_orders, dtype=np.int64),
        np.array(["O", "F", "P"], dtype=object)[
            rng.integers(0, 3, n_orders)],
        np.round(rng.uniform(1000, 400000, n_orders) * 100).astype(
            np.int64),
        odate,
        np.array(["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED",
                  "5-LOW"], dtype=object)[rng.integers(0, 5, n_orders)],
        ocomments[rng.integers(0, 5, n_orders)],
    ])
    okeys = rng.integers(0, n_orders, n_line, dtype=np.int64)
    sdate = odate[okeys] + rng.integers(1, 120, n_line).astype(np.int32)
    cdate = sdate + rng.integers(-30, 30, n_line).astype(np.int32)
    rdate = sdate + rng.integers(1, 30, n_line).astype(np.int32)
    load("lineitem", "create table lineitem (l_orderkey bigint, "
         "l_partkey bigint, l_suppkey bigint, l_quantity decimal(15,2), "
         "l_extendedprice decimal(15,2), l_discount decimal(15,2), "
         "l_tax decimal(15,2), "
         "l_returnflag varchar(1), l_linestatus varchar(1), "
         "l_shipdate date, l_commitdate date, l_receiptdate date, "
         "l_shipmode varchar(10))", [
        okeys,
        rng.integers(0, n_part, n_line, dtype=np.int64),
        rng.integers(0, n_supp, n_line, dtype=np.int64),
        rng.integers(100, 5100, n_line, dtype=np.int64),  # scaled .2
        np.round(rng.uniform(900, 105000, n_line) * 100).astype(np.int64),
        np.round(rng.uniform(0.0, 0.1, n_line) * 100).astype(np.int64),
        np.round(rng.uniform(0.0, 0.08, n_line) * 100).astype(np.int64),
        np.array(["A", "N", "R"], dtype=object)[
            rng.integers(0, 3, n_line)],
        np.array(["O", "F"], dtype=object)[rng.integers(0, 2, n_line)],
        sdate,
        cdate,
        rdate,
        np.array(["AIR", "MAIL", "SHIP", "TRUCK", "RAIL", "REG AIR",
                  "FOB"], dtype=object)[rng.integers(0, 7, n_line)],
    ])
    for t in ("lineitem", "orders", "customer"):
        s.execute(f"analyze table {t}")
    return s


#: how many base relations each query's FROM joins (join-tree depth:
#: the `tpch_matrix` receipt reports the fused fraction per depth)
TPCH_N_TABLES = {
    "q1": 1, "q2": 5, "q3": 3, "q4": 2, "q5": 5, "q6": 1, "q7": 6,
    "q8": 8, "q9": 6, "q10": 3, "q11": 3, "q12": 2, "q13": 2, "q14": 2,
    "q15": 2, "q16": 3, "q17": 2, "q18": 4, "q19": 2, "q20": 4,
    "q21": 6, "q22": 2,
}


# the canonical Q3-shaped query over build_q3_tables' pair (shared by the
# bench, the driver dryruns, and the multihost worker so they always
# exercise the same plan shape)
Q3_SQL = (
    "select l_orderkey, o_orderdate, o_shippriority,"
    " sum(l_extendedprice * (1 - l_discount)) as rev"
    " from lineitem, orders where l_orderkey = o_orderkey"
    " and o_orderdate < '1995-03-15' and l_shipdate > '1995-03-15'"
    " group by l_orderkey, o_orderdate, o_shippriority"
    " order by rev desc, l_orderkey limit 10"
)


def build_q3_tables(n_li: int, n_orders: int, regions: int = 8,
                    seed: int = 11):
    """Q3-shaped pair: orders (PK o_orderkey, the broadcast build side)
    joined by a lineitem fact table — the device lookup-join benchmark
    shape (reference executor/join.go role under TPC-H Q3)."""
    from .session import Domain
    from .types.values import parse_date

    domain = Domain()
    s = domain.new_session()
    s.execute("create table orders (o_orderkey bigint primary key,"
              " o_orderdate date, o_shippriority bigint)")
    s.execute("create table lineitem (l_orderkey bigint,"
              " l_extendedprice decimal(15,2), l_discount decimal(15,2),"
              " l_shipdate date)")
    rng = np.random.default_rng(seed)
    base = parse_date("1995-01-01")
    t_o = domain.catalog.info_schema().table("test", "orders")
    t_l = domain.catalog.info_schema().table("test", "lineitem")
    domain.storage.table(t_o.id).bulk_load_arrays([
        np.arange(n_orders, dtype=np.int64),
        (base + rng.integers(-400, 400, n_orders)).astype(np.int64),
        rng.integers(0, 5, n_orders),
    ], ts=domain.storage.current_ts())
    CHUNK = 1 << 21
    store = domain.storage.table(t_l.id)
    for s0 in range(0, n_li, CHUNK):
        m = min(CHUNK, n_li - s0)
        store.bulk_load_arrays([
            rng.integers(0, n_orders, m),
            rng.integers(90_000, 10_500_001, m),
            rng.integers(0, 11, m),
            (base + rng.integers(-300, 300, m)).astype(np.int64),
        ], ts=domain.storage.current_ts())
    domain.storage.regions.split_even(t_l.id, regions, store.base_rows)
    s.execute("analyze table orders")
    s.execute("analyze table lineitem")
    return s


#: the 22-query TPC-H golden corpus over build_tpch_domain's
#: schema (tests assert engine parity; the bench receipt classifies
#: each query's residency)
TPCH_QUERIES = {
    "q1": """
select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty,
       sum(l_extendedprice) as sum_base_price,
       sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
       sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
       avg(l_quantity) as avg_qty, avg(l_extendedprice) as avg_price,
       avg(l_discount) as avg_disc, count(*) as count_order
from lineitem
where l_shipdate <= date '1998-09-02'
group by l_returnflag, l_linestatus
order by l_returnflag, l_linestatus""",
    "q3": """
select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
       o_orderdate
from customer, orders, lineitem
where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
  and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
  and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate
order by revenue desc, o_orderkey
limit 10""",
    "q5": """
select n_name, sum(l_extendedprice * (1 - l_discount)) as revenue
from customer, orders, lineitem, supplier, nation
where c_custkey = o_custkey and l_orderkey = o_orderkey
  and l_suppkey = s_suppkey and c_nationkey = s_nationkey
  and s_nationkey = n_nationkey
  and o_orderdate >= date '1994-01-01' and o_orderdate < date '1995-01-01'
group by n_name order by revenue desc""",
    "q6": """
select sum(l_extendedprice * l_discount) as revenue
from lineitem
where l_shipdate >= date '1994-01-01' and l_shipdate < date '1995-01-01'
  and l_discount between 0.05 and 0.07 and l_quantity < 24""",
    "q10": """
select c_custkey, c_name, sum(l_extendedprice * (1 - l_discount)) as revenue
from customer, orders, lineitem
where c_custkey = o_custkey and l_orderkey = o_orderkey
  and o_orderdate >= date '1993-10-01' and o_orderdate < date '1994-01-01'
  and l_returnflag = 'R'
group by c_custkey, c_name
order by revenue desc, c_custkey limit 20""",
    "q12": """
select l_shipmode,
       sum(case when o_orderpriority = '1-URGENT'
                  or o_orderpriority = '2-HIGH' then 1 else 0 end)
         as high_line_count,
       sum(case when o_orderpriority <> '1-URGENT'
                 and o_orderpriority <> '2-HIGH' then 1 else 0 end)
         as low_line_count
from orders join lineitem on o_orderkey = l_orderkey
where l_shipmode in ('MAIL', 'SHIP')
  and l_commitdate < l_receiptdate and l_shipdate < l_commitdate
  and l_receiptdate >= date '1994-01-01'
  and l_receiptdate < date '1995-01-01'
group by l_shipmode order by l_shipmode""",
    "q13": """
select c_count, count(*) as custdist from (
  select c_custkey, count(o_orderkey) as c_count
  from customer left join orders on c_custkey = o_custkey
      and o_comment not like '%special%requests%'
  group by c_custkey
) c_orders
group by c_count
order by custdist desc, c_count desc limit 10""",
    "q14": """
select 100.00 * sum(case when p_type like 'PROMO%%'
                         then l_extendedprice * (1 - l_discount)
                         else 0 end) / sum(l_extendedprice * (1 - l_discount))
       as promo_revenue
from lineitem, part
where l_partkey = p_partkey
  and l_shipdate >= date '1995-09-01' and l_shipdate < date '1995-10-01'""",
    "q18": """
select c_custkey, o_orderkey, o_totalprice, sum(l_quantity)
from customer, orders, lineitem
where o_orderkey in (
    select l_orderkey from lineitem group by l_orderkey
    having sum(l_quantity) > 100
  )
  and c_custkey = o_custkey and o_orderkey = l_orderkey
group by c_custkey, o_orderkey, o_totalprice
order by o_totalprice desc, o_orderkey limit 10""",
    "q19": """
select sum(l_extendedprice * (1 - l_discount)) as revenue
from lineitem, part
where p_partkey = l_partkey
  and ((p_size >= 1 and p_size <= 15 and l_quantity >= 1)
       or (p_size >= 16 and l_quantity >= 10))
  and l_shipdate >= date '1994-01-01'""",
    "q4": """
select o_orderpriority, count(*) as order_count
from orders
where o_orderdate >= date '1993-07-01' and o_orderdate < date '1993-10-01'
  and exists (select 1 from lineitem
              where l_orderkey = o_orderkey and l_shipdate > o_orderdate)
group by o_orderpriority order by o_orderpriority""",
    "q17": """
select sum(l_extendedprice) / 7.0 as avg_yearly
from lineitem, part
where p_partkey = l_partkey and p_type = 'PROMO BRUSHED'
  and l_quantity < (select 0.2 * avg(l_quantity) from lineitem
                    where l_partkey = p_partkey)""",
    "q2": """
select s_acctbal, s_name, n_name, p_partkey, p_name
from part, supplier, partsupp, nation, region
where p_partkey = ps_partkey and s_suppkey = ps_suppkey
  and p_size < 25 and p_type like '%%POLISHED%%'
  and s_nationkey = n_nationkey and n_regionkey = r_regionkey
  and r_name = 'EUROPE'
  and ps_supplycost = (
    select min(ps_supplycost)
    from partsupp, supplier, nation, region
    where p_partkey = ps_partkey and s_suppkey = ps_suppkey
      and s_nationkey = n_nationkey and n_regionkey = r_regionkey
      and r_name = 'EUROPE')
order by s_acctbal desc, n_name, s_name, p_partkey limit 100""",
    "q7": """
select supp_nation, cust_nation, l_year, sum(volume) as revenue
from (
  select n1.n_name as supp_nation, n2.n_name as cust_nation,
         year(l_shipdate) as l_year,
         l_extendedprice * (1 - l_discount) as volume
  from supplier, lineitem, orders, customer, nation n1, nation n2
  where s_suppkey = l_suppkey and o_orderkey = l_orderkey
    and c_custkey = o_custkey and s_nationkey = n1.n_nationkey
    and c_nationkey = n2.n_nationkey
    and ((n1.n_name = 'NATION01' and n2.n_name = 'NATION02')
         or (n1.n_name = 'NATION02' and n2.n_name = 'NATION01'))
    and l_shipdate between date '1995-01-01' and date '1996-12-31'
) shipping
group by supp_nation, cust_nation, l_year
order by supp_nation, cust_nation, l_year""",
    "q8": """
select o_year,
       sum(case when nation = 'NATION02' then volume else 0 end)
         / sum(volume) as mkt_share
from (
  select year(o_orderdate) as o_year,
         l_extendedprice * (1 - l_discount) as volume,
         n2.n_name as nation
  from part, supplier, lineitem, orders, customer, nation n1, nation n2,
       region
  where p_partkey = l_partkey and s_suppkey = l_suppkey
    and l_orderkey = o_orderkey and o_custkey = c_custkey
    and c_nationkey = n1.n_nationkey and n1.n_regionkey = r_regionkey
    and r_name = 'AMERICA' and s_nationkey = n2.n_nationkey
    and o_orderdate between date '1995-01-01' and date '1996-12-31'
    and p_type = 'STANDARD POLISHED'
) all_nations
group by o_year order by o_year""",
    "q9": """
select nation, o_year, sum(amount) as sum_profit
from (
  select n_name as nation, year(o_orderdate) as o_year,
         l_extendedprice * (1 - l_discount)
           - ps_supplycost * l_quantity as amount
  from part, supplier, lineitem, partsupp, orders, nation
  where s_suppkey = l_suppkey and ps_suppkey = l_suppkey
    and ps_partkey = l_partkey and p_partkey = l_partkey
    and o_orderkey = l_orderkey and s_nationkey = n_nationkey
    and p_name like '%%1%%'
) profit
group by nation, o_year
order by nation, o_year desc limit 30""",
    "q11": """
select ps_partkey, sum(ps_supplycost * ps_availqty) as value
from partsupp, supplier, nation
where ps_suppkey = s_suppkey and s_nationkey = n_nationkey
  and n_name = 'NATION16'
group by ps_partkey
having sum(ps_supplycost * ps_availqty) > (
  select sum(ps_supplycost * ps_availqty) * 0.02
  from partsupp, supplier, nation
  where ps_suppkey = s_suppkey and s_nationkey = n_nationkey
    and n_name = 'NATION16')
order by value desc""",
    "q15": """
select s_suppkey, s_name, total_revenue
from supplier, (
  select l_suppkey as supplier_no,
         sum(l_extendedprice * (1 - l_discount)) as total_revenue
  from lineitem
  where l_shipdate >= date '1996-01-01' and l_shipdate < date '1996-04-01'
  group by l_suppkey) revenue
where s_suppkey = supplier_no
  and total_revenue = (
    select max(total_revenue) from (
      select l_suppkey as supplier_no,
             sum(l_extendedprice * (1 - l_discount)) as total_revenue
      from lineitem
      where l_shipdate >= date '1996-01-01'
        and l_shipdate < date '1996-04-01'
      group by l_suppkey) r)
order by s_suppkey""",
    "q16": """
select p_brand, p_type, p_size, count(distinct ps_suppkey) as supplier_cnt
from partsupp, part
where p_partkey = ps_partkey and p_brand <> 'Brand#1'
  and p_type not like 'SMALL%%'
  and p_size in (1, 5, 10, 15, 20, 25, 30, 35)
  and ps_suppkey not in (
    select s_suppkey from supplier
    where s_comment like '%%Customer%%Complaints%%')
group by p_brand, p_type, p_size
order by supplier_cnt desc, p_brand, p_type, p_size limit 20""",
    "q20": """
select s_name, s_nationkey
from supplier, nation
where s_suppkey in (
    select ps_suppkey from partsupp
    where ps_partkey in (select p_partkey from part
                         where p_name like 'PART000%%')
      and ps_availqty > (
        select 0.5 * sum(l_quantity) from lineitem
        where l_partkey = ps_partkey and l_suppkey = ps_suppkey
          and l_shipdate >= date '1994-01-01'
          and l_shipdate < date '1995-01-01'))
  and s_nationkey = n_nationkey and n_name = 'NATION03'
order by s_name""",
    "q21": """
select s_name, count(*) as numwait
from supplier, lineitem l1, orders, nation
where s_suppkey = l1.l_suppkey and o_orderkey = l1.l_orderkey
  and o_orderstatus = 'F' and l1.l_receiptdate > l1.l_commitdate
  and exists (select 1 from lineitem l2
              where l2.l_orderkey = l1.l_orderkey
                and l2.l_suppkey <> l1.l_suppkey)
  and not exists (select 1 from lineitem l3
                  where l3.l_orderkey = l1.l_orderkey
                    and l3.l_suppkey <> l1.l_suppkey
                    and l3.l_receiptdate > l3.l_commitdate)
  and s_nationkey = n_nationkey and n_name = 'NATION05'
group by s_name
order by numwait desc, s_name limit 100""",
    "q22": """
select cntrycode, count(*) as numcust, sum(c_acctbal) as totacctbal
from (
  select substring(c_phone, 1, 2) as cntrycode, c_acctbal
  from customer
  where substring(c_phone, 1, 2) in ('13', '31', '23', '29', '30', '18',
                                     '17')
    and c_acctbal > (
      select avg(c_acctbal) from customer
      where c_acctbal > 0.00
        and substring(c_phone, 1, 2) in ('13', '31', '23', '29', '30',
                                         '18', '17'))
    and not exists (select 1 from orders where o_custkey = c_custkey)
) custsale
group by cntrycode order by cntrycode""",
}
