"""Shared TPC-H-shaped data generation (bench.py + driver dryrun).

One lineitem recipe so the benchmark and the multichip dryrun can never
drift apart on schema or data distribution.
"""

from __future__ import annotations

import numpy as np

# authentic TPC-H column types (the reference's lineitem DDL uses
# DECIMAL(15,2) for quantity/extendedprice/discount/tax): decimals store
# as scaled int64, so every money column narrows on the wire
# (store/blockstore.py scaled-int decimal + parallel._wire_dtype)
LINEITEM_DDL = (
    "create table lineitem ("
    " l_orderkey bigint, l_quantity decimal(15,2),"
    " l_extendedprice decimal(15,2), l_discount decimal(15,2),"
    " l_tax decimal(15,2),"
    " l_returnflag varchar(1), l_linestatus varchar(1),"
    " l_shipdate date)"
)


def build_lineitem(n: int, regions: int = 8, seed: int = 7):
    """Fresh Domain with `n` synthetic lineitem rows split over `regions`
    regions; returns the session."""
    from .session import Domain
    from .types.values import parse_date

    domain = Domain()
    s = domain.new_session()
    s.execute(LINEITEM_DDL)
    t = domain.catalog.info_schema().table("test", "lineitem")
    store = domain.storage.table(t.id)
    rng = np.random.default_rng(seed)
    base = parse_date("1992-01-01")
    span = parse_date("1998-12-01") - base
    # string columns ship as Arrow-style dictionary codes: the generator
    # KNOWS its categories, so no per-row encode on the load path
    dicts = {5: ["A", "N", "R"], 6: ["F", "O"]}
    CHUNK = 1 << 21
    for s0 in range(0, n, CHUNK):
        m = min(CHUNK, n - s0)
        arrays = [
            rng.integers(1, n // 4 + 2, m, dtype=np.int64),     # orderkey
            rng.integers(100, 5100, m, dtype=np.int64),          # qty (scaled .2)
            rng.integers(90_000, 10_500_001, m, dtype=np.int64),  # price (.2)
            rng.integers(0, 11, m, dtype=np.int64),              # discount (.2)
            rng.integers(0, 9, m, dtype=np.int64),               # tax (.2)
            rng.integers(0, 3, m, dtype=np.int32),               # returnflag
            rng.integers(0, 2, m, dtype=np.int32),               # linestatus
            (base + rng.integers(0, span, m)).astype(np.int32),  # shipdate
        ]
        store.bulk_load_arrays(arrays, ts=domain.storage.current_ts(),
                               dictionaries=dicts)
    domain.storage.regions.split_even(t.id, regions, store.base_rows)
    from .copr.parallel import prefetch_table

    prefetch_table(domain.storage, t.id)
    return s


# the canonical Q3-shaped query over build_q3_tables' pair (shared by the
# bench, the driver dryruns, and the multihost worker so they always
# exercise the same plan shape)
Q3_SQL = (
    "select l_orderkey, o_orderdate, o_shippriority,"
    " sum(l_extendedprice * (1 - l_discount)) as rev"
    " from lineitem, orders where l_orderkey = o_orderkey"
    " and o_orderdate < '1995-03-15' and l_shipdate > '1995-03-15'"
    " group by l_orderkey, o_orderdate, o_shippriority"
    " order by rev desc, l_orderkey limit 10"
)


def build_q3_tables(n_li: int, n_orders: int, regions: int = 8,
                    seed: int = 11):
    """Q3-shaped pair: orders (PK o_orderkey, the broadcast build side)
    joined by a lineitem fact table — the device lookup-join benchmark
    shape (reference executor/join.go role under TPC-H Q3)."""
    from .session import Domain
    from .types.values import parse_date

    domain = Domain()
    s = domain.new_session()
    s.execute("create table orders (o_orderkey bigint primary key,"
              " o_orderdate date, o_shippriority bigint)")
    s.execute("create table lineitem (l_orderkey bigint,"
              " l_extendedprice decimal(15,2), l_discount decimal(15,2),"
              " l_shipdate date)")
    rng = np.random.default_rng(seed)
    base = parse_date("1995-01-01")
    t_o = domain.catalog.info_schema().table("test", "orders")
    t_l = domain.catalog.info_schema().table("test", "lineitem")
    domain.storage.table(t_o.id).bulk_load_arrays([
        np.arange(n_orders, dtype=np.int64),
        (base + rng.integers(-400, 400, n_orders)).astype(np.int64),
        rng.integers(0, 5, n_orders),
    ], ts=domain.storage.current_ts())
    CHUNK = 1 << 21
    store = domain.storage.table(t_l.id)
    for s0 in range(0, n_li, CHUNK):
        m = min(CHUNK, n_li - s0)
        store.bulk_load_arrays([
            rng.integers(0, n_orders, m),
            rng.integers(90_000, 10_500_001, m),
            rng.integers(0, 11, m),
            (base + rng.integers(-300, 300, m)).astype(np.int64),
        ], ts=domain.storage.current_ts())
    domain.storage.regions.split_even(t_l.id, regions, store.base_rows)
    s.execute("analyze table orders")
    s.execute("analyze table lineitem")
    return s
