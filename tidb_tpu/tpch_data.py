"""Shared TPC-H-shaped data generation (bench.py + driver dryrun).

One lineitem recipe so the benchmark and the multichip dryrun can never
drift apart on schema or data distribution.
"""

from __future__ import annotations

import numpy as np

# authentic TPC-H column types (the reference's lineitem DDL uses
# DECIMAL(15,2) for quantity/extendedprice/discount/tax): decimals store
# as scaled int64, so every money column narrows on the wire
# (store/blockstore.py scaled-int decimal + parallel._wire_dtype)
LINEITEM_DDL = (
    "create table lineitem ("
    " l_orderkey bigint, l_quantity decimal(15,2),"
    " l_extendedprice decimal(15,2), l_discount decimal(15,2),"
    " l_tax decimal(15,2),"
    " l_returnflag varchar(1), l_linestatus varchar(1),"
    " l_shipdate date)"
)


def build_lineitem(n: int, regions: int = 8, seed: int = 7):
    """Fresh Domain with `n` synthetic lineitem rows split over `regions`
    regions; returns the session."""
    from .session import Domain
    from .types.values import parse_date

    domain = Domain()
    s = domain.new_session()
    s.execute(LINEITEM_DDL)
    t = domain.catalog.info_schema().table("test", "lineitem")
    store = domain.storage.table(t.id)
    rng = np.random.default_rng(seed)
    base = parse_date("1992-01-01")
    span = parse_date("1998-12-01") - base
    flags = np.array(["A", "N", "R"], dtype=object)
    status = np.array(["F", "O"], dtype=object)
    CHUNK = 1 << 21
    for s0 in range(0, n, CHUNK):
        m = min(CHUNK, n - s0)
        arrays = [
            rng.integers(1, n // 4 + 2, m, dtype=np.int64),     # orderkey
            rng.integers(100, 5100, m, dtype=np.int64),          # qty (scaled .2)
            rng.integers(90_000, 10_500_001, m, dtype=np.int64),  # price (.2)
            rng.integers(0, 11, m, dtype=np.int64),              # discount (.2)
            rng.integers(0, 9, m, dtype=np.int64),               # tax (.2)
            flags[rng.integers(0, 3, m)],                        # returnflag
            status[rng.integers(0, 2, m)],                       # linestatus
            (base + rng.integers(0, span, m)).astype(np.int32),  # shipdate
        ]
        store.bulk_load_arrays(arrays, ts=domain.storage.current_ts())
    domain.storage.regions.split_even(t.id, regions, store.base_rows)
    from .copr.parallel import prefetch_table

    prefetch_table(domain.storage, t.id)
    return s
