"""Query tracing: span trees from the wire protocol down to XLA.

Reference: util/tracing (the reference's opentracing shim feeding
executor/trace.go's `TRACE <stmt>`), infoschema/slow_log.go (the
structured slow-query log) and util/execdetails (per-phase runtime
stats).  On a TPU backend the phases that matter are different from
TiKV's — XLA compile vs. program-cache hit, host->device transfer over
the tunnel, device execute, and the packed readback round trip — so the
span vocabulary is TPU-native while the three surfaces mirror the
reference: `TRACE [FORMAT='row'|'json'] <stmt>` over the wire,
INFORMATION_SCHEMA.SLOW_QUERY with per-phase columns, and aggregate
per-phase histograms on /metrics with recent traces on /status.

Design constraints (README "Observability"):

- contextvar-carried: spans nest through the session call stack with no
  plumbing; worker threads (distsql fan-out, transfer pool) re-attach
  explicitly via `attach(parent)`.
- strictly zero-cost when disabled: `span()` is one contextvar read +
  one `is None` test returning a no-op singleton; nothing allocates.
- ring buffer of recent query traces (process-global, bounded) backs
  /status and post-hoc inspection without unbounded growth.
- ONE execution-stats collection path: the per-operator stats EXPLAIN
  ANALYZE shows, the statement summary's phase aggregates and the slow
  log all read the same finished QueryTrace.
"""

from .recorder import (  # noqa: F401
    TRACE_RING,
    OperatorStats,
    QueryTrace,
    Span,
    annotate,
    attach,
    current_span,
    current_trace,
    finish_trace,
    run_attached,
    span,
    start_trace,
    tracing_active,
)
from .recorder import NOOP  # noqa: F401
from .export import (  # noqa: F401
    graft_or_append,
    import_trace,
    trace_payload,
)
from .slowlog import SlowQueryLog  # noqa: F401
from .profiler import (  # noqa: F401
    PROFILER,
    Profiler,
    install_profiler,
    stmt_class,
)
