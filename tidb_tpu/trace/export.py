"""Cross-host span export/import (coordination plane, ROADMAP trace
follow-up (a)).

Workers serialize each finished QueryTrace — the same dict tree
`TRACE FORMAT='json'` renders — and ship it to the coordinator at query
end (coord/plane.py owns the transport and the per-host byte cap).  The
coordinator rebuilds the span tree, tags every imported root with the
source host, and either GRAFTS it under its own trace of the same
statement (matched by qid, the SPMD statement-sequence correlation id)
or appends it to the ring standalone.  EXPLAIN ANALYZE, SLOW_QUERY and
/status then show ONE tree spanning hosts instead of each process
keeping a private fragment.
"""

from __future__ import annotations

from typing import Optional

from .recorder import TRACE_RING, QueryTrace, Span


def trace_payload(tr: QueryTrace) -> dict:
    """JSON-safe payload for one finished trace (adds the cross-host
    correlation id to the TRACE FORMAT='json' tree)."""
    d = tr.to_dict()
    d["qid"] = getattr(tr, "qid", None)
    d["uid"] = getattr(tr, "uid", None)
    return d


def import_trace(payload: dict, host: Optional[int] = None) -> QueryTrace:
    """Rebuild a forwarded payload into a QueryTrace whose span offsets
    and durations are preserved (start times re-anchor to import time —
    only RELATIVE offsets travel, so clock skew between hosts never
    corrupts the tree)."""
    tr = QueryTrace(payload.get("sql") or "",
                    int(payload.get("conn_id") or 0), imported=True)
    tr.qid = payload.get("qid")
    tr.imported_from = host
    tr.finished = True
    start_time = payload.get("start_time")
    if start_time:
        tr.start_time = float(start_time)
    base = tr.root.start_ns

    def build(d: dict) -> Span:
        s = Span(str(d.get("name") or "span"), tr)
        s.start_ns = base + int(d.get("start_us") or 0) * 1000
        s.dur_ns = int(d.get("duration_us") or 0) * 1000
        attrs = d.get("attrs")
        if attrs:
            s.attrs = dict(attrs)
        s.children = [build(c) for c in d.get("children") or ()]
        return s

    root = build(payload.get("root") or {})
    if host is not None:
        if root.attrs is None:
            root.attrs = {}
        root.attrs["host"] = int(host)
    tr.root = root
    return tr


def graft_or_append(payload: dict, host: Optional[int] = None,
                    ring=None) -> str:
    """Join a forwarded trace to the local ring: grafted as a child of
    the local trace with the same qid when one exists (one tree spanning
    hosts), appended standalone otherwise.  Imported traces never serve
    as graft targets — two workers' trees for the same statement both
    hang under the coordinator's, not under each other."""
    ring = TRACE_RING if ring is None else ring
    tr = import_trace(payload, host=host)
    src_uid = payload.get("uid")
    if tr.qid:
        for local in reversed(list(ring)):
            if (getattr(local, "qid", None) == tr.qid
                    and getattr(local, "imported_from", None) is None
                    # never graft a trace under ITSELF: with batched
                    # background forwarding the origin trace may already
                    # sit in this process's ring when its payload lands
                    and (src_uid is None
                         or getattr(local, "uid", None) != src_uid)):
                with local._mu:
                    local.root.children.append(tr.root)
                return "grafted"
    ring.append(tr)
    return "appended"
