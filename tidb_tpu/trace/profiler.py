"""Continuous profiler: every finished QueryTrace folds into weighted
span-path stacks over rotating time windows (ISSUE 13).

The profiler rides the `TRACE_EXPORT_HOOK` seam — the same hook the
coordination plane uses to forward worker traces — CHAINING the
previously installed hook, never replacing it.  Folding one finished
trace is O(spans): walk the span tree once, attribute each span's SELF
time (duration minus children) to its root-to-span path, and accumulate
into the current window's bounded path table.  With tracing disabled
nothing ever reaches the hook, so the disabled path stays the span
recorder's single contextvar read.

Surfaces:

- `/flame` — standard folded-stacks text (``frame;frame;frame weight``
  per line, weight in self-microseconds), directly consumable by
  flamegraph.pl / speedscope / inferno;
- `/status` "profile" section — window metadata + the top stacks;
- ``INFORMATION_SCHEMA.TIDB_TPU_PROFILE`` — one row per (window, stack).

Frames carry engine attribution (``copr.device.execute:mesh`` vs
``...:tile-fanout`` vs MPP rungs) so compiled-path vs interpreted-path
time separates per Flare's compile-attribution argument.

Knobs (env, read at construction): ``TIDB_TPU_PROFILE`` (0 disables),
``TIDB_TPU_PROFILE_WINDOW_S`` (rotation period, default 60),
``TIDB_TPU_PROFILE_WINDOWS`` (windows retained, default 5),
``TIDB_TPU_PROFILE_MAX_PATHS`` (distinct stacks per window; overflow
folds into ``<other>``), ``TIDB_TPU_PROFILE_DIR`` (when set, windows
persist atomically on rotation and reload at install — /flame survives
a rolling restart, ISSUE 17).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from ..metrics import REGISTRY
from ..util_concurrency import make_lock

#: depth cap on folded stacks: deeper spans attribute to their ancestor
#: path (flame views past ~32 frames are unreadable anyway)
MAX_STACK_DEPTH = 32


class Profiler:
    def __init__(self, window_s: Optional[float] = None,
                 n_windows: Optional[int] = None,
                 max_paths: Optional[int] = None,
                 enabled: Optional[bool] = None,
                 persist_dir: Optional[str] = None):
        self.window_s = float(window_s if window_s is not None else
                              os.environ.get("TIDB_TPU_PROFILE_WINDOW_S",
                                             "60"))
        self.n_windows = int(n_windows if n_windows is not None else
                             os.environ.get("TIDB_TPU_PROFILE_WINDOWS",
                                            "5"))
        self.max_paths = int(max_paths if max_paths is not None else
                             os.environ.get("TIDB_TPU_PROFILE_MAX_PATHS",
                                            "512"))
        self.enabled = (os.environ.get("TIDB_TPU_PROFILE", "1") != "0"
                        if enabled is None else bool(enabled))
        self.persist_dir = (persist_dir if persist_dir is not None else
                            os.environ.get("TIDB_TPU_PROFILE_DIR",
                                           "")) or None
        self._mu = make_lock("trace.profiler:Profiler._mu")
        self._windows: deque = deque(maxlen=max(self.n_windows, 1))
        self._installed = False
        self._loaded = False  # persisted windows restored once

    # ---- hook install (chains, never replaces) --------------------------
    def install(self):
        """Chain this profiler onto the trace export chain.  Idempotent:
        the Domain constructor calls it every time, and a coordination
        plane chained before or after stays in the chain (WorkerPlane
        chains too; list-removal semantics mean either side can leave
        without dropping the other)."""
        from . import recorder

        # restore persisted windows BEFORE taking the lock (file I/O is
        # never performed under _mu — the lock-blocking lint's rule and
        # the reason rotation snapshots then writes outside it too)
        with self._mu:
            need_load = bool(self.persist_dir) and not self._loaded
        restored = self._load() if need_load else None
        with self._mu:
            if not self._loaded:
                self._loaded = True
                if restored and not self._windows:
                    for w in restored:
                        self._windows.append(w)
            recorder.chain_export_hook(self.fold)
            self._installed = True

    # ---- folding --------------------------------------------------------
    def fold(self, tr):
        """Fold one finished QueryTrace into the current window."""
        if not self.enabled:
            return
        now = time.time()
        with self._mu:
            prev_start = (self._windows[-1]["start"] if self._windows
                          else None)
            w = self._current_locked(now)
            rotated = w["start"] != prev_start
            w["traces"] += 1
            self._walk(tr.root, "", w["paths"], 0)
        REGISTRY.inc("profile_traces_folded_total")
        if rotated and self.persist_dir:
            # persist on rotation, outside the lock: snapshot under _mu,
            # then atomic tmp-write + os.replace so readers (and a
            # restarted process) never observe a torn file
            self._persist()

    def _current_locked(self, now: float) -> dict:
        if not self._windows or \
                now - self._windows[-1]["start"] >= self.window_s:
            if self._windows:
                REGISTRY.inc("profile_windows_rotated_total")
            self._windows.append({"start": now, "traces": 0, "paths": {}})
        return self._windows[-1]

    def _walk(self, s, prefix: str, paths: dict, depth: int):
        name = s.name
        a = s.attrs
        if a:
            eng = a.get("engine") or a.get("rung")
            if eng:
                name = f"{name}:{eng}"
        stack = f"{prefix};{name}" if prefix else name
        dur = s.dur_ns or 0
        recurse = depth < MAX_STACK_DEPTH and s.children
        if recurse:
            self_ns = max(dur - sum(c.dur_ns or 0 for c in s.children), 0)
        else:
            # depth cap: un-walked children attribute their whole time
            # to this truncated ancestor frame instead of vanishing
            self_ns = dur
        self_us = self_ns // 1000
        if self_us > 0 or not s.children:
            self._bump_locked(paths, stack, self_us)
        if recurse:
            for c in s.children:
                self._walk(c, stack, paths, depth + 1)

    def _bump_locked(self, paths: dict, key: str, us: int):
        rec = paths.get(key)
        if rec is None:
            if len(paths) >= self.max_paths:
                # bounded path table: long-tail stacks fold into one
                # overflow frame instead of growing without limit
                key = "<other>"
                rec = paths.setdefault(key, [0, 0])
            else:
                rec = paths[key] = [0, 0]
        rec[0] += us
        rec[1] += 1

    # ---- operator sampling (ISSUE 18 trace (a)) -------------------------
    def fold_explain(self, ops):
        """Fold one EXPLAIN ANALYZE run's operator stats into the
        current window: `ops` is [(depth, operator_id, inclusive_ns)]
        in pre-order, stacks become root-to-operator id chains
        (``op:HashAgg_3;op:TableReader_5``) weighted by SELF time
        (inclusive minus direct children) — so /flame and the profile
        memtable carry the planner's operator ids alongside the
        span-path stacks, attributing window time to plan shape."""
        if not self.enabled or not ops:
            return
        n = len(ops)
        frames: List[str] = []
        now = time.time()
        with self._mu:
            w = self._current_locked(now)
            for i, (depth, op_id, inc_ns) in enumerate(ops):
                del frames[depth:]
                frames.append(f"op:{op_id}")
                child_ns = 0
                for d2, _o2, ns2 in ops[i + 1:n]:
                    if d2 <= depth:
                        break
                    if d2 == depth + 1:
                        child_ns += ns2
                self_us = max(inc_ns - child_ns, 0) // 1000
                is_leaf = i + 1 >= n or ops[i + 1][0] <= depth
                if self_us > 0 or is_leaf:
                    self._bump_locked(
                        w["paths"], ";".join(frames[:MAX_STACK_DEPTH]),
                        self_us)
        REGISTRY.inc("profile_op_samples_total")

    # ---- reads ----------------------------------------------------------
    def _merged_locked(self) -> Dict[str, list]:
        merged: Dict[str, list] = {}
        for w in self._windows:
            for stack, (us, n) in w["paths"].items():
                rec = merged.setdefault(stack, [0, 0])
                rec[0] += us
                rec[1] += n
        return merged

    def folded(self) -> str:
        """Folded-stacks text over all retained windows: one
        ``frame;frame weight`` line per stack, weight = accumulated
        self-time in microseconds, heaviest first."""
        with self._mu:
            merged = self._merged_locked()
        lines = [f"{stack} {us}" for stack, (us, _n)
                 in sorted(merged.items(), key=lambda kv: -kv[1][0])]
        return "\n".join(lines) + ("\n" if lines else "")

    def status_section(self, top: int = 12) -> dict:
        with self._mu:
            merged = self._merged_locked()
            windows = [{"start": w["start"], "traces": w["traces"],
                        "stacks": len(w["paths"])} for w in self._windows]
        ranked = sorted(merged.items(), key=lambda kv: -kv[1][0])
        return {
            "enabled": self.enabled,
            "window_s": self.window_s,
            "windows": windows,
            "stacks": len(merged),
            "top": [{"stack": stack, "self_ms": round(us / 1000.0, 3),
                     "count": n} for stack, (us, n) in ranked[:top]],
        }

    def rows(self) -> List[tuple]:
        """INFORMATION_SCHEMA.TIDB_TPU_PROFILE rows: (window_start,
        stack, count, self_ms), newest window last, heaviest first."""
        out = []
        with self._mu:
            snap = [(w["start"], dict(w["paths"])) for w in self._windows]
        for start, paths in snap:
            ts = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(start))
            for stack, (us, n) in sorted(paths.items(),
                                         key=lambda kv: -kv[1][0]):
                out.append((ts, stack, n, round(us / 1000.0, 3)))
        return out

    def reset(self):
        with self._mu:
            self._windows.clear()

    # ---- persistence across restarts (ISSUE 17) -------------------------
    def _file(self) -> str:
        return os.path.join(self.persist_dir, "profile_windows.json")

    def _persist(self):
        with self._mu:
            snap = [{"start": w["start"], "traces": w["traces"],
                     "paths": {k: list(v) for k, v in w["paths"].items()}}
                    for w in self._windows]
        try:
            os.makedirs(self.persist_dir, exist_ok=True)
            tmp = self._file() + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"window_s": self.window_s, "windows": snap}, f)
            os.replace(tmp, self._file())
        except OSError:  # pragma: no cover - disk-full etc.
            pass

    def persist_now(self):
        """Flush the current windows unconditionally (graceful-drain
        seam; rotation-driven persistence covers steady state)."""
        if self.persist_dir:
            self._persist()

    def _load(self) -> Optional[list]:
        try:
            with open(self._file()) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return None
        out = []
        for w in doc.get("windows", ())[-max(self.n_windows, 1):]:
            try:
                out.append({
                    "start": float(w["start"]),
                    "traces": int(w["traces"]),
                    "paths": {str(k): [int(v[0]), int(v[1])]
                              for k, v in w["paths"].items()},
                })
            except (KeyError, TypeError, ValueError, IndexError):
                return None  # torn/foreign file: start fresh
        return out


#: process-global profiler (installed by the Domain constructor)
PROFILER = Profiler()


def install_profiler():
    PROFILER.install()


# ---------------------------------------------------------------------------
# statement classification (SLO plane)
# ---------------------------------------------------------------------------

_DML_WORDS = ("insert", "update", "delete", "replace", "load")
_JOIN_RE = re.compile(r"\bjoin\b")
_AGG_RE = re.compile(
    r"\b(?:sum|count|avg|min|max|group_concat)\s*\(|\bgroup\s+by\b")


def stmt_class(sql: str) -> str:
    """Coarse statement class for per-class latency SLOs: point | agg |
    join | dml | other.  One cheap scan of the text — classification
    must not cost more than the histogram observation it labels."""
    s = sql.lstrip().lower()
    head = s.split(None, 1)[0].lstrip("(") if s else ""
    if head in _DML_WORDS:
        return "dml"
    if head not in ("select", "with"):
        return "other"
    if _JOIN_RE.search(s):
        return "join"
    if _AGG_RE.search(s):
        return "agg"
    return "point"
