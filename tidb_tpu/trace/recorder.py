"""Span-tree recorder: the low-overhead core of the trace subsystem.

A QueryTrace is a tree of Spans rooted at one statement execution.  The
CURRENT span travels in a contextvar; `span(name)` opens a child under
it.  Worker threads do not inherit the contextvar automatically — the
fan-out layers capture `current_span()` on the submitting thread and
re-enter with `attach(parent)` (the reference's opentracing
span-context propagation, contextvar-shaped).

Phase attribution: span names beginning with a known phase prefix (see
PHASES) aggregate into the per-phase totals the slow log, the statement
summary and the /metrics histograms consume; byte counts ride in span
attrs (`bytes=`), engine/rung attribution in `engine=` attrs.
"""

from __future__ import annotations

import itertools
import threading
import time
import uuid
import zlib
from collections import deque
from contextvars import ContextVar
from dataclasses import dataclass
from typing import Dict, List, Optional
from ..util_concurrency import make_lock

#: per-process statement-trace sequence: multi-controller SPMD runs the
#: same statement stream in every process, so (sql crc, seq) — the qid —
#: correlates one statement's traces ACROSS hosts (trace/export.py
#: grafts a worker's forwarded tree under the coordinator's by qid)
_TRACE_SEQ = itertools.count()
_TRACE_UID = itertools.count()
_PROC_TOKEN = uuid.uuid4().hex[:12]


@dataclass
class OperatorStats:
    """Per-operator runtime stats (rows/loops/time) for EXPLAIN ANALYZE —
    owned by the trace subsystem so the span tree and the operator table
    are one collection path (util/execdetails RuntimeStatsColl role)."""

    rows: int = 0
    loops: int = 0
    time_ns: int = 0
    # engine attribution (which engine actually served a cop task, incl.
    # mesh-rejection reasons — execdetails.go:326-396 analog)
    engine: str = ""

    def record(self, rows: int, dur_ns: int):
        self.rows += rows
        self.loops += 1
        self.time_ns += dur_ns


class Span:
    """One timed operation.  Children append under the owning trace's
    lock (fan-out workers record concurrently); attrs are written only
    by the thread inside the span, so they need no lock."""

    __slots__ = ("name", "start_ns", "dur_ns", "attrs", "children",
                 "_trace")

    def __init__(self, name: str, trace: "QueryTrace"):
        self.name = name
        self.start_ns = time.perf_counter_ns()
        self.dur_ns = 0
        self.attrs: Optional[Dict[str, object]] = None
        self.children: List["Span"] = []
        self._trace = trace

    def set(self, **attrs):
        if self.attrs is None:
            self.attrs = {}
        self.attrs.update(attrs)
        return self

    def add(self, key: str, value):
        """Accumulate a numeric attr (bytes, backoff_ms, ...)."""
        if self.attrs is None:
            self.attrs = {}
        self.attrs[key] = self.attrs.get(key, 0) + value

    def finish(self):
        self.dur_ns = time.perf_counter_ns() - self.start_ns


class QueryTrace:
    """The span tree of one statement execution plus its EXPLAIN ANALYZE
    operator stats — the single execution-stats carrier."""

    def __init__(self, sql: str, conn_id: int = 0,
                 imported: bool = False):
        self.sql = sql
        self.conn_id = conn_id
        self.start_time = time.time()
        self._mu = make_lock("trace.recorder:QueryTrace._mu")
        self.root = Span("session.execute", self)
        self.op_stats: Dict[int, OperatorStats] = {}
        self.finished = False
        # cross-host correlation id + import provenance (coord plane).
        # Imported shells (trace/export.py rebuilding a forwarded tree)
        # MUST NOT consume the sequence: SPMD correlation relies on every
        # process assigning the same seq to the same statement, and an
        # ingest that advanced the coordinator's counter would desync
        # qids from the workers' forever after the first forwarded trace.
        self.imported_from: Optional[int] = None
        # process-unique identity: with forwarding now BATCHED and
        # backgrounded (coord follow-up (c)), a trace may already sit in
        # this process's ring when its own payload flushes — the graft
        # step uses the uid to never graft a trace under itself.  The
        # token is RANDOM per process, not the pid: containerized SPMD
        # hosts all run as pid 1 with lockstep statement counters, and a
        # pid-based uid would collide across hosts and wrongly suppress
        # cross-host grafts.
        self.uid = f"{_PROC_TOKEN}-{next(_TRACE_UID)}"
        if imported:
            self.seq = -1
            self.qid: Optional[str] = None
        else:
            self.seq = next(_TRACE_SEQ)
            crc = zlib.crc32(sql.encode("utf-8", "replace")) & 0xFFFFFFFF
            self.qid = f"{crc:08x}-{self.seq}"

    # ---- tree assembly --------------------------------------------------
    def child(self, parent: Span, name: str) -> Span:
        s = Span(name, self)
        with self._mu:
            parent.children.append(s)
        return s

    def add_span(self, name: str, dur_ns: int = 0, **attrs) -> Span:
        """Append a pre-timed span under the root after the fact — the
        wire layer records result write time onto the already-finished
        trace (the statement ended before the rows hit the socket)."""
        s = Span(name, self)
        s.dur_ns = dur_ns
        if attrs:
            s.set(**attrs)
        with self._mu:
            self.root.children.append(s)
        return s

    # ---- rendering ------------------------------------------------------
    def duration_ms(self) -> float:
        return (self.root.dur_ns or
                (time.perf_counter_ns() - self.root.start_ns)) / 1e6

    def rows(self, indent_root: bool = True) -> List[tuple]:
        """(operation, start_offset_ms, duration_ms) rows, depth-first,
        with two-space indentation showing the tree (TRACE row format)."""
        out: List[tuple] = []
        t0 = self.root.start_ns

        def walk(s: Span, depth: int):
            dur = s.dur_ns or (time.perf_counter_ns() - s.start_ns)
            label = "  " * depth + s.name
            if s.attrs:
                kv = ", ".join(f"{k}: {v}" for k, v in sorted(s.attrs.items()))
                label += f" {{{kv}}}"
            out.append((label, f"{(s.start_ns - t0) / 1e6:.3f}ms",
                        f"{dur / 1e6:.3f}ms"))
            for c in s.children:
                walk(c, depth + 1)

        walk(self.root, 0)
        return out

    def to_dict(self) -> dict:
        def walk(s: Span) -> dict:
            d = {
                "name": s.name,
                "start_us": (s.start_ns - self.root.start_ns) // 1000,
                "duration_us": (s.dur_ns or 0) // 1000,
            }
            if s.attrs:
                d["attrs"] = {k: (v if isinstance(v, (int, float, str, bool))
                                  else str(v))
                              for k, v in s.attrs.items()}
            if s.children:
                d["children"] = [walk(c) for c in s.children]
            return d

        return {"sql": self.sql[:512], "conn_id": self.conn_id,
                "start_time": self.start_time, "root": walk(self.root)}

    # ---- phase aggregation ---------------------------------------------
    def phase_totals(self) -> dict:
        """Aggregate the tree into the per-phase columns SLOW_QUERY and
        the statement summary expose.  ms totals per phase prefix, byte
        totals for transfer/readback, backoff from attr accumulation,
        and engine/rung attribution collected from span attrs."""
        tot = {
            "parse_ms": 0.0, "plan_ms": 0.0, "compile_ms": 0.0,
            "transfer_ms": 0.0, "transfer_bytes": 0,
            "device_ms": 0.0, "readback_ms": 0.0, "readback_bytes": 0,
            "backoff_ms": 0.0, "exchange_ms": 0.0, "commit_ms": 0.0,
            "backfill_ms": 0.0, "throttle_ms": 0.0, "chunks": 0,
            "compile_hits": 0, "compile_misses": 0, "cop_tasks": 0,
            "wire_bytes": 0, "result_rows": 0,
            "hbm_peak_bytes": 0,
            "engines": set(), "devices": set(),
        }

        def nested_phase_ms(s: Span) -> float:
            """Descendant time already attributed to other copr phases."""
            out = 0.0
            for c in s.children:
                if c.name in ("copr.device.execute", "copr.readback",
                              "copr.transfer"):
                    out += (c.dur_ns or 0) / 1e6
                out += nested_phase_ms(c)
            return out

        def walk(s: Span):
            ms = (s.dur_ns or 0) / 1e6
            a = s.attrs or {}
            n = s.name
            if n == "copr.compile":
                # a cache miss labels the whole first dispatch; the
                # execute/readback spans nested inside it are attributed
                # to their own phases, so compile keeps only its SELF
                # time (no double counting across phase columns)
                tot["compile_ms"] += max(ms - nested_phase_ms(s), 0.0)
            elif n in PHASES:
                tot[PHASES[n]] += ms
            if n == "copr.compile":
                if a.get("cache") == "hit":
                    tot["compile_hits"] += 1
                else:
                    tot["compile_misses"] += 1
            elif n in ("copr.transfer",):
                tot["transfer_bytes"] += int(a.get("bytes", 0))
            elif n == "copr.readback":
                tot["readback_bytes"] += int(a.get("bytes", 0))
            elif n == "cop.task":
                tot["cop_tasks"] += 1
            elif n == "copr.chunk":
                # chunked-dispatch visibility (ISSUE 17): per-statement
                # device-launch count for EXPLAIN ANALYZE / slow log
                tot["chunks"] += 1
            elif n.startswith("wire."):
                tot["wire_bytes"] += int(a.get("bytes", 0))
            tot["wire_bytes"] += int(a.get("wire_read_bytes", 0))
            tot["backoff_ms"] += float(a.get("backoff_ms", 0.0))
            # device-memory telemetry (ISSUE 13): dispatch sites stamp
            # the resident HBM bytes (hot mesh cache + cold tier) on the
            # execute span — the trace-level high-water mark feeds
            # EXPLAIN ANALYZE's per-statement HBM attribution
            hbm = a.get("hbm_bytes")
            if hbm is not None and int(hbm) > tot["hbm_peak_bytes"]:
                tot["hbm_peak_bytes"] = int(hbm)
            eng = a.get("engine") or a.get("rung")
            if eng:
                tot["engines"].add(str(eng))
            for d in a.get("device_ids", ()) or ():
                tot["devices"].add(int(d))
            if "device" in a:
                tot["devices"].add(int(a["device"]))
            for c in s.children:
                walk(c)

        walk(self.root)
        # result rows = the TOP-LEVEL drain loops' row counts (nested
        # subplan drains during planning don't count toward the result)
        tot["result_rows"] = sum(
            int((c.attrs or {}).get("rows", 0))
            for c in self.root.children if c.name == "executor.next")
        tot["engines"] = ",".join(sorted(tot["engines"]))
        tot["devices"] = ",".join(str(d) for d in sorted(tot["devices"]))
        return tot


#: span name -> phase-total key (ms sums)
PHASES = {
    "parse": "parse_ms",
    "plan": "plan_ms",
    "copr.compile": "compile_ms",
    "copr.transfer": "transfer_ms",
    # one fused XLA launch per mesh dispatch (whole-fragment fusion);
    # the legacy name stays mapped for externally recorded traces
    "copr.device.execute": "device_ms",
    "copr.execute": "device_ms",
    "copr.readback": "readback_ms",
    "mpp.exchange": "exchange_ms",
    "txn.prewrite": "commit_ms",
    "txn.commit": "commit_ms",
    # online DDL index builds (ddl.backfill spans per batch)
    "ddl.backfill": "backfill_ms",
    # resource-group admission wait between chunked dispatches
    "resgroup.throttle": "throttle_ms",
}

#: phases surfaced as /metrics histograms on every finished trace
_METRIC_PHASES = ("parse_ms", "plan_ms", "compile_ms", "transfer_ms",
                  "device_ms", "readback_ms", "backoff_ms", "backfill_ms")

# the CURRENT span (None = tracing disabled for this context)
_CUR: ContextVar[Optional[Span]] = ContextVar("tidb_tpu_trace", default=None)

#: most recent finished traces (process-global; /status + tests)
TRACE_RING: deque = deque(maxlen=32)

#: cross-host span forwarding hook: a worker-side coordination plane
#: (tidb_tpu/coord) installs its forward_trace here so every finished
#: trace ships to the coordinator at query end; None (the default)
#: keeps finish_trace allocation-free
TRACE_EXPORT_HOOK = None

#: chain participants behind TRACE_EXPORT_HOOK (chain_export_hook /
#: unchain_export_hook below).  While the list is empty the seam stays
#: None so the disabled finish_trace path costs one global read.
_EXPORT_CHAIN: list = []
_EXPORT_MU = make_lock("trace.recorder:_EXPORT_MU")


def _dispatch_export(tr):
    """The single installed hook while any participant is chained: fan
    the finished trace to every participant in chain order, isolating
    failures (a broken forwarder must not starve the profiler, or vice
    versa).  Dispatch runs on a snapshot, outside _EXPORT_MU, so a
    participant may itself take locks freely."""
    for fn in list(_EXPORT_CHAIN):
        try:
            fn(tr)
        except Exception:
            pass


def chain_export_hook(fn):
    """Add `fn` to the export chain (idempotent).  A hook installed
    directly on TRACE_EXPORT_HOOK (tests, third parties) is adopted
    into the chain rather than dropped."""
    global TRACE_EXPORT_HOOK
    with _EXPORT_MU:
        cur = TRACE_EXPORT_HOOK
        if (cur is not None and cur is not _dispatch_export
                and cur not in _EXPORT_CHAIN):
            _EXPORT_CHAIN.append(cur)
        if fn not in _EXPORT_CHAIN:
            _EXPORT_CHAIN.append(fn)
        TRACE_EXPORT_HOOK = _dispatch_export


def unchain_export_hook(fn):
    """Remove `fn` wherever it sits in the chain — list removal, NOT
    restore-if-top, so a stopped participant always leaves regardless
    of install order.  Unknown hooks are a no-op."""
    global TRACE_EXPORT_HOOK
    with _EXPORT_MU:
        try:
            _EXPORT_CHAIN.remove(fn)
        except ValueError:
            pass
        if not _EXPORT_CHAIN and TRACE_EXPORT_HOOK is _dispatch_export:
            TRACE_EXPORT_HOOK = None


def clear_export_hooks():
    """Drop every chained participant and null the seam (plane reset /
    test isolation)."""
    global TRACE_EXPORT_HOOK
    with _EXPORT_MU:
        _EXPORT_CHAIN.clear()
        TRACE_EXPORT_HOOK = None


class _NoopSpan:
    """Singleton returned when tracing is off: every operation is a
    no-op, so the disabled path costs one contextvar read."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self

    def add(self, key, value):
        return self


NOOP = _NoopSpan()


class _SpanCtx:
    """Context manager entering/leaving one real span."""

    __slots__ = ("span", "_token")

    def __init__(self, s: Span):
        self.span = s
        self._token = None

    def __enter__(self):
        self._token = _CUR.set(self.span)
        return self.span

    def __exit__(self, *exc):
        self.span.finish()
        _CUR.reset(self._token)
        return False


def tracing_active() -> bool:
    return _CUR.get() is not None


def current_span() -> Optional[Span]:
    return _CUR.get()


def current_trace() -> Optional[QueryTrace]:
    s = _CUR.get()
    return s._trace if s is not None else None


def span(name: str, **attrs):
    """Open a child span under the current one; no-op when disabled."""
    cur = _CUR.get()
    if cur is None:
        return NOOP
    s = cur._trace.child(cur, name)
    if attrs:
        s.set(**attrs)
    return _SpanCtx(s)


def annotate(**attrs):
    """Attach attrs to the current span; no-op when disabled."""
    cur = _CUR.get()
    if cur is not None:
        cur.set(**attrs)


def attach(parent: Optional[Span]):
    """Re-enter a span context on another thread (fan-out workers):
    `with attach(parent): ...` makes `parent` the current span there.
    Passing None or the no-op (captured while tracing was off) no-ops."""
    if not isinstance(parent, Span):
        return NOOP
    return _AttachCtx(parent)


def run_attached(parent: Optional[Span], fn, *args, **kwargs):
    """Run fn under a re-attached span context (thread-pool submit
    wrapper for the transfer/fan-out pools)."""
    with attach(parent):
        return fn(*args, **kwargs)


class _AttachCtx:
    __slots__ = ("_parent", "_token")

    def __init__(self, parent: Span):
        self._parent = parent
        self._token = None

    def __enter__(self):
        self._token = _CUR.set(self._parent)
        return self._parent

    def __exit__(self, *exc):
        _CUR.reset(self._token)
        return False


def start_trace(sql: str, conn_id: int = 0) -> tuple:
    """Begin a trace for one statement execution; returns (trace, token).
    The caller MUST pass both to finish_trace (try/finally)."""
    tr = QueryTrace(sql, conn_id)
    token = _CUR.set(tr.root)
    return tr, token


def finish_trace(tr: QueryTrace, token):
    """Close the root span, restore the context, publish the ring entry
    and the per-phase metrics histograms."""
    _CUR.reset(token)
    tr.root.finish()
    tr.finished = True
    hook = TRACE_EXPORT_HOOK
    if hook is not None:
        # worker plane active: the finished tree rejoins the
        # coordinator's ring (failures count, never raise into the
        # query).  Fires BEFORE the local ring append so an in-process
        # coordinator grafts under ITS trace, never under this one.
        try:
            hook(tr)
        except Exception:
            pass
    TRACE_RING.append(tr)
    from ..metrics import REGISTRY

    totals = tr.phase_totals()
    # real log2-bucket histograms (ISSUE 13): p50/p95/p99 per phase on
    # /metrics and /status instead of the old _count/_sum/_max triple
    for key in _METRIC_PHASES:
        v = totals.get(key, 0)
        if v:
            REGISTRY.observe_hist(f"trace_phase_{key}", float(v))
    if totals["transfer_bytes"]:
        REGISTRY.inc("trace_transfer_bytes_total",
                     float(totals["transfer_bytes"]))
    if totals["readback_bytes"]:
        REGISTRY.inc("trace_readback_bytes_total",
                     float(totals["readback_bytes"]))
    return totals
