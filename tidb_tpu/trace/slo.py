"""SLO AUTO mode: per-class thresholds derived from observed p99.

Reference: TiDB's expensive-query threshold is a static knob; real
fleets instead alert on a *rolling* latency baseline.  Setting a
`tidb_tpu_slo_<class>_ms` sysvar to the string ``auto`` (ISSUE 20
satellite) derives that class's breach threshold from the statement
latencies actually observed, instead of a hand-tuned constant:

* every finished traced statement feeds a per-class **rotating window
  pair** of bounded log2-bucket histograms (the same structure as
  `metrics.Histogram`, a few hundred bytes per class).  The current
  window rotates out after `TIDB_TPU_SLO_AUTO_WINDOW_S` seconds
  (default 60); the previous window is kept so the estimate always
  spans between one and two windows of traffic and a rotation never
  empties the baseline;
* the AUTO threshold is the merged windows' p99 multiplied by
  `TIDB_TPU_SLO_AUTO_HEADROOM` (default 2.0) — a statement is a breach
  when it exceeds twice the recent p99, i.e. the SLO tracks the
  workload's own tail instead of a guess made at deploy time;
* until `TIDB_TPU_SLO_AUTO_MIN_SAMPLES` observations (default 50) have
  landed in the windows, the threshold is 0 and burn accounting stays
  off — a cold server must not mark its first queries as breaches of a
  baseline that does not exist yet.

The tracker is process-global (like the metrics REGISTRY) because the
burn counters it gates are process-global; fixed-threshold classes feed
it too, so flipping a class to ``auto`` acts on an already-warm
baseline.  Its mutex is a leaf: held only around bucket arithmetic.
"""

from __future__ import annotations

import os
import time
from typing import Dict

from ..metrics import Histogram
from ..util_concurrency import make_lock

_WINDOW_ENV = "TIDB_TPU_SLO_AUTO_WINDOW_S"
_MIN_SAMPLES_ENV = "TIDB_TPU_SLO_AUTO_MIN_SAMPLES"
_HEADROOM_ENV = "TIDB_TPU_SLO_AUTO_HEADROOM"
_DEFAULT_WINDOW_S = 60.0
_DEFAULT_MIN_SAMPLES = 50
_DEFAULT_HEADROOM = 2.0

#: the sysvar value that selects AUTO mode (case-insensitive)
AUTO = "auto"


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class _ClassWindows:
    """One statement class's rotating window pair (mutated under the
    owning tracker's mutex; never locked on its own)."""

    __slots__ = ("cur", "prev", "cur_start")

    def __init__(self, now: float):
        self.cur = Histogram()
        self.prev = Histogram()
        self.cur_start = now

    def rotate_if_due_locked(self, now: float, window_s: float):
        if now - self.cur_start >= window_s:
            # one rotation even after a long idle gap: the stale
            # previous window ages out, the (possibly stale) current
            # one becomes the baseline until fresh traffic lands
            self.prev = self.cur
            self.cur = Histogram()
            self.cur_start = now

    def merged_locked(self) -> Histogram:
        m = Histogram()
        m.counts = [a + b for a, b in zip(self.cur.counts,
                                          self.prev.counts)]
        m.sum = self.cur.sum + self.prev.sum
        m.count = self.cur.count + self.prev.count
        return m


class SloAutoWindows:
    """Per-class rotating latency windows + the derived AUTO threshold."""

    def __init__(self):
        self._mu = make_lock("trace.slo:SloAutoWindows._mu")
        self._classes: Dict[str, _ClassWindows] = {}

    def _window_s(self) -> float:
        return max(_env_float(_WINDOW_ENV, _DEFAULT_WINDOW_S), 0.05)

    def _min_samples(self) -> int:
        return max(int(_env_float(_MIN_SAMPLES_ENV,
                                  _DEFAULT_MIN_SAMPLES)), 1)

    def _headroom(self) -> float:
        return max(_env_float(_HEADROOM_ENV, _DEFAULT_HEADROOM), 1.0)

    def observe(self, cls: str, dur_ms: float) -> None:
        now = time.monotonic()
        with self._mu:
            w = self._classes.get(cls)
            if w is None:
                w = self._classes[cls] = _ClassWindows(now)
            w.rotate_if_due_locked(now, self._window_s())
            w.cur.observe(float(dur_ms))

    def threshold_ms(self, cls: str) -> float:
        """The derived breach threshold: headroom x rolling p99, or 0.0
        while the windows hold fewer than the minimum samples."""
        now = time.monotonic()
        with self._mu:
            w = self._classes.get(cls)
            if w is None:
                return 0.0
            w.rotate_if_due_locked(now, self._window_s())
            m = w.merged_locked()
        if m.count < self._min_samples():
            return 0.0
        return m.quantile(0.99) * self._headroom()

    def snapshot(self, cls: str) -> dict:
        """Observability read for /status: window occupancy + the
        rolling p99 the threshold derives from."""
        now = time.monotonic()
        with self._mu:
            w = self._classes.get(cls)
            if w is None:
                return {"samples": 0, "p99_ms": 0.0}
            w.rotate_if_due_locked(now, self._window_s())
            m = w.merged_locked()
        return {
            "samples": m.count,
            "p99_ms": m.quantile(0.99),
            "min_samples": self._min_samples(),
            "headroom": self._headroom(),
            "window_s": self._window_s(),
        }

    def reset(self) -> None:
        """Test seam: drop all windows."""
        with self._mu:
            self._classes.clear()


SLO_AUTO = SloAutoWindows()


def is_auto(raw: str) -> bool:
    """Does a `tidb_tpu_slo_<class>_ms` sysvar value select AUTO mode?"""
    return isinstance(raw, str) and raw.strip().lower() == AUTO


def resolve_threshold_ms(raw: str, cls: str) -> float:
    """The effective breach threshold for one class given the sysvar's
    raw GLOBAL value: ``auto`` derives from the rolling windows, an
    integer is itself, anything unparseable disables burn accounting."""
    if is_auto(raw):
        return SLO_AUTO.threshold_ms(cls)
    try:
        return float(int(str(raw).strip() or 0))
    except ValueError:
        return 0.0
