"""Structured slow-query log backing INFORMATION_SCHEMA.SLOW_QUERY.

Reference: infoschema/slow_log.go — tidb-slow.log parsed back into a
virtual table.  Here each entry is one JSON line with the TPU-native
per-phase columns (compile/transfer/device/readback/backoff, engine and
device attribution) computed from the statement's QueryTrace, plus an
in-memory ring serving the memtable without touching disk.

Durability follows the delta-log torn-tail contract (store/persist):
an append interrupted mid-record (process kill, full disk) leaves a
torn final line; recovery DROPS the torn tail (that statement's entry
was never acknowledged anywhere) and counts it in
`slow_log_torn_tail_total` — it never poisons the table or fails the
server.  Mid-file corruption is equally non-fatal here (the log is
advisory, unlike the delta log) but counts separately.  Writes never
raise into the query path and never leak a file handle: the append
handle is scoped per record.
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import List, Optional

from ..store.fault import FAILPOINTS
from ..util_concurrency import make_lock

#: column order of INFORMATION_SCHEMA.SLOW_QUERY (infoschema_tables.py)
ENTRY_FIELDS = (
    "time", "conn_id", "query_time", "parse_ms", "plan_ms", "compile_ms",
    "compile_hits", "compile_misses", "transfer_bytes", "device_ms",
    "readback_ms", "readback_bytes", "backoff_ms", "backfill_ms",
    "cop_tasks", "engines", "devices", "rows", "termination", "query",
)


class SlowQueryLog:
    def __init__(self, path: Optional[str] = None, capacity: int = 256,
                 max_bytes: int = 0, keep: Optional[int] = None):
        self.path = path
        self._mu = make_lock("trace.slowlog:SlowQueryLog._mu")
        self._ring: deque = deque(maxlen=capacity)
        # size-capped rotation (ISSUE 13): when the active file crosses
        # max_bytes it renames to .1 (shifting .1->.2 .. up to `keep`
        # rotated files, oldest dropped).  0 = unbounded (the old
        # behavior); the domain refreshes max_bytes from the
        # tidb_tpu_slow_log_max_bytes global on every record.
        self.max_bytes = int(max_bytes)
        self.keep = max(int(keep if keep is not None else os.environ.get(
            "TIDB_TPU_SLOW_LOG_KEEP", "3")), 1)
        # append + rotate are one unit
        self._io_mu = make_lock("trace.slowlog:SlowQueryLog._io_mu")
        self._size = 0
        if path is not None:
            self._recover()

    # ---- write path ----------------------------------------------------
    def record(self, entry: dict):
        """Append one entry; ring first (the memtable's source of truth
        for this process), then best-effort durable append.  A writer
        killed mid-record must neither corrupt the table nor leak a
        handle — the failpoint models the kill between partial writes."""
        with self._mu:
            self._ring.append(dict(entry))
        if self.path is None:
            return
        from ..metrics import REGISTRY

        line = json.dumps(entry, sort_keys=True, default=str)
        with self._io_mu:
            try:
                with open(self.path, "a", encoding="utf-8") as f:
                    # torn-write window: the chaos harness kills the
                    # writer here, leaving a prefix of the record on disk
                    f.write(line[: len(line) // 2])
                    FAILPOINTS.hit("trace/slow_log_write", entry=entry)
                    f.write(line[len(line) // 2:] + "\n")
                # size is tracked in BYTES (recovery counts bytes too;
                # non-ASCII SQL makes len(str) undercount)
                self._size += len(line.encode("utf-8")) + 1
            except Exception:
                # advisory log: a failed append never fails the
                # statement.  Resync the stream: terminate whatever
                # partial bytes landed so the NEXT (successful) record
                # never merges into the torn one and get lost with it at
                # recovery time.
                REGISTRY.inc("slow_log_write_errors_total")
                try:
                    with open(self.path, "a", encoding="utf-8") as f:
                        f.write("\n")
                    # the torn prefix landed too: resync from the file
                    self._size = os.path.getsize(self.path)
                except Exception:
                    pass
            if self.max_bytes > 0 and self._size > self.max_bytes:
                self._rotate_locked()

    def _rotate_locked(self):
        """Rotate the active file into `.1` (shifting `.1`->`.2` ... up
        to `keep`, oldest dropped).  Every move is an atomic rename, so
        a crash mid-rotation never tears a record: the active file is
        either pre- or post-rename, and torn-tail recovery continues to
        apply to whichever file is active on restart."""
        from ..metrics import REGISTRY

        try:
            for i in range(self.keep - 1, 0, -1):
                src = f"{self.path}.{i}"
                if os.path.exists(src):
                    os.replace(src, f"{self.path}.{i + 1}")
            os.replace(self.path, f"{self.path}.1")
            self._size = 0
            REGISTRY.inc("slow_log_rotations_total")
        except OSError:
            # rotation is best-effort: a failed rename keeps appending
            # to the (oversized) active file rather than losing records
            REGISTRY.inc("slow_log_rotation_errors_total")

    # ---- read / recovery ----------------------------------------------
    def entries(self) -> List[dict]:
        with self._mu:
            return list(self._ring)

    def rows(self) -> List[tuple]:
        """Entries in SLOW_QUERY column order, oldest first."""
        out = []
        for e in self.entries():
            out.append(tuple(e.get(k) for k in ENTRY_FIELDS))
        return out

    def _recover(self):
        """Load persisted entries, tolerating a torn final record (the
        delta-log torn-tail pattern): the tail line is dropped and
        counted; earlier undecodable lines are dropped and counted under
        their own metric (advisory data, never fatal)."""
        from ..metrics import REGISTRY

        try:
            with open(self.path, "rb") as f:
                raw = f.read()
        except OSError:
            return
        if not raw:
            return
        # _size is the append path's byte counter (guarded by _io_mu):
        # recovery runs at construction but a shared-path second log
        # could already be appending, so take the same lock
        with self._io_mu:
            self._size = len(raw)
        lines = raw.split(b"\n")
        torn = lines[-1] != b""  # no trailing newline: torn final record
        body, tail = (lines[:-1], lines[-1]) if torn else (lines[:-1], None)
        if torn and tail:
            REGISTRY.inc("slow_log_torn_tail_total")
            # TRUNCATE the torn bytes from disk (the delta-log recovery
            # contract, store/persist torn-tail handling): leaving them
            # would merge the first post-restart append into the torn
            # record and lose it at the next recovery
            try:
                with open(self.path, "r+b") as f:
                    f.truncate(len(raw) - len(tail))
                with self._io_mu:
                    self._size = len(raw) - len(tail)
            except OSError:
                pass
        with self._mu:
            for i, ln in enumerate(body):
                if not ln:
                    continue
                try:
                    self._ring.append(json.loads(ln.decode("utf-8",
                                                           "replace")))
                except ValueError:
                    if i == len(body) - 1:
                        # a torn record terminated by a resync newline
                        REGISTRY.inc("slow_log_torn_tail_total")
                    else:
                        REGISTRY.inc("slow_log_corrupt_records_total")
