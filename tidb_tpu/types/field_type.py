"""Scalar type system.

Reference: /root/reference/types (FieldType, Datum, mydecimal.go, time.go).
Design departure for TPU: every kind has a fixed-width physical representation
so columns are dense numpy/jax arrays with separate validity bitmaps:

- INT / UINT      -> int64 (uint stored in int64, flag distinguishes)
- FLOAT           -> float64 on host, float32/bfloat16 on device where safe
- DECIMAL(p, s)   -> scaled int64 (value * 10^s); MySQL's mydecimal replaced by
                     fixed-point arithmetic which XLA handles natively
- STRING          -> host: numpy object array; device: int32 dictionary codes
- DATE            -> int32 days since epoch
- DATETIME        -> int64 microseconds since epoch
- BOOL            -> int64 0/1 (MySQL booleans are TINYINT)
- NULLTYPE        -> type of bare NULL literal
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field, replace

import numpy as np


class TypeKind(enum.IntEnum):
    NULLTYPE = 0
    INT = 1
    UINT = 2
    FLOAT = 3
    DECIMAL = 4
    STRING = 5
    DATE = 6
    DATETIME = 7
    BOOL = 8
    # round-4 surface types (reference: types/time.go Duration, ENUM/SET in
    # types/etc.go, BIT in types/binary_literal.go, JSON in types/json/)
    TIME = 9      # int64 signed microseconds (MySQL TIME, range +-838:59:59)
    ENUM = 10     # int64 1-based member index (FieldType.elems holds members)
    SET = 11      # int64 bitmask over FieldType.elems (max 64 members)
    BIT = 12      # int64 holding up to 64 bits
    JSON = 13     # host object array of compact-serialized JSON strings

    @property
    def is_numeric(self) -> bool:
        return self in (
            TypeKind.INT,
            TypeKind.UINT,
            TypeKind.FLOAT,
            TypeKind.DECIMAL,
            TypeKind.BOOL,
            TypeKind.BIT,
        )

    @property
    def is_temporal(self) -> bool:
        return self in (TypeKind.DATE, TypeKind.DATETIME, TypeKind.TIME)


# numpy physical dtype per kind (host representation).
_NP_DTYPE = {
    TypeKind.NULLTYPE: np.int64,
    TypeKind.INT: np.int64,
    TypeKind.UINT: np.int64,
    TypeKind.FLOAT: np.float64,
    TypeKind.DECIMAL: np.int64,
    TypeKind.STRING: object,
    TypeKind.DATE: np.int32,
    TypeKind.DATETIME: np.int64,
    TypeKind.BOOL: np.int64,
    TypeKind.TIME: np.int64,
    TypeKind.ENUM: np.int64,
    TypeKind.SET: np.int64,
    TypeKind.BIT: np.int64,
    TypeKind.JSON: object,
}

# widest decimal precision whose scaled value always fits int64 (2^63 ~
# 9.2e18): the device fast path.  Past this the host computes with exact
# Python ints in object arrays (mydecimal.go's 65-digit range, minus the
# 9-digit-limb machinery XLA has no use for).
DECIMAL_INT64_DIGITS = 18
MAX_DECIMAL_PRECISION = 65  # types/mydecimal.go notDefinedPrecision bound


@dataclass(frozen=True)
class FieldType:
    kind: TypeKind
    nullable: bool = True
    # decimal: precision/scale.  scale is also used by DATETIME for fsp (unused
    # in arithmetic; micros are always stored) and by BIT for declared width.
    precision: int = 0
    scale: int = 0
    # ENUM/SET member names, in definition order (1-based index / bit order)
    elems: tuple = ()

    @property
    def np_dtype(self):
        if self.kind == TypeKind.DECIMAL and self.is_wide_decimal:
            return object
        return _NP_DTYPE[self.kind]

    @property
    def is_wide_decimal(self) -> bool:
        """True when scaled values may exceed int64 — exact host path."""
        return (self.kind == TypeKind.DECIMAL
                and self.precision > DECIMAL_INT64_DIGITS)

    @property
    def is_numeric(self) -> bool:
        return self.kind.is_numeric

    @property
    def is_string(self) -> bool:
        return self.kind == TypeKind.STRING

    def not_null(self) -> "FieldType":
        return replace(self, nullable=False)

    def with_nullable(self, nullable: bool) -> "FieldType":
        return replace(self, nullable=nullable)

    def sql_name(self) -> str:
        k = self.kind
        if k == TypeKind.DECIMAL:
            return f"DECIMAL({self.precision},{self.scale})"
        if k == TypeKind.ENUM:
            return "ENUM(" + ",".join(f"'{e}'" for e in self.elems) + ")"
        if k == TypeKind.SET:
            return "SET(" + ",".join(f"'{e}'" for e in self.elems) + ")"
        if k == TypeKind.BIT:
            return f"BIT({self.precision or 1})"
        return {
            TypeKind.NULLTYPE: "NULL",
            TypeKind.INT: "BIGINT",
            TypeKind.UINT: "BIGINT UNSIGNED",
            TypeKind.FLOAT: "DOUBLE",
            TypeKind.STRING: "VARCHAR",
            TypeKind.DATE: "DATE",
            TypeKind.DATETIME: "DATETIME",
            TypeKind.BOOL: "TINYINT",
            TypeKind.TIME: "TIME",
            TypeKind.JSON: "JSON",
        }[k]

    def __repr__(self):  # compact for plan dumps
        s = self.sql_name()
        if not self.nullable:
            s += " NOT NULL"
        return s


def ty_null() -> FieldType:
    return FieldType(TypeKind.NULLTYPE)


def ty_bool(nullable: bool = True) -> FieldType:
    return FieldType(TypeKind.BOOL, nullable)


def ty_int(nullable: bool = True) -> FieldType:
    return FieldType(TypeKind.INT, nullable)


def ty_uint(nullable: bool = True) -> FieldType:
    return FieldType(TypeKind.UINT, nullable)


def ty_float(nullable: bool = True) -> FieldType:
    return FieldType(TypeKind.FLOAT, nullable)


def ty_decimal(precision: int = 18, scale: int = 2, nullable: bool = True) -> FieldType:
    return FieldType(TypeKind.DECIMAL, nullable, precision, scale)


def ty_string(nullable: bool = True) -> FieldType:
    return FieldType(TypeKind.STRING, nullable)


def ty_date(nullable: bool = True) -> FieldType:
    return FieldType(TypeKind.DATE, nullable)


def ty_datetime(nullable: bool = True) -> FieldType:
    return FieldType(TypeKind.DATETIME, nullable)


def ty_time(nullable: bool = True) -> FieldType:
    return FieldType(TypeKind.TIME, nullable)


def ty_enum(elems, nullable: bool = True) -> FieldType:
    return FieldType(TypeKind.ENUM, nullable, elems=tuple(elems))


def ty_set(elems, nullable: bool = True) -> FieldType:
    return FieldType(TypeKind.SET, nullable, elems=tuple(elems))


def ty_bit(width: int = 1, nullable: bool = True) -> FieldType:
    return FieldType(TypeKind.BIT, nullable, precision=width)


def ty_json(nullable: bool = True) -> FieldType:
    return FieldType(TypeKind.JSON, nullable)


def merge_types(a: FieldType, b: FieldType) -> FieldType:
    """Result type when values of both types flow into one column (UNION /
    CASE / COALESCE).  MySQL-ish widening lattice."""
    if a.kind == TypeKind.NULLTYPE:
        return b.with_nullable(True)
    if b.kind == TypeKind.NULLTYPE:
        return a.with_nullable(True)
    nullable = a.nullable or b.nullable
    if a.kind == b.kind:
        if a.kind == TypeKind.DECIMAL:
            scale = max(a.scale, b.scale)
            prec = max(a.precision - a.scale, b.precision - b.scale) + scale
            return ty_decimal(min(prec, MAX_DECIMAL_PRECISION), scale,
                              nullable)
        if a.kind in (TypeKind.ENUM, TypeKind.SET) and a.elems != b.elems:
            return ty_string(nullable)  # different member sets: text
        return a.with_nullable(nullable)
    # ENUM/SET/JSON mixed with anything else merge as text (MySQL casts
    # the member name / JSON text, never the index/bitmask)
    if TypeKind.ENUM in (a.kind, b.kind) or TypeKind.SET in (a.kind, b.kind) \
            or TypeKind.JSON in (a.kind, b.kind):
        return ty_string(nullable)
    ka, kb = a.kind, b.kind
    ints = (TypeKind.INT, TypeKind.UINT, TypeKind.BOOL)
    if ka in ints and kb in ints:
        return ty_int(nullable)
    if TypeKind.FLOAT in (ka, kb) or TypeKind.STRING in (ka, kb):
        if TypeKind.STRING in (ka, kb) and not (ka.is_numeric and kb.is_numeric):
            # string vs temporal/string mix -> string
            if ka == TypeKind.STRING and kb == TypeKind.STRING:
                return ty_string(nullable)
            if ka.is_temporal or kb.is_temporal:
                return ty_string(nullable)
        return ty_float(nullable)
    if TypeKind.DECIMAL in (ka, kb):
        dec = a if ka == TypeKind.DECIMAL else b
        if ka in ints or kb in ints:
            return ty_decimal(max(dec.precision, 20), dec.scale, nullable)
        return ty_float(nullable)
    if ka.is_temporal and kb.is_temporal:
        return ty_datetime(nullable)
    return ty_string(nullable)


def common_arith_type(a: FieldType, b: FieldType) -> FieldType:
    """Type in which binary arithmetic (+,-,*) is carried out.

    Reference behavior (types/field_type.go AggFieldType + expression type
    inference): int op int -> int; anything with float/string -> float
    (strings coerce to float in arithmetic); decimal op {int,decimal} ->
    decimal with combined scale.
    """
    ka, kb = a.kind, b.kind
    nullable = a.nullable or b.nullable
    if ka == TypeKind.NULLTYPE or kb == TypeKind.NULLTYPE:
        nullable = True
    ints = (TypeKind.INT, TypeKind.UINT, TypeKind.BOOL, TypeKind.NULLTYPE)
    if (ka in (TypeKind.FLOAT, TypeKind.STRING) or kb in (TypeKind.FLOAT, TypeKind.STRING)
            or ka.is_temporal or kb.is_temporal):
        return ty_float(nullable)
    if ka == TypeKind.DECIMAL or kb == TypeKind.DECIMAL:
        sa = a.scale if ka == TypeKind.DECIMAL else 0
        sb = b.scale if kb == TypeKind.DECIMAL else 0
        return ty_decimal(38, max(sa, sb), nullable)
    if ka in ints and kb in ints:
        if TypeKind.UINT in (ka, kb):
            return ty_uint(nullable)
        return ty_int(nullable)
    return ty_float(nullable)


def common_compare_type(a: FieldType, b: FieldType) -> FieldType:
    """Type in which a comparison is evaluated (both sides cast to it)."""
    ka, kb = a.kind, b.kind
    if ka == kb:
        return a.with_nullable(True)
    if ka == TypeKind.NULLTYPE:
        return b
    if kb == TypeKind.NULLTYPE:
        return a
    # ENUM/SET against a string literal compare in the member domain (the
    # constant is translated to an index/bitmask at build time)
    if ka in (TypeKind.ENUM, TypeKind.SET) and kb == TypeKind.STRING:
        return a
    if kb in (TypeKind.ENUM, TypeKind.SET) and ka == TypeKind.STRING:
        return b
    if TypeKind.JSON in (ka, kb):
        return ty_string()
    if ka.is_temporal and kb == TypeKind.STRING:
        return a
    if kb.is_temporal and ka == TypeKind.STRING:
        return b
    # DECIMAL vs string literal: compare in the decimal domain (exact —
    # a float64 detour collapses distinct wide values; see
    # builtins._compare_arrays' exact string-side parse)
    if ka == TypeKind.DECIMAL and kb == TypeKind.STRING:
        return a.with_nullable(True)
    if kb == TypeKind.DECIMAL and ka == TypeKind.STRING:
        return b.with_nullable(True)
    if ka == TypeKind.STRING and kb == TypeKind.STRING:
        return ty_string()
    return common_arith_type(a, b)
