"""Scalar value helpers: NULL sentinel, temporal codecs, decimal rounding.

Reference: /root/reference/types/time.go, mytime.go, mydecimal.go.  We store
DATE as int32 days since 1970-01-01 and DATETIME as int64 microseconds since
epoch; MySQL-visible formatting happens only at the result boundary.
"""

from __future__ import annotations

import datetime as _dt

import numpy as np

# Python-side NULL sentinel used in literal/Datum positions.  Columns carry
# nulls in validity bitmaps, never as sentinel values in data arrays.
NULL = None

_EPOCH = _dt.date(1970, 1, 1)


def date_to_days(d: _dt.date) -> int:
    return (d - _EPOCH).days


def days_to_date(days: int) -> _dt.date:
    return _EPOCH + _dt.timedelta(days=int(days))


def datetime_to_micros(dt: _dt.datetime) -> int:
    delta = dt - _dt.datetime(1970, 1, 1)
    return delta.days * 86_400_000_000 + delta.seconds * 1_000_000 + delta.microseconds


def micros_to_datetime(us: int) -> _dt.datetime:
    return _dt.datetime(1970, 1, 1) + _dt.timedelta(microseconds=int(us))


def parse_date(s: str) -> int:
    """'1998-09-02' -> days since epoch. MySQL also accepts 19980902 etc.;
    we support the ISO forms used by TPC-H/SSB plus compact digits."""
    s = s.strip()
    if "-" in s:
        y, m, d = s.split("-")[:3]
        return date_to_days(_dt.date(int(y), int(m), int(d[:2])))
    if len(s) == 8 and s.isdigit():
        return date_to_days(_dt.date(int(s[:4]), int(s[4:6]), int(s[6:8])))
    raise ValueError(f"bad DATE literal {s!r}")


def parse_datetime(s: str) -> int:
    s = s.strip().replace("T", " ")
    if " " in s:
        d, t = s.split(" ", 1)
        days = parse_date(d)
        parts = t.split(":")
        h = int(parts[0]) if parts else 0
        mi = int(parts[1]) if len(parts) > 1 else 0
        sec = float(parts[2]) if len(parts) > 2 else 0.0
        return (
            days * 86_400_000_000
            + h * 3_600_000_000
            + mi * 60_000_000
            + int(round(sec * 1_000_000))
        )
    return parse_date(s) * 86_400_000_000


def format_date(days: int) -> str:
    return days_to_date(days).isoformat()


def format_datetime(us: int) -> str:
    dt = micros_to_datetime(us)
    if dt.microsecond:
        return dt.strftime("%Y-%m-%d %H:%M:%S.%f")
    return dt.strftime("%Y-%m-%d %H:%M:%S")


def decimal_round_half_up(x: np.ndarray | int, ndigits_drop: int):
    """Round scaled-int decimals by dropping `ndigits_drop` decimal digits
    with MySQL's round-half-away-from-zero semantics.

    e.g. value 12345 at scale 3 -> scale 1: decimal_round_half_up(12345, 2)
    == 123 (12.345 -> 12.3); 12355 -> 124 (12.355 -> 12.4 -> wait: 12.36?).
    Half-up on the dropped part: sign(x) * ((|x| + 5*10^(d-1)) // 10^d).
    Works on int64 AND object (exact Python int) arrays — np.sign has no
    object loop, so the sign comes from comparisons there.
    """
    if ndigits_drop <= 0:
        return x
    p = 10 ** ndigits_drop
    half = p // 2
    if isinstance(x, np.ndarray):
        if x.dtype == object:
            neg = np.array([v < 0 for v in x], dtype=np.bool_)
            mag = np.array([(abs(int(v)) + half) // p for v in x],
                           dtype=object)
            return np.where(neg, -mag, mag)
        sign = np.sign(x)
        return sign * ((np.abs(x) + half) // p)
    sign = -1 if x < 0 else 1
    return sign * ((abs(x) + half) // p)


def parse_decimal_exact(s: str, scale: int) -> int:
    """Decimal literal -> exact scaled Python int at `scale` (no float
    round-trip — mydecimal.go FromString's exactness contract), MySQL
    half-away-from-zero rounding of excess fractional digits."""
    s = str(s).strip()
    neg = s.startswith("-")
    if s and s[0] in "+-":
        s = s[1:]
    if "e" in s or "E" in s:
        # scientific notation: exact via Decimal-free integer math
        mant, _, exp = s.replace("E", "e").partition("e")
        exp = int(exp or 0)
        intp, _, frac = mant.partition(".")
        digits = (intp + frac) or "0"
        eff_scale = len(frac) - exp
        v = int(digits or "0")
    else:
        intp, _, frac = s.partition(".")
        v = int((intp or "0") + frac or "0")
        eff_scale = len(frac)
    if eff_scale < scale:
        v *= 10 ** (scale - eff_scale)
    elif eff_scale > scale:
        v = decimal_round_half_up(v, eff_scale - scale)
    return -v if neg else v


def format_decimal(v: int, scale: int) -> str:
    """Scaled int -> MySQL decimal string ('-12.30' keeps trailing zeros)."""
    v = int(v)
    sign = "-" if v < 0 else ""
    a = abs(v)
    if scale <= 0:
        return f"{sign}{a}"
    return f"{sign}{a // 10**scale}.{a % 10**scale:0{scale}d}"


# ---------------------------------------------------------------------------
# TIME (MySQL Duration): int64 signed microseconds, range +-838:59:59
# ---------------------------------------------------------------------------

MAX_TIME_US = (838 * 3600 + 59 * 60 + 59) * 1_000_000


def parse_time(s: str) -> int:
    """'[-]HH:MM:SS[.frac]' / '[-]HHMMSS' / '[-]D HH:MM:SS' -> signed us,
    clamped to the MySQL TIME range (types/time.go Duration parsing)."""
    s = str(s).strip()
    neg = s.startswith("-")
    if neg:
        s = s[1:]
    days = 0
    if " " in s:
        d, s = s.split(" ", 1)
        days = int(d)
    if ":" in s:
        parts = s.split(":")
        h = int(parts[0]) if parts[0] else 0
        mi = int(parts[1]) if len(parts) > 1 and parts[1] else 0
        sec = float(parts[2]) if len(parts) > 2 and parts[2] else 0.0
    else:
        # compact HHMMSS (MySQL numeric time)
        body, _, frac = s.partition(".")
        x = int(body or "0")
        h, mi, sec = x // 10000, (x // 100) % 100, float(x % 100)
        if frac:
            sec += float("0." + frac)
    us = ((days * 24 + h) * 3600 + mi * 60) * 1_000_000 + int(
        round(sec * 1_000_000))
    us = min(us, MAX_TIME_US)
    return -us if neg else us


def format_time(us: int) -> str:
    us = int(us)
    sign = "-" if us < 0 else ""
    a = abs(us)
    h, rem = divmod(a, 3_600_000_000)
    mi, rem = divmod(rem, 60_000_000)
    sec, frac = divmod(rem, 1_000_000)
    if frac:
        return f"{sign}{h:02d}:{mi:02d}:{sec:02d}.{frac:06d}"
    return f"{sign}{h:02d}:{mi:02d}:{sec:02d}"


def scale_factor(scale: int) -> int:
    return 10 ** scale
