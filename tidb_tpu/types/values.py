"""Scalar value helpers: NULL sentinel, temporal codecs, decimal rounding.

Reference: /root/reference/types/time.go, mytime.go, mydecimal.go.  We store
DATE as int32 days since 1970-01-01 and DATETIME as int64 microseconds since
epoch; MySQL-visible formatting happens only at the result boundary.
"""

from __future__ import annotations

import datetime as _dt

import numpy as np

# Python-side NULL sentinel used in literal/Datum positions.  Columns carry
# nulls in validity bitmaps, never as sentinel values in data arrays.
NULL = None

_EPOCH = _dt.date(1970, 1, 1)


def date_to_days(d: _dt.date) -> int:
    return (d - _EPOCH).days


def days_to_date(days: int) -> _dt.date:
    return _EPOCH + _dt.timedelta(days=int(days))


def datetime_to_micros(dt: _dt.datetime) -> int:
    delta = dt - _dt.datetime(1970, 1, 1)
    return delta.days * 86_400_000_000 + delta.seconds * 1_000_000 + delta.microseconds


def micros_to_datetime(us: int) -> _dt.datetime:
    return _dt.datetime(1970, 1, 1) + _dt.timedelta(microseconds=int(us))


def parse_date(s: str) -> int:
    """'1998-09-02' -> days since epoch. MySQL also accepts 19980902 etc.;
    we support the ISO forms used by TPC-H/SSB plus compact digits."""
    s = s.strip()
    if "-" in s:
        y, m, d = s.split("-")[:3]
        return date_to_days(_dt.date(int(y), int(m), int(d[:2])))
    if len(s) == 8 and s.isdigit():
        return date_to_days(_dt.date(int(s[:4]), int(s[4:6]), int(s[6:8])))
    raise ValueError(f"bad DATE literal {s!r}")


def parse_datetime(s: str) -> int:
    s = s.strip().replace("T", " ")
    if " " in s:
        d, t = s.split(" ", 1)
        days = parse_date(d)
        parts = t.split(":")
        h = int(parts[0]) if parts else 0
        mi = int(parts[1]) if len(parts) > 1 else 0
        sec = float(parts[2]) if len(parts) > 2 else 0.0
        return (
            days * 86_400_000_000
            + h * 3_600_000_000
            + mi * 60_000_000
            + int(round(sec * 1_000_000))
        )
    return parse_date(s) * 86_400_000_000


def format_date(days: int) -> str:
    return days_to_date(days).isoformat()


def format_datetime(us: int) -> str:
    dt = micros_to_datetime(us)
    if dt.microsecond:
        return dt.strftime("%Y-%m-%d %H:%M:%S.%f")
    return dt.strftime("%Y-%m-%d %H:%M:%S")


def decimal_round_half_up(x: np.ndarray | int, ndigits_drop: int):
    """Round scaled-int decimals by dropping `ndigits_drop` decimal digits
    with MySQL's round-half-away-from-zero semantics.

    e.g. value 12345 at scale 3 -> scale 1: decimal_round_half_up(12345, 2)
    == 123 (12.345 -> 12.3); 12355 -> 124 (12.355 -> 12.4 -> wait: 12.36?).
    Half-up on the dropped part: sign(x) * ((|x| + 5*10^(d-1)) // 10^d).
    """
    if ndigits_drop <= 0:
        return x
    p = 10 ** ndigits_drop
    half = p // 2
    if isinstance(x, np.ndarray):
        sign = np.sign(x)
        return sign * ((np.abs(x) + half) // p)
    sign = -1 if x < 0 else 1
    return sign * ((abs(x) + half) // p)


def scale_factor(scale: int) -> int:
    return 10 ** scale
