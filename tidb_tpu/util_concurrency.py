"""Ranked locks + the opt-in lock-order witness (ISSUE 16).

Every lock in the tree is constructed through `make_lock` / `make_rlock`
with its registry name (`module:Owner.attr`, the key into
`lint.concur.LOCK_RANKS`).  With `TIDB_TPU_LOCKCHECK` unset (the
default, read once at construction) the factories return plain
`threading.Lock` / `threading.RLock` objects — zero overhead, zero
indirection on the hot path.  With `TIDB_TPU_LOCKCHECK=1` (the tier-1
conftest sets it) they return a `RankedLock` wrapper that keeps a
per-thread stack of held locks and raises `LockOrderError` on any
acquisition that does not strictly increase the declared rank — the
runtime half of the concurrency lint: the static pass
(`lint/concur.py`) covers paths tests never take, the witness validates
the declared order against real executions.

Re-entry is permitted only for the SAME RLock object (rank equality
against a different lock is still an error: two locks sharing a rank
may not nest).  Witness bookkeeping (total guarded acquisitions, max
held depth, violations) feeds `/status`'s "lockcheck" section and the
`lockcheck` bench receipt.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional


class LockOrderError(RuntimeError):
    """A lock acquisition inverted the declared rank order (or used an
    unregistered name).  Raised by the witness at the faulty
    acquisition site — the stack trace IS the repro."""


def lockcheck_enabled() -> bool:
    """Witness mode, read at each construction site (module-import
    time for globals — set the env var before importing tidb_tpu)."""
    return os.environ.get("TIDB_TPU_LOCKCHECK", "0") not in ("", "0")


# per-thread stack of currently-held RankedLocks (witness mode only)
_held = threading.local()

# witness counters; guarded by a plain internal lock that is itself
# never held while acquiring a ranked lock (leaf by construction)
_stats_mu = threading.Lock()
_STATS = {"acquisitions": 0, "max_depth": 0, "violations": 0}


def _ranks() -> Dict[str, int]:
    # lazy: lint.concur imports nothing heavy, but keeping the import
    # here lets plain (non-witness) processes never load the lint pkg
    from .lint.concur import LOCK_RANKS

    return LOCK_RANKS


def _stack():
    s = getattr(_held, "stack", None)
    if s is None:
        s = _held.stack = []
    return s


class RankedLock:
    """Witness wrapper: a named, ranked lock enforcing that every
    thread acquires locks in strictly increasing rank order."""

    __slots__ = ("name", "rank", "reentrant", "_lock")

    def __init__(self, name: str, lock, reentrant: bool):
        ranks = _ranks()
        if name not in ranks:
            raise LockOrderError(
                f"lock {name!r} is not in lint.concur.LOCK_RANKS — "
                f"declare its rank before constructing it")
        self.name = name
        self.rank = ranks[name]
        self.reentrant = reentrant
        self._lock = lock

    # ---- witness core ---------------------------------------------------
    def _check(self):
        stack = _stack()
        if stack:
            top = stack[-1]
            if top is self or (self.reentrant
                               and any(h is self for h in stack)):
                return  # same-object RLock re-entry
            if top.rank >= self.rank:
                with _stats_mu:
                    _STATS["violations"] += 1
                held = " -> ".join(f"{h.name}({h.rank})" for h in stack)
                raise LockOrderError(
                    f"lock-order violation: acquiring {self.name!r} "
                    f"(rank {self.rank}) while holding [{held}] — "
                    f"ranks must strictly increase")

    def _push(self):
        stack = _stack()
        stack.append(self)
        with _stats_mu:
            _STATS["acquisitions"] += 1
            if len(stack) > _STATS["max_depth"]:
                _STATS["max_depth"] = len(stack)

    def _pop(self):
        stack = _stack()
        # LIFO in practice (`with` blocks); tolerate out-of-order
        # release by removing the last matching entry by identity
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                return

    # ---- threading.Lock surface ----------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        self._check()
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            self._push()
        return ok

    def release(self):
        self._pop()
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._lock.locked()

    def __repr__(self):  # pragma: no cover — diagnostics only
        return f"<RankedLock {self.name} rank={self.rank}>"


def make_lock(name: str):
    """A `threading.Lock` registered under `name` (witness-wrapped when
    `TIDB_TPU_LOCKCHECK=1`).  `name` must literal-match the site:
    `module:Owner.attr` for instance locks, `module:GLOBAL` for module
    globals — the static pass cross-checks the literal against the
    construction site."""
    lock = threading.Lock()
    if not lockcheck_enabled():
        return lock
    return RankedLock(name, lock, reentrant=False)


def make_rlock(name: str):
    """`make_lock` for re-entrant locks: same-object re-entry is legal,
    everything else follows the rank order."""
    lock = threading.RLock()
    if not lockcheck_enabled():
        return lock
    return RankedLock(name, lock, reentrant=True)


def witness_stats() -> dict:
    """Witness counters for /status ("lockcheck") and the bench
    receipt.  All zeros (enabled=False) when the witness is off."""
    with _stats_mu:
        snap = dict(_STATS)
    snap["enabled"] = lockcheck_enabled()
    return snap


def reset_witness_stats():
    with _stats_mu:
        for k in _STATS:
            _STATS[k] = 0


def held_depth() -> int:
    """Current thread's held-lock depth (0 when the witness is off)."""
    return len(getattr(_held, "stack", ()))
