"""Ranked locks + the opt-in lock-order witness (ISSUE 16).

Every lock in the tree is constructed through `make_lock` / `make_rlock`
with its registry name (`module:Owner.attr`, the key into
`lint.concur.LOCK_RANKS`).  With `TIDB_TPU_LOCKCHECK` unset (the
default, read once at construction) the factories return plain
`threading.Lock` / `threading.RLock` objects — zero overhead, zero
indirection on the hot path.  With `TIDB_TPU_LOCKCHECK=1` (the tier-1
conftest sets it) they return a `RankedLock` wrapper that keeps a
per-thread stack of held locks and raises `LockOrderError` on any
acquisition that does not strictly increase the declared rank — the
runtime half of the concurrency lint: the static pass
(`lint/concur.py`) covers paths tests never take, the witness validates
the declared order against real executions.

Re-entry is permitted only for the SAME RLock object (rank equality
against a different lock is still an error: two locks sharing a rank
may not nest).  Witness bookkeeping (total guarded acquisitions, max
held depth, violations) feeds `/status`'s "lockcheck" section and the
`lockcheck` bench receipt.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional


class LockOrderError(RuntimeError):
    """A lock acquisition inverted the declared rank order (or used an
    unregistered name).  Raised by the witness at the faulty
    acquisition site — the stack trace IS the repro."""


def lockcheck_enabled() -> bool:
    """Witness mode, read at each construction site (module-import
    time for globals — set the env var before importing tidb_tpu)."""
    return os.environ.get("TIDB_TPU_LOCKCHECK", "0") not in ("", "0")


# per-thread stack of currently-held RankedLocks (witness mode only)
_held = threading.local()

# witness counters; guarded by a plain internal lock that is itself
# never held while acquiring a ranked lock (leaf by construction)
# "wait_trips" (held-lock waits, concurrency (a)) is deliberately a
# separate key from "violations": the conftest fixture fails any test
# that bumps violations, while wait trips have their own negative test.
_stats_mu = threading.Lock()
_STATS = {"acquisitions": 0, "max_depth": 0, "violations": 0,
          "wait_trips": 0}

# per-lock acquire contention (concurrency (c)): name -> [contended
# acquisitions, total wait ms, log2 wait-ms bucket counts].  Buckets are
# exponent-indexed at 2^(i-1)..2^i ms; index 0 holds sub-1ms waits.
_CONTENTION_BUCKETS = 16
_CONTENTION: Dict[str, list] = {}


def _record_contention(name: str, wait_ms: float):
    b = 0
    ms = wait_ms
    while ms >= 1.0 and b < _CONTENTION_BUCKETS - 1:
        ms /= 2.0
        b += 1
    with _stats_mu:
        rec = _CONTENTION.get(name)
        if rec is None:
            rec = _CONTENTION[name] = [0, 0.0, [0] * _CONTENTION_BUCKETS]
        rec[0] += 1
        rec[1] += wait_ms
        rec[2][b] += 1


def _ranks() -> Dict[str, int]:
    # lazy: lint.concur imports nothing heavy, but keeping the import
    # here lets plain (non-witness) processes never load the lint pkg
    from .lint.concur import LOCK_RANKS

    return LOCK_RANKS


def _stack():
    s = getattr(_held, "stack", None)
    if s is None:
        s = _held.stack = []
    return s


class RankedLock:
    """Witness wrapper: a named, ranked lock enforcing that every
    thread acquires locks in strictly increasing rank order."""

    __slots__ = ("name", "rank", "reentrant", "_lock")

    def __init__(self, name: str, lock, reentrant: bool):
        ranks = _ranks()
        if name not in ranks:
            raise LockOrderError(
                f"lock {name!r} is not in lint.concur.LOCK_RANKS — "
                f"declare its rank before constructing it")
        self.name = name
        self.rank = ranks[name]
        self.reentrant = reentrant
        self._lock = lock

    # ---- witness core ---------------------------------------------------
    def _check(self):
        stack = _stack()
        if stack:
            top = stack[-1]
            if top is self or (self.reentrant
                               and any(h is self for h in stack)):
                return  # same-object RLock re-entry
            if top.rank >= self.rank:
                with _stats_mu:
                    _STATS["violations"] += 1
                held = " -> ".join(f"{h.name}({h.rank})" for h in stack)
                raise LockOrderError(
                    f"lock-order violation: acquiring {self.name!r} "
                    f"(rank {self.rank}) while holding [{held}] — "
                    f"ranks must strictly increase")

    def _push(self):
        stack = _stack()
        stack.append(self)
        with _stats_mu:
            _STATS["acquisitions"] += 1
            if len(stack) > _STATS["max_depth"]:
                _STATS["max_depth"] = len(stack)

    def _pop(self):
        stack = _stack()
        # LIFO in practice (`with` blocks); tolerate out-of-order
        # release by removing the last matching entry by identity
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                return

    # ---- threading.Lock surface ----------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        self._check()
        # contention probe: an uncontended acquire stays a single
        # non-blocking call; only a contended one pays for two clock
        # reads, and only that wait lands in the per-lock histogram
        ok = self._lock.acquire(False)
        if not ok and blocking:
            t0 = time.monotonic()
            ok = self._lock.acquire(True, timeout)
            _record_contention(self.name,
                               (time.monotonic() - t0) * 1000.0)
        if ok:
            self._push()
        return ok

    def release(self):
        self._pop()
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._lock.locked()

    def __repr__(self):  # pragma: no cover — diagnostics only
        return f"<RankedLock {self.name} rank={self.rank}>"


def make_lock(name: str):
    """A `threading.Lock` registered under `name` (witness-wrapped when
    `TIDB_TPU_LOCKCHECK=1`).  `name` must literal-match the site:
    `module:Owner.attr` for instance locks, `module:GLOBAL` for module
    globals — the static pass cross-checks the literal against the
    construction site."""
    lock = threading.Lock()
    if not lockcheck_enabled():
        return lock
    return RankedLock(name, lock, reentrant=False)


def make_rlock(name: str):
    """`make_lock` for re-entrant locks: same-object re-entry is legal,
    everything else follows the rank order."""
    lock = threading.RLock()
    if not lockcheck_enabled():
        return lock
    return RankedLock(name, lock, reentrant=True)


def witness_stats() -> dict:
    """Witness counters for /status ("lockcheck") and the bench
    receipt.  All zeros (enabled=False) when the witness is off.
    "locks" carries the per-lock contention table (concurrency (c)):
    contended acquisitions, summed wait ms and the log2 wait-ms bucket
    counts, keyed by the registered lock name."""
    with _stats_mu:
        snap = dict(_STATS)
        snap["locks"] = {
            name: {"contended": rec[0],
                   "wait_ms": round(rec[1], 3),
                   "wait_ms_log2": list(rec[2])}
            for name, rec in sorted(_CONTENTION.items())
        }
    snap["enabled"] = lockcheck_enabled()
    return snap


def reset_witness_stats():
    with _stats_mu:
        for k in _STATS:
            _STATS[k] = 0
        _CONTENTION.clear()


def held_depth() -> int:
    """Current thread's held-lock depth (0 when the witness is off)."""
    return len(getattr(_held, "stack", ()))


def witness_wait_check(what: str):
    """Witness half of concurrency (a): raise if this thread is about to
    block on a condition/event WAIT while holding a ranked lock.  The
    notifier of that wait must run to wake us; if waking requires any
    lock ranked at or below what we hold, the wait IS a deadlock waiting
    for load — so the witness bans held-lock waits outright (the static
    pass in lint/concur.py applies the rank comparison; at runtime any
    held ranked lock is grounds to trip).  Counted under "wait_trips",
    not "violations", so the negative test doesn't fail itself via the
    conftest violation fixture."""
    stack = getattr(_held, "stack", None)
    if not stack:
        return
    with _stats_mu:
        _STATS["wait_trips"] += 1
    held = " -> ".join(f"{h.name}({h.rank})" for h in stack)
    raise LockOrderError(
        f"held-lock wait: {what} would block while holding [{held}] — "
        f"the notifier cannot be guaranteed to run without acquiring a "
        f"lower-ranked lock; release before waiting")
