"""Memory tracking with OOM actions.

Reference: util/memory/tracker.go:40-174 (Tracker tree attached from
Request.MemTracker down to operators) + action.go:28-100 (ActionOnExceed =
log | cancel | spill).  Cancel surfaces as MemoryQuotaExceededError caught at
the statement boundary (executor/adapter.go:275-284 catches the panic).
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

from .errors import MemoryQuotaExceededError
from .util_concurrency import make_lock


class MemTracker:
    def __init__(self, label: str, quota: int = 0,
                 parent: Optional["MemTracker"] = None,
                 action: str = "cancel"):
        self.label = label
        self.quota = quota  # 0 = unlimited
        self.parent = parent
        self.action = action  # cancel | log
        self._consumed = 0
        self._max = 0
        self._mu = make_lock("util_memory:MemTracker._mu")
        # spill callbacks registered by operators that can shed memory
        self._spill_hooks: List[Callable[[], int]] = []

    @property
    def consumed(self) -> int:
        with self._mu:
            return self._consumed

    @property
    def max_consumed(self) -> int:
        with self._mu:
            return self._max

    def register_spill(self, hook: Callable[[], int]):
        """hook() frees memory and returns bytes released.  Registration
        is locked: parallel operators (hash-join build workers, fan-out
        pipelines) register concurrently, and an unlocked list.append
        racing _on_exceed's snapshot can drop a hook."""
        with self._mu:
            self._spill_hooks.append(hook)

    def consume(self, nbytes: int):
        with self._mu:
            self._consumed += nbytes
            if self._consumed > self._max:
                self._max = self._consumed
            # quota decision on the in-lock snapshot: a racing release
            # must not hide an exceed that was real when we booked it
            over = bool(self.quota and self._consumed > self.quota)
        if self.parent is not None:
            self.parent.consume(nbytes)
            return
        if over:
            self._on_exceed()

    def release(self, nbytes: int):
        self.consume(-nbytes)

    def _on_exceed(self):
        # try spilling first (action.go SpillDiskAction analog)
        with self._mu:
            hooks = list(self._spill_hooks)
        for hook in hooks:
            freed = hook()
            if freed > 0 and self.consumed <= self.quota:
                return
        if self.consumed <= self.quota:
            return
        if self.action == "cancel":
            # mark the statement scope first so sibling fan-out workers
            # stop promptly and the termination reason reads mem_quota
            from .lifecycle import current_scope

            current_scope().cancel("mem_quota")
            raise MemoryQuotaExceededError(self.quota, self.consumed)
        # log action: keep going (the reference logs; we count it)
        from .metrics import REGISTRY

        REGISTRY.inc("mem_quota_exceeded_total")
